"""Threaded stdlib-HTTP front for the continuous-batching engine.

No web framework — ``http.server.ThreadingHTTPServer`` with one handler
thread per connection, all of them funneling into the single engine
thread through the scheduler's bounded queue (the paper's
many-callers-one-controller shape, over HTTP).

Endpoints:

* ``POST /generate`` — body ``{"tokens": [...], "max_new_tokens": N,
  "eos_id": E?, "timeout_ms": T?, "speculative": bool?,
  "temperature": f?, "top_k": K?, "top_p": p?, "seed": s?,
  "priority": "interactive"|"batch"?, "stream": bool?}`` (or
  ``{"text": ...}`` when the
  server was built with an ``encode`` callable).  Replies ``{"tokens":
  [...], "finish_reason": ..., "ttft_ms": ...}`` (+ ``"text"`` with a
  detokenizer).  Typed rejections map to HTTP: queue full -> 429,
  too long -> 413, deadline -> 504, draining / engine failed -> 503,
  bad request -> 400 (including invalid sampling parameters).  When no
  ``timeout_ms`` is sent, the request's
  engine deadline defaults to the server's ``request_timeout`` — every
  admitted request carries a deadline, so a vanished client can never
  pin a slot to ``max_new_tokens``.

  ``temperature``/``top_k``/``top_p``/``seed`` select per-request
  SAMPLING (temperature 0 = greedy, the default; docs/serving.md
  "Sampling + streaming") — one compiled tick serves every mix, and a
  fixed seed reproduces the stream across retries, restarts, and
  failovers.  ``"stream": true`` switches the response to chunked
  Server-Sent Events (``text/event-stream``): one ``token`` event per
  retired token as the engine's overlapped pipeline emits it (one-tick
  lag), then exactly one terminal ``done`` (same payload as the
  non-streamed 200) or ``error`` (same payload as the typed-error
  bodies, resume descriptor included) event — see
  :mod:`horovod_tpu.serving.sse` for the exact frames.  A client that
  disconnects mid-stream CANCELS its request: the engine reclaims the
  slot and its KV pages on the next tick
  (``serving_disconnects_total``).  Submit-time rejections arrive as
  ordinary JSON error responses — the stream only starts once the
  request is live.
* ``GET /healthz`` — readiness keyed to the engine state machine:
  200 for ``healthy``/``degraded``, **503 for ``draining`` and
  ``failed``** so load balancers stop routing before teardown or after
  an unrecovered failure.  Carries ``heartbeat_age_s`` (seconds since
  the last completed tick) and ``engine_restarts`` so liveness probes
  never have to parse the full ``/stats`` JSON.
* ``GET /stats`` — the full metrics snapshot (serving/metrics.py),
  including ``state``, ``state_transitions``, ``engine_failures`` and
  ``engine_restarts``.  Four keys are a STABLE ROUTING CONTRACT
  (docs/serving.md "HTTP API") — always present, always typed:
  ``queue_depth`` (int), ``occupancy`` (float 0..1), ``engine_state``
  (str), ``heartbeat_age_s`` (float; -1.0 until the first tick
  completes).  The front tier balances and evicts on exactly these.
* ``GET /metrics`` — Prometheus text exposition (0.0.4): the engine's
  ``serving_*`` families plus the process default registry (training,
  elastic, eager-runtime, timeline families) in one scrape.
* ``GET /tuning`` — autotuner state when ``EngineConfig.autotune`` is
  on (phase, current/best knob settings, objective trajectory);
  ``{"enabled": false}`` otherwise (docs/serving.md "Autotuning").

Tracing (docs/observability.md): every ``/generate`` request gets a
trace id — the ``X-Trace-Id`` header when present and valid, a minted
one otherwise — propagated through the scheduler and engine and echoed
back in the response (``trace_id`` field + ``X-Trace-Id`` header, on
SUCCESS AND on every typed-error path), alongside a per-request timing
``breakdown`` (queue wait, prefill, decode, host-sync lag).
"""

from __future__ import annotations

import json
import queue
import select
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Sequence

from horovod_tpu.obs import tracing as obs_tracing
from horovod_tpu.obs.registry import default_registry
from horovod_tpu.serving import sse
from horovod_tpu.serving.engine import DEGRADED, HEALTHY, InferenceEngine
from horovod_tpu.serving.scheduler import (
    CacheOutOfPagesError,
    DeadlineExceededError,
    DrainingError,
    EngineFailedError,
    QueueFullError,
    RequestTooLongError,
    ServingError,
)

__all__ = ["ServingServer"]


class _Handler(BaseHTTPRequestHandler):
    # The ThreadingHTTPServer instance carries the engine (see
    # ServingServer.start); BaseHTTPRequestHandler exposes it as
    # self.server.
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: metrics are the log
        pass

    def _json(self, code: int, payload: dict,
              trace_id: Optional[str] = None,
              headers: Optional[dict] = None) -> None:
        if trace_id is not None:
            payload.setdefault("trace_id", trace_id)
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        if trace_id is not None:
            self.send_header(obs_tracing.TRACE_ID_HEADER, trace_id)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        engine: InferenceEngine = self.server.engine
        if self.path == "/healthz":
            state = engine.health
            code = 200 if state in (HEALTHY, DEGRADED) else 503
            age = engine.heartbeat_age
            self._json(code, {
                "status": state,
                "slots_free": engine.slots.free_count,
                "queue_depth": engine.scheduler.depth,
                # -1.0 = no tick completed yet (same sentinel as
                # /stats: the key is always a float, never null)
                "heartbeat_age_s":
                    round(age, 3) if age is not None else -1.0,
                "engine_restarts": engine.metrics.engine_restarts.value,
            }, headers=None if code == 200 else {"Retry-After": "1"})
        elif self.path == "/stats":
            self._json(200, engine.stats())
        elif self.path == "/tuning":
            # Autotuner state: phase, current/best knob settings, and
            # the objective trajectory (docs/serving.md "Autotuning").
            tuner = engine._tuner
            if tuner is None:
                self._json(200, {"enabled": False})
            else:
                self._json(200, {"enabled": True, **tuner.snapshot()})
        elif self.path == "/metrics":
            # One scrape covers everything: the engine's private
            # serving_* registry plus the process-wide default registry
            # (training / elastic / eager / timeline families).
            # Windowed gauges (achieved FLOP/s) refresh per scrape,
            # not only when someone polls /stats.
            engine.refresh_windowed_gauges()
            text = (engine.metrics.registry.to_prometheus()
                    + default_registry().to_prometheus())
            body = text.encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._json(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        # Trace-id ingress FIRST — accept a valid X-Trace-Id
        # (Dapper-style propagation from an upstream caller), mint
        # otherwise — so EVERY response carries the id, including the
        # malformed-input 400s below; a trace that dead-ends exactly on
        # bad input is no trace at all.
        # The ONE ingress trust rule (shared with the router so the
        # two fronts cannot drift): X-Parent-Span and X-Trace-Sampled
        # are honored only alongside a valid propagated X-Trace-Id —
        # a parent span on a freshly minted trace would be a dangling
        # (or spoofed) edge, and malformed/oversized span ids are
        # dropped, never echoed into span streams.
        trace_id, parent_span, sampled = \
            obs_tracing.propagation_from_headers(self.headers)
        # Read the body, even on error paths: HTTP/1.1 keep-alive
        # reuses the connection, and unread body bytes would be parsed
        # as the next request line.
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
        except ValueError:
            self._json(400, {"error": "bad Content-Length"},
                       trace_id=trace_id)
            return
        if self.path != "/generate":
            self._json(404, {"error": f"unknown path {self.path}"})
            return
        engine: InferenceEngine = self.server.engine
        try:
            req = json.loads(body or b"{}")
        except json.JSONDecodeError as e:
            self._json(400, {"error": f"bad JSON body: {e}"},
                       trace_id=trace_id)
            return

        tokens = req.get("tokens")
        if tokens is None and "text" in req:
            encode = self.server.encode
            if encode is None:
                self._json(400, {"error": "server has no text encoder; "
                                          "send token ids"},
                           trace_id=trace_id)
                return
            tokens = encode(req["text"])
        if not tokens:
            self._json(400, {"error": "need non-empty 'tokens' (or "
                                      "'text' with an encoder)"},
                       trace_id=trace_id)
            return

        def fut_err(code: int, e: BaseException, etype: str,
                    headers: Optional[dict] = None,
                    resume: Optional[dict] = None) -> None:
            payload = {"error": str(e), "type": etype}
            b = fut.breakdown() if fut is not None else None
            if b is not None:
                payload["breakdown"] = b
            if resume is not None:
                payload["resume"] = resume
            self._json(code, payload, trace_id=trace_id, headers=headers)

        def resume_descriptor(deadline: float) -> Optional[dict]:
            """The RESUME DESCRIPTOR (docs/serving.md "Front tier"):
            on an engine-failure response for a request that was IN
            FLIGHT, tell the caller (the router) exactly what a
            re-dispatch needs — the tokens this engine already emitted
            (append them to the prompt elsewhere and decode continues
            token-identically) and the REMAINING deadline budget (a
            failover inherits what is left, never a fresh timeout)."""
            if fut is None:
                return None  # submit-time rejection: nothing ran
            return {
                "emitted_tokens": fut.tokens_so_far(),
                "deadline_remaining_ms": max(0.0, round(
                    (deadline - time.monotonic()) * 1e3, 3)),
                # the failed attempt's span id: a resumed re-dispatch
                # links back to it in the cross-process trace tree
                "span_id": fut.trace.span_id
                if fut.trace is not None else None,
            }

        timeout_ms = req.get("timeout_ms")
        stream = bool(req.get("stream"))
        t_recv = time.monotonic()
        tok_q: Optional[queue.Queue] = None
        on_token = None
        if stream:
            # The engine thread must never block on a client socket:
            # tokens cross to this handler thread through a queue, and
            # the SSE writes happen here (bounded by max_new_tokens).
            tok_q = queue.Queue()

            def on_token(tok, piece, _q=tok_q):
                _q.put((tok, piece))
        fut = None
        try:
            # Every request gets an engine deadline: the client's
            # timeout_ms, or the server's request_timeout when none is
            # sent — an abandoned request retires itself even if this
            # handler dies before it can cancel.
            deadline = time.monotonic() + (
                float(timeout_ms) / 1e3 if timeout_ms
                else self.server.request_timeout)
            fut = engine.submit(
                [int(t) for t in tokens],
                max_new_tokens=req.get("max_new_tokens"),
                eos_id=req.get("eos_id"),
                deadline=deadline,
                on_token=on_token,
                trace_id=trace_id,
                parent_span=parent_span,
                sampled=sampled,
                # Per-request speculative opt-out ("speculative":
                # false pins the request to one-token-per-tick greedy
                # inside the same executable; output is identical).
                speculative=req.get("speculative"),
                # Per-request sampling (validated in submit; bad
                # values land in the ServingError -> 400 path below).
                temperature=req.get("temperature", 0.0),
                top_k=req.get("top_k", 0),
                top_p=req.get("top_p", 0.0),
                seed=req.get("seed"),
                # SLO class (docs/serving.md "Scheduling"): priority-
                # then-EDF admission order, preemption down the class
                # order under pressure.  Unknown classes are a typed
                # ServingError -> 400 below.
                priority=req.get("priority", "interactive"))
            if stream:
                # The request is live: from here the response is the
                # SSE stream (200 + chunked), errors included — it
                # never raises back into the JSON error paths.
                self._stream_response(engine, fut, trace_id, tok_q,
                                      t_recv, deadline)
                return
            # The engine's deadline retirement (partial result, reason
            # "deadline") should win over this hard HTTP timeout, which
            # only fires when the engine cannot retire (e.g. hung) —
            # hence the grace on top of request_timeout.
            out = fut.result(timeout=self.server.request_timeout
                             + self.server.timeout_grace)
        except QueueFullError as e:
            fut_err(429, e, "queue_full")
            return
        except CacheOutOfPagesError as e:
            # Shed load, same protocol as a full queue: the page pool
            # cannot hold this request (submit-time) or it was
            # preempted mid-decode — retry with backoff.
            fut_err(429, e, "out_of_pages")
            return
        except RequestTooLongError as e:
            fut_err(413, e, "too_long")
            return
        except DeadlineExceededError as e:
            fut_err(504, e, "deadline_exceeded")
            return
        except DrainingError as e:
            # Retry-After: draining is TRANSIENT from the fleet's point
            # of view — a router retries elsewhere immediately, a bare
            # client should come back shortly (docs/serving.md).
            fut_err(503, e, "draining", headers={"Retry-After": "1"})
            return
        except EngineFailedError as e:
            # Submit-time (terminally failed) or result-time (this
            # request was in flight when the engine failed/stalled
            # beyond its resume grace).  In-flight failures carry the
            # resume descriptor so a front tier can continue the
            # request on another replica from where it left off.
            fut_err(503, e, "engine_failed",
                    resume=resume_descriptor(deadline))
            return
        except (ServingError, ValueError, TypeError) as e:
            # TypeError covers non-numeric JSON fields (timeout_ms,
            # max_new_tokens, nested token lists): a 400, not a dropped
            # connection.
            self._json(400, {"error": str(e)}, trace_id=trace_id)
            return
        except TimeoutError as e:
            # 504 without cancellation would leak the slot: the engine
            # would keep decoding to max_new_tokens for a caller that
            # already got its error page.  cancel() reclaims the slot
            # (or purges the queue entry) on the next tick.
            if fut is not None:
                fut.cancel()
            fut_err(504, e, "timeout")
            return
        payload = {
            "tokens": out,
            "finish_reason": fut.finish_reason,
            "ttft_ms": round(fut.ttft * 1e3, 3) if fut.ttft else None,
            "breakdown": fut.breakdown(),
        }
        if engine.detokenize is not None:
            payload["text"] = fut.text
        self._json(200, payload, trace_id=trace_id)

    # -- SSE streaming (stream=true) ---------------------------------------

    def _client_gone(self) -> bool:
        """Peek the client socket between events: a readable socket
        whose recv returns b"" is a half-closed connection — the
        client hung up while we were decoding.  (A client PIPELINING
        bytes reads as data, not a hangup.)"""
        try:
            r, _, _ = select.select([self.connection], [], [], 0)
            if not r:
                return False
            return self.connection.recv(1, socket.MSG_PEEK) == b""
        except (OSError, ValueError):
            return True

    def _stream_response(self, engine: InferenceEngine, fut, trace_id,
                         tok_q: "queue.Queue", t_recv: float,
                         deadline: float) -> None:
        """Stream one live request as chunked SSE: token events as the
        engine emits them (the overlap pipeline's one-tick lag — a
        token event means the identity-checked, journaled emission
        already happened), then exactly one terminal ``done``/``error``
        event (:mod:`horovod_tpu.serving.sse`).

        Client disconnect — detected on a failed event write OR by the
        socket peek while idle between tokens — CANCELS the request:
        the engine reclaims the slot and its pages on its next tick
        (``serving_disconnects_total``).  The stream never raises back
        into ``do_POST``'s JSON error paths: once the 200 is on the
        wire, failures are in-band ``error`` events (engine failures
        carry the same resume descriptor the non-streamed 503 does, so
        a router can fail the stream over mid-flight)."""
        metrics = engine.metrics
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header(obs_tracing.TRACE_ID_HEADER, trace_id)
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        # The stream owns this connection to the end — no keep-alive
        # reuse after a mid-stream cancel/error could half-happen.
        self.close_connection = True
        budget = t_recv + self.server.request_timeout \
            + self.server.timeout_grace
        first = True
        n_sent = 0

        def emit(kind, payload) -> None:
            data = sse.event_bytes(kind, payload)
            self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")

        def send_tok(tok, piece) -> None:
            # The ONE token-event emitter (live loop + post-resolution
            # drain): event shape, TTFB observation, and counters
            # cannot drift between the two.
            nonlocal first, n_sent
            ev = {"i": n_sent, "token": int(tok)}
            if piece is not None:
                ev["text"] = piece
            emit("token", ev)
            if first:
                first = False
                metrics.streamed_ttfb.observe(time.monotonic() - t_recv)
            n_sent += 1
            metrics.streamed_tokens.inc()

        try:
            while True:
                try:
                    tok, piece = tok_q.get(timeout=0.05)
                except queue.Empty:
                    if fut.done():
                        break
                    if time.monotonic() > budget:
                        # The hard HTTP timeout (engine hung past its
                        # own deadline retirement): cancel and say so
                        # in-band.
                        fut.cancel()
                        emit("error", {
                            "type": "timeout",
                            "error": "generation still in progress at "
                                     "the server timeout",
                            "trace_id": trace_id})
                        self.wfile.write(b"0\r\n\r\n")
                        return
                    if self._client_gone():
                        raise ConnectionAbortedError("client gone")
                    continue
                send_tok(tok, piece)
            # Resolved: drain what the resolving emission already
            # queued (tokens always land on the queue before the
            # future resolves), then the one terminal event.
            while True:
                try:
                    tok, piece = tok_q.get_nowait()
                except queue.Empty:
                    break
                send_tok(tok, piece)
            try:
                out = fut.result(timeout=0)
            except EngineFailedError as e:
                # Same resume contract as the non-streamed 503: the
                # router absorbs the descriptor and continues the
                # stream on a surviving replica.
                emit("error", {
                    "type": "engine_failed", "error": str(e),
                    "trace_id": trace_id,
                    "resume": {
                        "emitted_tokens": fut.tokens_so_far(),
                        "deadline_remaining_ms": max(0.0, round(
                            (deadline - time.monotonic()) * 1e3, 3)),
                        "span_id": fut.trace.span_id
                        if fut.trace is not None else None,
                    }})
            except DeadlineExceededError as e:
                emit("error", {"type": "deadline_exceeded",
                               "error": str(e), "trace_id": trace_id})
            except CacheOutOfPagesError as e:
                # Preempted mid-decode (pool exhausted): same type tag
                # as the non-streamed 429, retryable elsewhere.
                emit("error", {"type": "out_of_pages", "error": str(e),
                               "trace_id": trace_id})
            except ServingError as e:
                emit("error", {"type": "error", "error": str(e),
                               "trace_id": trace_id})
            else:
                payload = {
                    "tokens": out,
                    "finish_reason": fut.finish_reason,
                    "ttft_ms": round(fut.ttft * 1e3, 3)
                    if fut.ttft else None,
                    "breakdown": fut.breakdown(),
                    "trace_id": trace_id,
                }
                if engine.detokenize is not None:
                    payload["text"] = fut.text
                emit("done", payload)
            self.wfile.write(b"0\r\n\r\n")
        except OSError:
            # Client disconnect (write failed, or the idle peek saw
            # the hangup): cancel — the engine reclaims the slot and
            # its pages on the next tick; the future resolves
            # "cancelled" with the tokens so far, which also purges
            # the journal entry.
            if fut.cancel():
                metrics.disconnects.inc()


class ServingServer:
    """Own the engine thread + HTTP listener lifecycle.

    >>> srv = ServingServer(engine, port=0)      # 0 = ephemeral port
    >>> srv.start()                              # engine + HTTP threads
    >>> srv.address                              # ("127.0.0.1", 43117)
    >>> srv.stop(drain_timeout=30)               # graceful drain, then down
    """

    def __init__(self, engine: InferenceEngine, *,
                 host: str = "127.0.0.1", port: int = 8000,
                 encode: Optional[Callable[[str], Sequence[int]]] = None,
                 request_timeout: float = 120.0,
                 timeout_grace: float = 5.0):
        self.engine = engine
        self.host = host
        self.port = port
        self.encode = encode
        self.request_timeout = request_timeout
        self.timeout_grace = timeout_grace
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self):
        """(host, port) actually bound (resolves port=0)."""
        if self._httpd is None:
            return (self.host, self.port)
        return self._httpd.server_address[:2]

    def start(self) -> "ServingServer":
        if self._httpd is not None:
            return self
        self.engine.start()
        self._httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.engine = self.engine
        self._httpd.encode = self.encode
        self._httpd.request_timeout = self.request_timeout
        self._httpd.timeout_grace = self.timeout_grace
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serving-http",
            daemon=True)
        self._thread.start()
        return self

    def stop(self, drain_timeout: float = 30.0) -> None:
        """Graceful drain, then teardown — bounded by ``drain_timeout``.

        1. The engine enters ``draining``: new ``/generate`` calls get
           503 ``"draining"``, ``/healthz`` goes non-200 (load
           balancers stop routing).
        2. Admitted and queued requests run to completion (the engine
           keeps ticking); if the budget lapses first, whatever remains
           is force-resolved with a typed :class:`EngineFailedError` —
           teardown never strands a future.
        3. The HTTP listener and the engine thread shut down.
        """
        if self._httpd is None and self._thread is None:
            return
        self.engine.begin_drain()
        if not self.engine.drain(timeout=drain_timeout):
            self.engine.terminate(
                f"server shutdown: drain budget ({drain_timeout}s) "
                f"exhausted")
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        self.engine.stop()

    def __enter__(self) -> "ServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
