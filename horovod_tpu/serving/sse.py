"""Server-Sent Events plumbing shared by the serving server (emit), the
router (parse + re-emit across failovers), and the tests (client-side
assertions) — ONE definition of the wire format so the three cannot
drift.

The stream a ``POST /generate`` with ``"stream": true`` returns
(docs/serving.md "HTTP API"):

* ``event: token`` — ``{"i": N, "token": ID}`` (+ ``"text"`` with a
  detokenizer): one event per retired token, in order, ``i`` the
  0-based GLOBAL index within the request (the router keeps it global
  across failovers, so a client can detect gaps/dupes trivially).
* ``event: done`` — the same payload shape as the non-streamed 200
  body (``tokens`` — the full id list, authoritative — plus
  ``finish_reason`` / ``ttft_ms`` / ``breakdown`` / ``trace_id``).
* ``event: error`` — the same payload shape as the non-streamed typed
  error body (``type`` / ``error`` / optional ``resume`` descriptor),
  for failures AFTER the 200 + headers are already on the wire.

Every stream ends with exactly one ``done`` OR one ``error`` event
(the terminal event), carried over chunked transfer encoding.
"""

from __future__ import annotations

import json
from typing import Dict, List, Tuple

__all__ = ["SSEParser", "event_bytes", "read_stream"]


def event_bytes(kind: str, payload: Dict) -> bytes:
    """One SSE event frame: ``event: <kind>`` + one JSON ``data`` line."""
    return (f"event: {kind}\ndata: "
            f"{json.dumps(payload, separators=(',', ':'))}\n\n").encode()


class SSEParser:
    """Incremental SSE frame parser: feed raw body bytes (any chunking),
    get completed ``(kind, payload)`` events out.  Unknown lines are
    ignored (comments, retry hints); a frame with unparseable JSON data
    surfaces as ``(kind, {"_raw": <text>})`` rather than killing the
    stream — the consumer decides how loud to be."""

    def __init__(self) -> None:
        self._buf = b""

    def feed(self, data: bytes) -> List[Tuple[str, Dict]]:
        self._buf += data
        out: List[Tuple[str, Dict]] = []
        while b"\n\n" in self._buf:
            frame, self._buf = self._buf.split(b"\n\n", 1)
            kind, payload = "message", {}
            for line in frame.decode("utf-8", "replace").splitlines():
                if line.startswith("event:"):
                    kind = line[len("event:"):].strip()
                elif line.startswith("data:"):
                    text = line[len("data:"):].strip()
                    try:
                        payload = json.loads(text)
                    except json.JSONDecodeError:
                        payload = {"_raw": text}
            out.append((kind, payload))
        return out


def read_stream(resp, chunk: int = 4096) -> List[Tuple[str, Dict]]:
    """Drain an ``http.client.HTTPResponse`` SSE body to completion —
    the test/client convenience.  Uses ``read1`` (returns as soon as
    the current chunk has data) so events arrive live; plain
    ``read(n)`` would block until ``n`` bytes accumulate."""
    parser = SSEParser()
    events: List[Tuple[str, Dict]] = []
    read1 = getattr(resp, "read1", None)
    while True:
        data = read1(chunk) if read1 is not None else resp.read(chunk)
        if not data:
            return events
        events.extend(parser.feed(data))
