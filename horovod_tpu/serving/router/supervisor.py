"""ReplicaSupervisor: keep N engine replicas alive, forever.

The elastic driver's playbook (:mod:`horovod_tpu.runner.elastic_driver`
— exit-code watchers, heartbeat staleness, notice → grace → terminate,
exponential backoff between epochs) pointed at serving workers instead
of training ranks.  Differences that matter:

* replicas are INDEPENDENT — there is no mesh to re-rendezvous, so a
  death never touches the survivors: the dead slot respawns alone
  while the registry keeps routing to the rest;
* "failed" has two shapes HTTP can see that an exit code cannot:
  a replica whose engine went terminally ``failed`` (the replica
  self-exits with :data:`EXIT_CODE_REPLICA_FAILED`, and the registry
  evicts it within a poll either way), and a WEDGED replica whose
  process is alive but whose engine stopped ticking (stale
  ``heartbeat_age_s``) or whose HTTP listener stopped answering.  The
  supervisor watches the registry for replicas that stay unroutable
  past ``unhealthy_grace`` (or never become routable within
  ``startup_timeout``) and runs the drain sequence on them: SIGTERM
  (the replica's graceful-drain handler), ``shutdown_grace`` to
  comply, then SIGKILL — the exit watcher then respawns as usual;
* restarts are UNBOUNDED: a front tier's job is to keep capacity up,
  so a crash-looping replica is rate-limited by exponential backoff
  (``backoff_initial``..``backoff_max``, reset after a replica
  survives ``backoff_reset_after`` seconds), never given up on.

Each spawn gets a fresh port and a fresh registry identity
(``r<slot>g<generation>``), so a respawn can never inherit a dead
process's poll state.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from horovod_tpu.runner.run_func import _free_port
from horovod_tpu.serving.router.registry import (
    ReplicaEndpoint,
    ReplicaRegistry,
)

logger = logging.getLogger("horovod_tpu")

__all__ = ["EXIT_CODE_REPLICA_FAILED", "ReplicaHandle", "ReplicaSpec",
           "ReplicaSupervisor"]

#: A replica whose engine went terminally ``failed`` exits with this
#: code (cf. the elastic worker's EXIT_CODE_RESTART=75): the exit
#: watcher sees an unambiguous "engine dead, process fine" and
#: respawns without waiting for the registry to notice.
EXIT_CODE_REPLICA_FAILED = 76


@dataclasses.dataclass
class ReplicaSpec:
    """What one replica process serves — rendered into a
    ``python -m horovod_tpu.serving.router.replica_main`` command line.

    Either ``params_path`` (a pickle written by
    :func:`horovod_tpu.serving.router.replica_main.dump_model` — the
    trained-model path ``examples/serve.py --replicas`` uses) or the
    model-shape fields + ``seed`` (deterministic init, what the tests
    use: every replica built from the same seed serves oracle-identical
    greedy output).  ``faults`` are replica-side FaultInjector specs
    (``site:kind[:skip[:delay]]``) for chaos tests.
    """

    params_path: Optional[str] = None
    seed: int = 0
    vocab: int = 64
    d_model: int = 32
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 64
    max_seq: int = 48
    n_kv_heads: int = 2
    #: tensor-parallel degree per replica (docs/serving.md
    #: "Tensor-parallel replicas"): each replica process owns a tp-
    #: device GSPMD mesh.  The supervisor hands every SLOT a DISJOINT
    #: device set — accelerator hosts via the visible-devices envs
    #: (CUDA_VISIBLE_DEVICES / TPU_VISIBLE_DEVICES: slot s gets
    #: ordinals [s*tp, (s+1)*tp), filled only when the operator has
    #: not pinned them; multi-host TPU topologies additionally need
    #: operator-set TPU_PROCESS_BOUNDS — out of scope here), CPU
    #: hosts via forced host-device partitioning (each process's
    #: virtual devices are private to it by construction) — so N tp-K
    #: replicas coexist behind the same router with failover/resume/
    #: streaming unchanged.
    tp: int = 1
    slots: int = 4
    max_queue_depth: int = 64
    max_prefills_per_tick: int = 2
    tick_timeout: float = 60.0
    request_timeout: float = 120.0
    drain_timeout: float = 10.0
    warm: Sequence[int] = ()
    faults: Sequence[str] = ()
    #: Config-generation label (docs/serving.md "Fleet rollouts"):
    #: stamped into the replica's EngineConfig and echoed through its
    #: /stats so the rollout controller can prove which config a live
    #: process was built at.  0 = the incumbent baseline.
    config_gen: int = 0
    #: Extra EngineConfig overrides rendered as repeatable
    #: ``--set name=value`` flags (typed like replay's settings:
    #: int/float/bool/none/str) — how a rollout candidate carries
    #: engine knobs that have no dedicated CLI flag.
    engine_knobs: Dict[str, object] = dataclasses.field(
        default_factory=dict)
    extra_args: Sequence[str] = ()

    def command(self, port: int, host: str = "127.0.0.1") -> List[str]:
        cmd = [sys.executable, "-m",
               "horovod_tpu.serving.router.replica_main",
               "--host", host,
               "--port", str(port),
               "--slots", str(self.slots),
               "--max-queue-depth", str(self.max_queue_depth),
               "--max-prefills-per-tick", str(self.max_prefills_per_tick),
               "--tick-timeout", repr(self.tick_timeout),
               "--request-timeout", repr(self.request_timeout),
               "--drain-timeout", repr(self.drain_timeout)]
        if self.params_path:
            cmd += ["--params", self.params_path]
        else:
            cmd += ["--seed", str(self.seed),
                    "--vocab", str(self.vocab),
                    "--d-model", str(self.d_model),
                    "--n-heads", str(self.n_heads),
                    "--n-layers", str(self.n_layers),
                    "--d-ff", str(self.d_ff),
                    "--max-seq", str(self.max_seq),
                    "--kv-heads", str(self.n_kv_heads)]
        if self.tp > 1:
            cmd += ["--tp", str(self.tp)]
        for w in self.warm:
            cmd += ["--warm", str(w)]
        for f in self.faults:
            cmd += ["--fault", f]
        if self.config_gen:
            cmd += ["--config-gen", str(self.config_gen)]
        for name, value in self.engine_knobs.items():
            rendered = ("none" if value is None
                        else str(value).lower() if isinstance(value, bool)
                        else str(value))
            cmd += ["--set", f"{name}={rendered}"]
        cmd += list(self.extra_args)
        return cmd


@dataclasses.dataclass
class ReplicaHandle:
    """One supervised replica slot's live process."""

    slot: int
    gen: int
    port: int
    proc: subprocess.Popen
    spawned_at: float
    restarts: int = 0            # respawns of this SLOT so far
    term_sent_at: Optional[float] = None
    kill_sent: bool = False      # drain escalated to SIGKILL (once)
    unroutable_since: Optional[float] = None

    @property
    def rid(self) -> str:
        return f"r{self.slot}g{self.gen}"

    @property
    def pid(self) -> int:
        return self.proc.pid


class ReplicaSupervisor:
    """Spawn, monitor, drain, and respawn N replica processes.

    ``spec`` is a :class:`ReplicaSpec` or a callable
    ``(slot, port) -> command list`` for custom replica programs.  The
    supervisor feeds the shared ``registry`` (creating one when not
    given): endpoints are added at spawn and removed at reap, so the
    router's routing set always reflects live processes — readiness
    itself comes from the registry's polls.
    """

    def __init__(self, spec, n_replicas: int, *,
                 registry: Optional[ReplicaRegistry] = None,
                 host: str = "127.0.0.1",
                 env: Optional[Dict[str, str]] = None,
                 backoff_initial: float = 0.5,
                 backoff_max: float = 10.0,
                 backoff_reset_after: float = 30.0,
                 shutdown_grace: float = 5.0,
                 unhealthy_grace: float = 5.0,
                 startup_timeout: float = 300.0,
                 monitor_interval: float = 0.1,
                 log_dir: Optional[str] = None,
                 journal_dir: Optional[str] = None,
                 span_dir: Optional[str] = None) -> None:
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self._spec = spec
        self.n_replicas = n_replicas
        self.registry = registry if registry is not None \
            else ReplicaRegistry()
        self._host = host
        self._env = env
        self._backoff_initial = backoff_initial
        self._backoff_max = backoff_max
        self._backoff_reset_after = backoff_reset_after
        self._shutdown_grace = shutdown_grace
        self._unhealthy_grace = unhealthy_grace
        self._startup_timeout = startup_timeout
        self._monitor_interval = monitor_interval
        self._log_dir = log_dir
        # Request-journal files (docs/serving.md "Front tier"): each
        # replica journals its in-flight decode state to
        # journal_dir/<rid>.journal.jsonl; the mapping OUTLIVES the
        # process (kept after reap) so the router can read a SIGKILL'd
        # replica's journal post-mortem and resume its requests
        # elsewhere (RouterServer(resume_lookup=sup.resume_lookup)).
        self._journal_dir = journal_dir
        self._journal_paths: Dict[str, str] = {}
        # Span streams (docs/observability.md "Distributed tracing"):
        # each replica generation appends spans to
        # span_dir/<rid>.spans.jsonl; the directory is what
        # RouterServer(span_dir=...) assembles GET /trace/<id> from —
        # a SIGKILL'd generation's stream is exactly the evidence the
        # autopsy needs, so files survive the reap (pruned past gen-1
        # like journals, bounding crash loops).
        self._span_dir = span_dir
        self._span_paths: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._handles: Dict[int, ReplicaHandle] = {}   # slot -> handle
        self._respawn_at: Dict[int, float] = {}        # slot -> monotonic
        self._gen: Dict[int, int] = {}
        # Per-slot spec overrides (rollout controller): a slot with an
        # override respawns at THAT spec instead of self._spec — the
        # mechanism by which a rolling reconfiguration rebuilds one
        # replica at a time while the rest keep the incumbent config.
        self._slot_specs: Dict[int, ReplicaSpec] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ReplicaSupervisor":
        if self._thread is not None:
            return self
        self._stop.clear()
        for slot in range(self.n_replicas):
            self._spawn(slot)
        self._thread = threading.Thread(
            target=self._monitor_loop, name="replica-supervisor",
            daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop supervision and tear every replica down — gracefully
        (SIGTERM → replica drain) when ``drain``, escalating to
        SIGKILL after ``shutdown_grace`` either way."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        with self._lock:
            handles = list(self._handles.values())
            self._handles.clear()
            self._respawn_at.clear()
        for h in handles:
            self.registry.remove(h.rid)
            if h.proc.poll() is None:
                self._signal(h, signal.SIGTERM if drain else signal.SIGKILL)
        deadline = time.monotonic() + self._shutdown_grace
        for h in handles:
            while h.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if h.proc.poll() is None:
                if drain:
                    h.kill_sent = True
                    self.registry.metrics.drain_timeouts.inc()
                    self._instant("replica_drain_timeout",
                                  {"rid": h.rid, "pid": h.pid,
                                   "grace_s": self._shutdown_grace})
                    logger.warning(
                        "router: replica %s (pid %d) did not drain "
                        "within shutdown_grace=%.1fs at stop; "
                        "escalating to SIGKILL", h.rid, h.pid,
                        self._shutdown_grace)
                self._signal(h, signal.SIGKILL)
                h.proc.wait()

    def wait_ready(self, n: Optional[int] = None,
                   timeout: float = 300.0) -> bool:
        """Block until ``n`` (default: all) replicas are in rotation.
        The registry poll thread must be running (RouterServer.start
        does that) — or poll here when it is not."""
        want = self.n_replicas if n is None else n
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.registry._thread is None:
                self.registry.poll_now()
            if len(self.registry.in_rotation()) >= want:
                return True
            time.sleep(0.1)
        return False

    def replicas(self) -> List[ReplicaHandle]:
        with self._lock:
            return list(self._handles.values())

    def handle(self, slot: int) -> Optional[ReplicaHandle]:
        with self._lock:
            return self._handles.get(slot)

    # -- per-slot spec overrides (rollout controller) ----------------------

    @property
    def spec(self):
        """The fleet-wide base spec (ReplicaSpec or command callable)."""
        return self._spec

    def set_base_spec(self, spec: ReplicaSpec) -> None:
        """Promote ``spec`` to the fleet-wide base and drop every slot
        override — the rollout controller's final act after a full
        promotion (from here on, ANY respawn lands on the new config)."""
        with self._lock:
            self._spec = spec
            self._slot_specs.clear()

    def slot_spec(self, slot: int):
        """The spec ``slot`` will (re)spawn at: its override when the
        rollout controller set one, else the fleet-wide base spec."""
        with self._lock:
            return self._slot_specs.get(slot, self._spec)

    def set_slot_spec(self, slot: int, spec: ReplicaSpec) -> None:
        """Override ``slot``'s spec — takes effect on its NEXT spawn
        (the rollout controller drains the slot to trigger one)."""
        if callable(self._spec):
            raise TypeError(
                "slot spec overrides require a ReplicaSpec base, not a "
                "callable command factory")
        with self._lock:
            self._slot_specs[slot] = spec

    def clear_slot_spec(self, slot: int) -> None:
        with self._lock:
            self._slot_specs.pop(slot, None)

    def drain_slot(self, slot: int,
                   reason: str = "rollout") -> Optional[ReplicaHandle]:
        """Start the graceful drain of one slot's live process (SIGTERM
        → the replica's drain handler; the monitor escalates to SIGKILL
        after ``shutdown_grace``).  The exit watcher then respawns the
        slot at :meth:`slot_spec` — this is the rollout controller's
        one-replica-at-a-time rebuild primitive.  Returns the handle
        being drained (None for an empty slot)."""
        with self._lock:
            h = self._handles.get(slot)
        if h is None or h.proc.poll() is not None:
            return h
        if h.term_sent_at is None:
            h.term_sent_at = time.monotonic()
            self._instant("replica_drain",
                          {"rid": h.rid, "pid": h.pid, "reason": reason})
            logger.info("router: draining replica %s (pid %d) for %s",
                        h.rid, h.pid, reason)
            self._signal(h, signal.SIGTERM)
        return h

    # -- spawn / reap ------------------------------------------------------

    def _command(self, slot: int, port: int,
                 journal_path: Optional[str] = None,
                 span_path: Optional[str] = None) -> List[str]:
        if callable(self._spec):
            # Custom commands own their bind address; the registry
            # still polls self._host, so the callable must agree.
            # (Journaling/span streams are replica_main plumbing —
            # custom programs arm their own.)
            return list(self._spec(slot, port))
        cmd = self.slot_spec(slot).command(port, self._host)
        if journal_path:
            cmd += ["--journal", journal_path]
        if span_path:
            cmd += ["--spans", span_path]
        return cmd

    def resume_lookup(self, rid: str, trace_id: str) -> Optional[Dict]:
        """Post-mortem resume descriptor for ``trace_id`` on replica
        ``rid`` — reads the (possibly dead) replica's journal file.
        Wire this into ``RouterServer(resume_lookup=...)``; it keeps
        working after the reap removed the endpoint from the
        registry."""
        path = self._journal_paths.get(rid)
        if not path:
            return None
        try:
            from horovod_tpu.serving.journal import RequestJournal

            return RequestJournal.read_live(path).get(trace_id)
        except Exception:  # pragma: no cover - post-mortem best effort
            return None

    def _arm_gen_file(self, base_dir: Optional[str], paths: Dict[str, str],
                      slot: int, gen: int, suffix: str) -> Optional[str]:
        """One per-generation artifact file (journal or span stream):
        create its path under ``base_dir``, record it in ``paths``
        (the mapping OUTLIVES the process so post-mortem readers keep
        working after the reap), and prune this slot's generations
        older than gen-1 — the previous generation is live evidence
        the router may be reading right now, anything older is
        bounded away so a crash loop cannot grow the directory."""
        if not base_dir or callable(self._spec):
            return None
        os.makedirs(base_dir, exist_ok=True)
        path = os.path.join(base_dir, f"r{slot}g{gen}.{suffix}")
        paths[f"r{slot}g{gen}"] = path
        for g in range(gen - 1):
            old = paths.pop(f"r{slot}g{g}", None)
            if old:
                try:
                    os.remove(old)
                except OSError:
                    pass
        return path

    def _spawn(self, slot: int) -> None:
        gen = self._gen.get(slot, -1) + 1
        self._gen[slot] = gen
        port = _free_port()
        env = dict(os.environ)
        if self._env:
            env.update(self._env)
        # The replica must import horovod_tpu no matter where the
        # supervisor's process got it from (checkout, PYTHONPATH, or
        # bare cwd): pin the package's own root onto the child's path.
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_root)
        # Tensor-parallel replicas get a DISJOINT device set per SLOT
        # (stable across respawns — a respawned generation inherits
        # its slot's devices, never a survivor's): accelerator hosts
        # via the visible-devices env, CPU hosts via the forced-host-
        # device flag (each process's virtual devices are private to
        # it, so disjointness is by construction).  An operator who
        # already pinned the env wins — the supervisor only fills
        # blanks.
        spec = self.slot_spec(slot)
        tp = getattr(spec, "tp", 1) if not callable(spec) else 1
        if tp > 1:
            flag = "--xla_force_host_platform_device_count"
            if flag not in env.get("XLA_FLAGS", ""):
                env["XLA_FLAGS"] = (
                    f"{env.get('XLA_FLAGS', '')} {flag}={tp}".strip())
            ordinals = ",".join(str(slot * tp + i) for i in range(tp))
            for var in ("CUDA_VISIBLE_DEVICES", "TPU_VISIBLE_DEVICES"):
                if var not in env:
                    env[var] = ordinals
        prev = self._handles.get(slot)
        restarts = prev.restarts + 1 if prev is not None else 0
        journal_path = self._arm_gen_file(
            self._journal_dir, self._journal_paths, slot, gen,
            "journal.jsonl")
        span_path = self._arm_gen_file(
            self._span_dir, self._span_paths, slot, gen, "spans.jsonl")
        out = subprocess.DEVNULL
        if self._log_dir:
            os.makedirs(self._log_dir, exist_ok=True)
            out = open(os.path.join(self._log_dir,
                                    f"r{slot}g{gen}.log"), "wb")
        proc = subprocess.Popen(
            self._command(slot, port, journal_path, span_path), env=env,
            stdout=out, stderr=subprocess.STDOUT if self._log_dir
            else subprocess.DEVNULL,
            start_new_session=True)
        if out is not subprocess.DEVNULL:
            out.close()  # the child holds its own fd now
        h = ReplicaHandle(slot=slot, gen=gen, port=port, proc=proc,
                          spawned_at=time.monotonic(), restarts=restarts)
        with self._lock:
            self._handles[slot] = h
            self._respawn_at.pop(slot, None)
        self.registry.add(ReplicaEndpoint(h.rid, self._host, port,
                                          journal_path=journal_path))
        self._instant("replica_spawn" if gen == 0 else "replica_respawn",
                      {"rid": h.rid, "pid": proc.pid, "port": port})
        if gen:
            self.registry.metrics.replica_restarts.inc()
            logger.warning(
                "router: respawned replica slot %d as %s (pid %d, "
                "port %d, restart #%d)", slot, h.rid, proc.pid, port,
                restarts)

    def _signal(self, h: ReplicaHandle, sig: int) -> None:
        try:
            # The whole session: a replica that forked helpers dies
            # with them (start_new_session=True above).
            os.killpg(os.getpgid(h.proc.pid), sig)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                h.proc.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass

    # -- monitor -----------------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self._monitor_interval):
            try:
                self._sweep()
            except Exception:  # pragma: no cover - supervision survives
                logger.exception("router: supervisor sweep failed")

    def _sweep(self) -> None:
        now = time.monotonic()
        routable = {s.endpoint.rid
                    for s in self.registry.in_rotation()}
        with self._lock:
            handles = list(self._handles.items())
        for slot, h in handles:
            rc = h.proc.poll()
            if rc is not None:
                self._reap(slot, h, rc, now)
                continue
            # Health policing over the registry's view: a live process
            # whose replica is terminally failed, wedged (stale
            # heartbeat), or unreachable gets the drain sequence.
            if h.rid in routable:
                h.unroutable_since = None
                if h.term_sent_at is None:
                    continue
            if h.term_sent_at is not None:
                if (now - h.term_sent_at >= self._shutdown_grace
                        and not h.kill_sent):
                    # Drain blew its budget: count it, mark the
                    # timeline, and escalate ONCE — in-flight requests
                    # now fail over via the journal instead of
                    # finishing locally.
                    h.kill_sent = True
                    self.registry.metrics.drain_timeouts.inc()
                    self._instant("replica_drain_timeout",
                                  {"rid": h.rid, "pid": h.pid,
                                   "grace_s": self._shutdown_grace})
                    logger.warning(
                        "router: replica %s (pid %d) drain exceeded "
                        "shutdown_grace=%.1fs; escalating to SIGKILL",
                        h.rid, h.pid, self._shutdown_grace)
                    self._signal(h, signal.SIGKILL)
                continue
            if h.unroutable_since is None:
                h.unroutable_since = now
                continue
            grace = (self._unhealthy_grace
                     if self._was_ready(h) else self._startup_timeout)
            if now - h.unroutable_since >= grace:
                logger.warning(
                    "router: replica %s (pid %d) unroutable for %.1fs; "
                    "draining and respawning", h.rid, h.pid,
                    now - h.unroutable_since)
                self._instant("replica_drain", {"rid": h.rid,
                                                "pid": h.pid})
                h.term_sent_at = now
                self._signal(h, signal.SIGTERM)

    def _was_ready(self, h: ReplicaHandle) -> bool:
        for s in self.registry.statuses():
            if s.endpoint.rid == h.rid:
                return s.ever_routable
        return False

    def _reap(self, slot: int, h: ReplicaHandle, rc: int,
              now: float) -> None:
        with self._lock:
            if self._handles.get(slot) is not h:
                return  # already replaced
            first = slot not in self._respawn_at
            if first:
                if now - h.spawned_at >= self._backoff_reset_after:
                    # Survived long enough: this death starts a FRESH
                    # backoff sequence (crash loops back off, steady
                    # replicas respawn instantly).
                    h.restarts = -1  # _spawn adds 1 -> 0
                    backoff = 0.0
                else:
                    backoff = min(
                        self._backoff_initial * (2.0 ** h.restarts),
                        self._backoff_max)
                self._respawn_at[slot] = now + backoff
            when = self._respawn_at[slot]
        if first:
            self.registry.remove(h.rid)
            self._instant("replica_exit", {"rid": h.rid, "pid": h.pid,
                                           "exit_code": rc})
            logger.warning(
                "router: replica %s (pid %d) exited with code %s%s%s",
                h.rid, h.pid, rc,
                " (engine terminally failed)"
                if rc == EXIT_CODE_REPLICA_FAILED else "",
                " (drain timed out; was SIGKILLed)"
                if h.kill_sent else "")
        if now >= when and not self._stop.is_set():
            self._spawn(slot)

    @staticmethod
    def _instant(name: str, args: Dict) -> None:
        try:
            from horovod_tpu.obs import tracing as obs_tracing

            obs_tracing.instant(name, args)
        except Exception:  # pragma: no cover
            pass
