"""ReplicaRegistry: the live routing set, maintained by health polls.

The registry is the router's single source of truth about replicas.
The supervisor :meth:`ReplicaRegistry.add`/:meth:`remove`\\ s endpoints
as it spawns and reaps processes; a poll thread GETs each replica's
``/stats`` every ``poll_interval`` seconds and keeps a
:class:`ReplicaStatus` per endpoint from the snapshot's four stable
contract keys (docs/serving.md "HTTP API"):

* ``queue_depth`` (int) and ``occupancy`` (float) — what
  join-shortest-queue balances on;
* ``engine_state`` — only ``healthy``/``degraded`` replicas are
  routable; ``draining``/``failed`` leave rotation within one poll;
* ``heartbeat_age_s`` (float; ``-1.0`` = no tick completed yet) —
  a replica whose engine stopped ticking for ``heartbeat_stale``
  seconds is wedged even if its HTTP thread still answers, and leaves
  rotation; a fresh replica that NEVER ticks gets ``startup_grace``
  from the moment it is added before the same judgment.

The contract also carries two INFORMATIONAL typed keys — ``tp`` (the
replica's tensor-parallel degree) and ``mesh`` (its device layout;
docs/serving.md "Tensor-parallel replicas") — surfaced per replica in
the router's ``/stats`` fleet view but never routed on: a tp=K
replica is one queue like any other.

``fail_threshold`` consecutive poll failures (connection refused,
timeout, garbage payload) also evict — a SIGKILL'd replica stops
answering long before anyone inspects its exit code.  The proxy path
can evict faster still with :meth:`mark_failed` (a failed ``/generate``
connection is fresher evidence than the last poll).  Re-admission has
HYSTERESIS: an evicted replica needs ``readmit_threshold`` (default 2)
CONSECUTIVE good polls before it rejoins rotation, so a flapping
replica — one that answers every other poll — stays out instead of
oscillating in and out every ``poll_interval``.  A replica that was
never evicted (failures below threshold, never marked) is unaffected:
one good poll still clears a transient blip.

The contract's ``config_generation`` key (an opaque int label stamped
by the supervisor at spawn; docs/serving.md "Fleet rollouts") is
tracked per replica so the rollout controller can verify fleet
convergence — like ``tp``/``mesh`` it is never routed on.

:meth:`pick` implements join-shortest-queue: least ``queue_depth``,
then least ``occupancy``, round-robin among ties so equally idle
replicas share load instead of dogpiling the lowest id.  During a
rollout, :meth:`set_canary` overlays a deterministic weighted split:
the canary replica receives exactly ``weight`` of picks (a credit
accumulator, no RNG) and everyone else splits the rest by JSQ.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from horovod_tpu.serving.router.metrics import RouterMetrics

logger = logging.getLogger("horovod_tpu")

__all__ = ["ReplicaEndpoint", "ReplicaRegistry", "ReplicaStatus"]

ROUTABLE_STATES = ("healthy", "degraded")


@dataclasses.dataclass(frozen=True)
class ReplicaEndpoint:
    """Where one replica listens.  ``rid`` is unique per PROCESS
    generation (``r<slot>g<gen>`` from the supervisor) so a respawn is
    a new endpoint with fresh poll state, never a stale carryover.

    ``journal_path`` is the replica's request-journal file when the
    supervisor armed one (``--journal``): the router reads it
    POST-MORTEM after a connection-level death to resume the dead
    replica's in-flight requests elsewhere — part of the routing
    contract, like the four ``/stats`` keys."""

    rid: str
    host: str
    port: int
    journal_path: Optional[str] = None

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"


@dataclasses.dataclass
class ReplicaStatus:
    """Last known health of one replica, as the poll thread saw it."""

    endpoint: ReplicaEndpoint
    queue_depth: int = 0
    occupancy: float = 0.0
    engine_state: str = "unknown"
    heartbeat_age_s: float = -1.0
    # Serving topology (docs/serving.md "Tensor-parallel replicas"):
    # the replica's tensor-parallel degree and mesh layout, surfaced
    # from the /stats contract's typed tp/mesh keys so operators (and
    # capacity planners reading the router's per-replica view) can
    # tell one tp=K replica from K tp=1 replicas.  Informational —
    # routing still balances on queue_depth/occupancy alone.
    tp: int = 1
    mesh: str = ""
    # Which config generation this replica was built at (stamped by the
    # supervisor via --config-gen, echoed through /stats).  The rollout
    # controller reads it to prove fleet convergence; routing ignores it.
    config_gen: int = 0
    added_at: float = 0.0
    last_ok: Optional[float] = None     # monotonic time of last good poll
    consecutive_failures: int = 0
    consecutive_ok: int = 0             # good polls since last failure/mark
    marked_failed: bool = False         # proxy-side eviction flag
    mark_seq: int = 0                   # bumped per mark_failed (race guard)
    ever_routable: bool = False
    polls: int = 0

    def as_dict(self) -> Dict:
        return {
            "rid": self.endpoint.rid,
            "url": self.endpoint.base_url,
            "queue_depth": self.queue_depth,
            "occupancy": self.occupancy,
            "engine_state": self.engine_state,
            "heartbeat_age_s": self.heartbeat_age_s,
            "tp": self.tp,
            "mesh": self.mesh,
            "config_generation": self.config_gen,
            "consecutive_poll_failures": self.consecutive_failures,
            "marked_failed": self.marked_failed,
            "polls": self.polls,
        }


class ReplicaRegistry:
    """Thread-safe routing set over polled replica health.

    ``poll_interval`` bounds eviction latency (a dead replica leaves
    rotation within one interval plus ``fail_threshold - 1`` extra
    polls); ``poll_timeout`` bounds how long one wedged replica can
    delay the sweep.  Polls run sequentially in one daemon thread —
    the front tier targets a handful of replicas, not hundreds.
    """

    def __init__(self, *, poll_interval: float = 0.25,
                 poll_timeout: float = 2.0,
                 fail_threshold: int = 2,
                 readmit_threshold: int = 2,
                 heartbeat_stale: float = 60.0,
                 startup_grace: Optional[float] = None,
                 metrics: Optional[RouterMetrics] = None) -> None:
        if fail_threshold < 1:
            raise ValueError("fail_threshold must be >= 1")
        if readmit_threshold < 1:
            raise ValueError("readmit_threshold must be >= 1")
        self.poll_interval = poll_interval
        self.poll_timeout = poll_timeout
        self.fail_threshold = fail_threshold
        self.readmit_threshold = readmit_threshold
        self.heartbeat_stale = heartbeat_stale
        # A cold replica pays imports + XLA compiles before its first
        # tick; give it the stale budget (or more) before calling a
        # -1.0 heartbeat "wedged".
        self.startup_grace = (startup_grace if startup_grace is not None
                              else max(heartbeat_stale, 60.0))
        self.metrics = metrics if metrics is not None else RouterMetrics()
        self._lock = threading.Lock()
        self._status: Dict[str, ReplicaStatus] = {}
        self._rr = 0  # round-robin tiebreak cursor
        # Canary overlay (rollout controller): while set, pick() routes
        # exactly `weight` of requests to the canary rid via a credit
        # accumulator and JSQ-balances the rest across the incumbents.
        self._canary_rid: Optional[str] = None
        self._canary_weight = 0.0
        self._canary_credit = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- membership (supervisor-driven) -----------------------------------

    def add(self, endpoint: ReplicaEndpoint) -> None:
        with self._lock:
            if endpoint.rid in self._status:
                raise ValueError(f"replica {endpoint.rid} already registered")
            self._status[endpoint.rid] = ReplicaStatus(
                endpoint=endpoint, added_at=time.monotonic())
            self.metrics.replicas_total.set(len(self._status))

    def remove(self, rid: str) -> None:
        with self._lock:
            self._status.pop(rid, None)
            self.metrics.replicas_total.set(len(self._status))
            self.metrics.replicas_in_rotation.set(
                sum(1 for s in self._status.values()
                    if self._routable(s)))

    def mark_failed(self, rid: str) -> None:
        """Proxy-side eviction: a /generate attempt to this replica
        just failed at the connection level.  Takes effect immediately;
        ``readmit_threshold`` consecutive successful polls re-admit."""
        with self._lock:
            st = self._status.get(rid)
            if st is None or st.marked_failed:
                return
            if self._routable(st):
                self.metrics.replica_evictions.inc()
                self._instant("replica_evicted",
                              {"rid": rid, "reason": "proxy_failure"})
            st.marked_failed = True
            st.mark_seq += 1
            st.consecutive_ok = 0
            self.metrics.replicas_in_rotation.set(
                sum(1 for s in self._status.values()
                    if self._routable(s)))

    # -- canary overlay (rollout controller) -------------------------------

    def set_canary(self, rid: str, weight: float) -> None:
        """Route exactly ``weight`` (0..1) of picks to ``rid`` while it
        is routable and at least one other replica is too; the rest go
        through normal JSQ over the incumbents.  Deterministic: a
        credit accumulator, not a coin flip, so a scoring window of K
        requests sends ``floor``/``ceil`` of ``weight*K`` to the
        canary."""
        with self._lock:
            self._canary_rid = rid
            self._canary_weight = max(0.0, min(1.0, float(weight)))
            self._canary_credit = 0.0

    def clear_canary(self) -> None:
        with self._lock:
            self._canary_rid = None
            self._canary_weight = 0.0
            self._canary_credit = 0.0

    def canary(self) -> Optional[str]:
        with self._lock:
            return self._canary_rid

    # -- routing set -------------------------------------------------------

    def _routable(self, st: ReplicaStatus) -> bool:
        """Caller holds the lock (or owns a private copy)."""
        if st.marked_failed or st.last_ok is None:
            return False
        if st.consecutive_failures >= self.fail_threshold:
            return False
        if st.engine_state not in ROUTABLE_STATES:
            return False
        if st.heartbeat_age_s >= 0.0:
            if st.heartbeat_age_s > self.heartbeat_stale:
                return False
        elif time.monotonic() - st.added_at > self.startup_grace:
            return False  # never ticked, past the warmup allowance
        return True

    def statuses(self) -> List[ReplicaStatus]:
        """Snapshot of every registered replica's last known status."""
        with self._lock:
            return [dataclasses.replace(s) for s in self._status.values()]

    def in_rotation(self) -> List[ReplicaStatus]:
        with self._lock:
            return [dataclasses.replace(s) for s in self._status.values()
                    if self._routable(s)]

    def is_routable(self, rid: str) -> bool:
        with self._lock:
            st = self._status.get(rid)
            return st is not None and self._routable(st)

    def pick(self, exclude=()) -> Optional[ReplicaStatus]:
        """Join-shortest-queue: least ``queue_depth``, then least
        ``occupancy``, round-robin among ties.  ``exclude`` skips
        replicas this request already tried."""
        exclude = set(exclude)
        with self._lock:
            cands = [s for s in self._status.values()
                     if self._routable(s) and s.endpoint.rid not in exclude]
            if not cands:
                return None
            if self._canary_rid is not None:
                canary = next((s for s in cands
                               if s.endpoint.rid == self._canary_rid), None)
                others = [s for s in cands if s is not canary]
                if canary is not None and others:
                    self._canary_credit += self._canary_weight
                    if self._canary_credit >= 1.0:
                        self._canary_credit -= 1.0
                        return dataclasses.replace(canary)
                    cands = others
                # Canary alone in rotation (or gone): fall through to
                # plain JSQ — availability beats the traffic split.
            best = min((s.queue_depth, s.occupancy) for s in cands)
            ties = sorted(
                (s for s in cands
                 if (s.queue_depth, s.occupancy) == best),
                key=lambda s: s.endpoint.rid)
            st = ties[self._rr % len(ties)]
            self._rr += 1
            return dataclasses.replace(st)

    # -- polling -----------------------------------------------------------

    def _fetch_stats(self, endpoint: ReplicaEndpoint) -> Dict:
        with urllib.request.urlopen(endpoint.base_url + "/stats",
                                    timeout=self.poll_timeout) as r:
            return json.loads(r.read())

    def poll_now(self) -> None:
        """One synchronous sweep over every registered replica —
        the poll thread's body, also callable directly from tests."""
        with self._lock:
            endpoints = [(s.endpoint, s.mark_seq)
                         for s in self._status.values()]
        for ep, pre_fetch_seq in endpoints:
            try:
                snap = self._fetch_stats(ep)
                qd = int(snap["queue_depth"])
                occ = float(snap["occupancy"])
                state = str(snap["engine_state"])
                hb = float(snap["heartbeat_age_s"])
                # tp/mesh joined the contract in PR 15; .get defaults
                # keep a mixed-version fleet pollable during a rollout.
                tp = int(snap.get("tp", 1))
                mesh_desc = str(snap.get("mesh", ""))
                cg = int(snap.get("config_generation", 0))
            except Exception as e:
                self.metrics.poll_errors.inc()
                with self._lock:
                    st = self._status.get(ep.rid)
                    if st is None:
                        continue
                    was = self._routable(st)
                    st.consecutive_failures += 1
                    st.consecutive_ok = 0
                    st.polls += 1
                    if was and not self._routable(st):
                        self.metrics.replica_evictions.inc()
                        self._instant("replica_evicted", {
                            "rid": ep.rid, "reason": f"poll: {e}"})
                        logger.warning(
                            "router: replica %s left rotation (poll "
                            "failure #%d: %s)", ep.rid,
                            st.consecutive_failures, e)
                continue
            with self._lock:
                st = self._status.get(ep.rid)
                if st is None:
                    continue  # removed mid-poll
                was = self._routable(st)
                st.queue_depth = qd
                st.occupancy = occ
                st.engine_state = state
                st.heartbeat_age_s = hb
                st.tp = tp
                st.mesh = mesh_desc
                st.config_gen = cg
                st.last_ok = time.monotonic()
                st.consecutive_ok += 1
                # Re-admission hysteresis: an EVICTED replica (failures
                # at/past threshold, or proxy-marked) must string
                # together readmit_threshold good polls before its
                # eviction state clears — a flapper that fails every
                # other poll never makes it back.  A replica that was
                # never evicted clears a sub-threshold blip on the
                # first good poll, as before.
                evicted = (st.marked_failed
                           or st.consecutive_failures >= self.fail_threshold)
                if (not evicted
                        or st.consecutive_ok >= self.readmit_threshold):
                    st.consecutive_failures = 0
                    # Clear the proxy-side eviction only if no NEW mark
                    # landed while this (lock-free) fetch was in flight —
                    # a mark issued after the snapshot was taken is
                    # fresher evidence than the snapshot.
                    if st.mark_seq == pre_fetch_seq:
                        st.marked_failed = False
                st.polls += 1
                now_routable = self._routable(st)
                if was and not now_routable:
                    self.metrics.replica_evictions.inc()
                    self._instant("replica_evicted", {
                        "rid": ep.rid, "reason": state
                        if state not in ROUTABLE_STATES else "stale"})
                    logger.warning(
                        "router: replica %s left rotation (state=%s, "
                        "heartbeat_age=%.3fs)", ep.rid, state, hb)
                elif now_routable and not was:
                    self._instant("replica_rejoined" if st.ever_routable
                                  else "replica_ready", {"rid": ep.rid})
                if now_routable:
                    st.ever_routable = True
        with self._lock:
            self.metrics.replicas_in_rotation.set(
                sum(1 for s in self._status.values() if self._routable(s)))

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.poll_now()
            except Exception:  # pragma: no cover - never kill the sweep
                logger.exception("router: poll sweep failed")

    def start(self) -> "ReplicaRegistry":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._poll_loop, name="router-registry",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    @staticmethod
    def _instant(name: str, args: Dict) -> None:
        """Timeline instants (replica lifecycle on the one Perfetto
        axis) — observability never gates routing."""
        try:
            from horovod_tpu.obs import tracing as obs_tracing

            obs_tracing.instant(name, args)
        except Exception:  # pragma: no cover
            pass
