"""Replicated serving front tier (docs/serving.md "Front tier").

One engine replica is a single point of failure; this package spreads
traffic over N of them and keeps N true.  It marries the repo's two
halves — the elastic runner's supervision machinery (exit-code +
heartbeat monitoring, drain, exponential-backoff respawn, exactly the
:mod:`horovod_tpu.runner.elastic_driver` playbook pointed at serving
workers) and the continuous-batching engine — behind one stdlib-HTTP
front door:

* :class:`~horovod_tpu.serving.router.supervisor.ReplicaSupervisor`
  spawns N replica processes (each a full engine + HTTP server on its
  own port, :mod:`horovod_tpu.serving.router.replica_main`), watches
  their exit codes, and drains/respawns dead, terminally-``failed``,
  or wedged replicas with exponential backoff;
* :class:`~horovod_tpu.serving.router.registry.ReplicaRegistry` polls
  each replica's ``/stats`` snapshot (the stable contract keys:
  ``queue_depth``, ``occupancy``, ``engine_state``,
  ``heartbeat_age_s``) on a short interval and maintains the live
  routing set — draining/failed/stale/unreachable replicas leave
  rotation within one poll;
* :class:`~horovod_tpu.serving.router.server.RouterServer` proxies
  ``/generate`` with a join-shortest-queue policy, propagates
  ``X-Trace-Id``, and on replica failure mid-request retries on
  another replica (capped attempts + backoff) — a SIGKILL'd replica
  under load drops zero requests, because a failed replica resolved
  nothing.  Failover RESUMES partially decoded requests when a resume
  descriptor is available (the replica's typed failure response, or
  its journal file read post-mortem after SIGKILL): the surviving
  replica continues from the emitted-token frontier under the
  REMAINING deadline budget, instead of re-executing from scratch.

    from horovod_tpu.serving.router import (
        ReplicaRegistry, ReplicaSpec, ReplicaSupervisor, RouterServer)

    registry = ReplicaRegistry()
    sup = ReplicaSupervisor(ReplicaSpec(seed=0), n_replicas=3,
                            registry=registry).start()
    sup.wait_ready(timeout=120)
    with RouterServer(registry, port=8000) as rt:
        ...                       # POST /generate just like one engine
    sup.stop()
"""

from horovod_tpu.serving.router.metrics import RouterMetrics
from horovod_tpu.serving.router.registry import (
    ReplicaEndpoint,
    ReplicaRegistry,
    ReplicaStatus,
)
from horovod_tpu.serving.router.rollout import (
    RolloutController,
    RolloutError,
)
from horovod_tpu.serving.router.server import RouterServer
from horovod_tpu.serving.router.supervisor import (
    EXIT_CODE_REPLICA_FAILED,
    ReplicaHandle,
    ReplicaSpec,
    ReplicaSupervisor,
)

__all__ = [
    "EXIT_CODE_REPLICA_FAILED",
    "ReplicaEndpoint", "ReplicaHandle", "ReplicaRegistry", "ReplicaSpec",
    "ReplicaStatus", "ReplicaSupervisor", "RolloutController",
    "RolloutError", "RouterMetrics", "RouterServer",
]
