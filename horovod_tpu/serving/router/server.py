"""The front door: one stdlib HTTP listener proxying over N replicas.

Same idiom as :mod:`horovod_tpu.serving.server` — a
``ThreadingHTTPServer`` with one handler thread per connection — but
each ``POST /generate`` is PROXIED to a replica chosen by
join-shortest-queue over the registry's live routing set, instead of
submitted to a local engine.

Failover contract (docs/serving.md "Front tier"): when the chosen
replica fails mid-request at the connection level (refused, reset,
proxy timeout — the SIGKILL signature), the router evicts it from
rotation immediately (:meth:`ReplicaRegistry.mark_failed`) and retries
the SAME request on another replica, up to ``max_attempts`` with
exponential backoff.  The retry is safe: a replica that died at the
connection level resolved nothing — the client saw no bytes — and
generation is repeatable, so re-running it elsewhere changes nothing
the caller can observe.  A replica that ANSWERS, even with a typed
error, resolved the request; 503 (draining / engine failed — the
replica is leaving rotation and produced no tokens the CLIENT saw) and
429 (queue full / out of pages — another replica may have room) are
relayed only after a retry elsewhere also fails.  Responses the
replica produced tokens for (200, 400, 413, 504) are relayed verbatim,
trace id and all.

Failover RESUMES rather than re-executes whenever a resume descriptor
is available (docs/serving.md "Front tier"): a replica whose engine
failed terminally answers 503 with ``{"resume": {"emitted_tokens":
[...], "deadline_remaining_ms": ...}}``, and a SIGKILL'd replica
leaves a request journal file (``--journal``, read post-mortem via
``resume_lookup``).  The router then re-dispatches ``prompt + emitted``
with the REMAINING decode and deadline budgets — the surviving replica
re-prefills once and decode continues token-identically — and prepends
the carried tokens to the final response (``"resumed": true``).  A
deadline that expires mid-failover resolves as the same typed 504 the
replicas use.  Only the paid-for work moves; nothing is generated
twice, nothing is dropped.

SLO classes ride failover untouched: the client's ``"priority"`` field
lives in the request body, and every re-dispatch (``dispatch_body`` /
the streamed twin) rewrites only ``tokens`` / ``max_new_tokens`` /
``timeout_ms`` around the original body — so a batch-class request
resumes as batch on the survivor, and a journal descriptor additionally
records the class (``priority`` in ``RequestJournal.read_live``) for
consumers that rebuild a body from scratch.  Deadline budgets compose
with EDF scheduling: the REMAINING ``timeout_ms`` a failover dispatches
becomes the replica-side deadline the scheduler orders on.

STREAMING (``"stream": true`` — docs/serving.md "Sampling +
streaming"): the replica's chunked SSE body is proxied through
event-by-event with trace headers intact, token indices kept GLOBAL
across failovers.  A replica that dies mid-stream (connection death,
or an in-band ``error`` event carrying a resume descriptor) is failed
over like the non-streamed path — the journal/descriptor tells the
router every token the dead replica emitted, the continuation is
dispatched as ``prompt + frontier`` with the remaining budgets, and
the client's stream continues WITHOUT re-emitting anything it already
received (tokens the dead replica journaled but never got onto the
wire are synthesized by the router first, then the survivor's events
follow).  The terminal ``done`` event carries the full concatenated
token list (``resumed: true``), byte-identical to an uninterrupted
run.  A client that disconnects mid-stream tears down the upstream
leg, which cancels the request on the replica within one tick.

Endpoints:

* ``POST /generate`` — proxied with failover, as above.  Adds
  ``X-Router-Replica`` (the replica that answered) and
  ``X-Router-Attempts``.  When no replica is in rotation: 503
  ``{"type": "no_replicas"}`` with a ``Retry-After`` header.
* ``GET /healthz`` — 200 while at least one replica is in rotation,
  503 (+ ``Retry-After``) otherwise; body carries
  ``replicas_in_rotation`` / ``replicas_total``.
* ``GET /stats`` — the router metrics snapshot plus every replica's
  last polled status.
* ``GET /metrics`` — the ``router_*`` families as Prometheus text.
* ``GET /trace/<id>`` — the full request AUTOPSY: the cross-process
  span tree for one trace id, assembled from the spans directory
  (``span_dir``) every process of this deployment appends to — every
  attempt on every replica generation (a SIGKILL'd attempt shows as an
  UNFINISHED span), the failover / resume / retry edges, and the
  carried-token accounting.  404 for an unknown id, 503 when no
  ``span_dir`` was configured.

Distributed tracing (docs/observability.md): when a span recorder is
active in the router process (``obs.tracing.start_spans``), each
request gets a ``router /generate`` root span with one child span per
proxy attempt; the attempt's span id rides the ``X-Parent-Span``
header to the replica, whose request span nests under it — the
collector then assembles ONE tree across processes.  Failover /
resume re-dispatches also carry ``X-Trace-Sampled: 1``: the
downstream share of an interesting trace must not be tail-dropped by
a replica that saw nothing unusual.
"""

from __future__ import annotations

import http.client
import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from horovod_tpu.obs import tracing as obs_tracing
from horovod_tpu.serving import sse
from horovod_tpu.serving.journal import RequestJournal
from horovod_tpu.serving.router.registry import ReplicaRegistry

__all__ = ["RouterServer"]

#: Replica responses that mean "this replica cannot take the request,
#: but another one might": worth a retry elsewhere before relaying.
RETRYABLE_STATUS = (429, 503)


class _ProxyError(Exception):
    """A proxy attempt died at the connection level: nothing was
    resolved on the replica side, so a retry duplicates no work the
    client could ever observe."""


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: metrics are the log
        pass

    # -- plumbing ----------------------------------------------------------

    def _json(self, code: int, payload: dict,
              headers: Optional[Dict[str, str]] = None) -> None:
        self._sent_code = code  # the root span's status (do_POST)
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- GET ---------------------------------------------------------------

    def do_GET(self):
        router: "RouterServer" = self.server.router
        registry = router.registry
        if self.path == "/healthz":
            up = len(registry.in_rotation())
            total = len(registry.statuses())
            code = 200 if up else 503
            hdrs = {} if up else {"Retry-After": str(router.retry_after)}
            self._json(code, {
                "status": "healthy" if up else "no_replicas",
                "replicas_in_rotation": up,
                "replicas_total": total,
            }, headers=hdrs)
        elif self.path == "/stats":
            self._json(200, router.stats())
        elif self.path == "/rollout":
            if router.rollout is None:
                self._json(503, {
                    "error": "no rollout controller configured on this "
                             "router (RouterServer(rollout=...))",
                    "type": "no_rollout_controller"})
            else:
                self._json(200, router.rollout.status())
        elif self.path == "/metrics":
            body = registry.metrics.registry.to_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path.startswith("/trace/"):
            tid = self.path[len("/trace/"):]
            if not obs_tracing.valid_trace_id(tid):
                self._json(400, {"error": "bad trace id",
                                 "type": "bad_trace_id"})
            elif router.span_dir is None:
                self._json(503, {
                    "error": "no span_dir configured on this router "
                             "(RouterServer(span_dir=...))",
                    "type": "no_span_store"})
            else:
                try:
                    autopsy = router.autopsy(tid)
                except Exception as e:
                    # A broken store must read as a broken STORE, not
                    # as "trace never recorded" — a 404 here would
                    # misdirect an operator mid-postmortem.
                    self._json(500, {
                        "error": f"span store unreadable: {e!r}",
                        "type": "span_store_error"})
                    return
                if autopsy is None:
                    self._json(404, {"error": f"trace {tid} not found",
                                     "type": "unknown_trace"})
                else:
                    self._json(200, autopsy)
        else:
            self._json(404, {"error": f"unknown path {self.path}"})

    # -- POST /rollout: the fleet-reconfiguration admin surface ------------

    def _rollout_admin(self, router: "RouterServer", body: bytes) -> None:
        """``POST /rollout`` (docs/serving.md "Fleet rollouts"):
        ``{"candidate": {...}}`` starts a rolling reconfiguration (202
        + status), ``{"abort": true}`` trips the active one into
        rollback.  409 when one is already in flight, 503 when the
        router has no controller wired."""
        from horovod_tpu.serving.router.rollout import RolloutError

        if router.rollout is None:
            self._json(503, {
                "error": "no rollout controller configured on this "
                         "router (RouterServer(rollout=...))",
                "type": "no_rollout_controller"})
            return
        try:
            obj = json.loads(body or b"{}")
        except json.JSONDecodeError:
            self._json(400, {"error": "body is not valid JSON",
                             "type": "bad_request"})
            return
        if not isinstance(obj, dict):
            self._json(400, {"error": "body must be a JSON object",
                             "type": "bad_request"})
            return
        if obj.get("abort"):
            self._json(200, router.rollout.abort())
            return
        candidate = obj.get("candidate")
        if not isinstance(candidate, dict) or not candidate:
            self._json(400, {
                "error": 'body needs {"candidate": {...config '
                         'deltas...}} or {"abort": true}',
                "type": "bad_request"})
            return
        try:
            status = router.rollout.start(
                candidate,
                allow_capacity_dip=obj.get("allow_capacity_dip"))
        except RolloutError as e:
            active = router.rollout.active
            self._json(409 if active else 400,
                       {"error": str(e),
                        "type": "rollout_active" if active
                        else "bad_candidate"})
            return
        self._json(202, status)

    # -- POST /generate: proxy with failover -------------------------------

    def _proxy_once(self, status_ep, body: bytes,
                    trace_id: Optional[str],
                    timeout: float,
                    parent_span: Optional[str] = None,
                    force_sample: bool = False
                    ) -> Tuple[int, bytes, Dict[str, str]]:
        """One attempt against one replica.  Raises :class:`_ProxyError`
        on connection-level failure (retry-safe); returns the replica's
        full response otherwise."""
        ep = status_ep.endpoint
        conn = http.client.HTTPConnection(ep.host, ep.port,
                                          timeout=timeout)
        try:
            headers = {"Content-Type": "application/json"}
            if trace_id:
                headers[obs_tracing.TRACE_ID_HEADER] = trace_id
                if parent_span:
                    headers[obs_tracing.PARENT_SPAN_HEADER] = parent_span
                if force_sample:
                    headers[obs_tracing.SAMPLED_HEADER] = "1"
            conn.request("POST", "/generate", body=body, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            out_headers = {}
            for h in (obs_tracing.TRACE_ID_HEADER, "Retry-After"):
                v = resp.getheader(h)
                if v is not None:
                    out_headers[h] = v
            return resp.status, payload, out_headers
        except (OSError, socket.timeout, http.client.HTTPException) as e:
            raise _ProxyError(f"replica {ep.rid}: {e}") from e
        finally:
            conn.close()

    def _proxy_open(self, status_ep, body: bytes,
                    trace_id: Optional[str], timeout: float,
                    parent_span: Optional[str] = None,
                    force_sample: bool = False):
        """Open one attempt and return ``(conn, resp)`` WITHOUT reading
        the body — the streaming variant of :meth:`_proxy_once` (the
        caller forwards the SSE body incrementally and must close the
        connection).  Raises :class:`_ProxyError` on connection-level
        failure before any response line arrived."""
        ep = status_ep.endpoint
        conn = http.client.HTTPConnection(ep.host, ep.port,
                                          timeout=timeout)
        try:
            headers = {"Content-Type": "application/json"}
            if trace_id:
                headers[obs_tracing.TRACE_ID_HEADER] = trace_id
                if parent_span:
                    headers[obs_tracing.PARENT_SPAN_HEADER] = parent_span
                if force_sample:
                    headers[obs_tracing.SAMPLED_HEADER] = "1"
            conn.request("POST", "/generate", body=body, headers=headers)
            return conn, conn.getresponse()
        except (OSError, socket.timeout, http.client.HTTPException) as e:
            conn.close()
            raise _ProxyError(f"replica {ep.rid}: {e}") from e

    def do_POST(self):
        router: "RouterServer" = self.server.router
        registry = router.registry
        metrics = registry.metrics
        # Read the body FIRST, error paths included: HTTP/1.1
        # keep-alive would parse unread body bytes as the next request.
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
        except ValueError:
            self._json(400, {"error": "bad Content-Length"})
            return
        if self.path == "/rollout":
            self._rollout_admin(router, body)
            return
        if self.path != "/generate":
            self._json(404, {"error": f"unknown path {self.path}"})
            return
        # The shared ingress trust rule (obs/tracing.py — identical at
        # replica ingress, so the two fronts cannot drift): a client's
        # X-Parent-Span nests the root span, and X-Trace-Sampled
        # force-samples every dispatch of this request — both honored
        # only alongside a valid X-Trace-Id.
        trace_id, client_parent, client_sampled = \
            obs_tracing.propagation_from_headers(self.headers)
        metrics.requests.inc()

        # Distributed-tracing root span (module docstring): one
        # "router /generate" span per request, one child per proxy
        # attempt, typed events for every failover hop.  rec is None
        # unless obs.tracing.start_spans ran in this process — every
        # site below is a no-op then.
        rec = obs_tracing.spans()
        root_sid = None
        if rec is not None:
            root_sid = rec.begin("router /generate", trace_id,
                                 parent=client_parent)
        self._sent_code = 0
        self._root_attrs: Dict = {}
        try:
            self._generate(router, registry, metrics, body, trace_id,
                           rec, root_sid, client_sampled, client_parent)
        finally:
            if rec is not None and root_sid is not None:
                rec.finish(root_sid,
                           status=f"http:{self._sent_code}"
                           if self._sent_code else "error:unsent",
                           attrs=self._root_attrs)

    def _generate(self, router, registry, metrics, body, trace_id,
                  rec, root_sid, client_sampled=False,
                  client_parent=None):

        # Resume-aware failover state (docs/serving.md "Front tier").
        # A failed attempt may yield a RESUME DESCRIPTOR — from the
        # replica's typed engine-failure response, or post-mortem from
        # a SIGKILL'd replica's journal file — carrying the tokens it
        # already emitted and the REMAINING deadline budget.  The next
        # attempt then dispatches prompt + carried tokens with the
        # reduced decode budget and the remaining timeout: decode
        # continues where it left off (greedy output is a pure function
        # of the token sequence), and the final relay prepends the
        # carried tokens so the client sees one seamless result.
        try:
            body_obj = json.loads(body or b"{}")
        except json.JSONDecodeError:
            body_obj = None
        resumable = (isinstance(body_obj, dict)
                     and isinstance(body_obj.get("tokens"), list)
                     and isinstance(body_obj.get("max_new_tokens"), int))
        if isinstance(body_obj, dict) and body_obj.get("stream"):
            self._generate_stream(router, registry, metrics, body,
                                  body_obj, resumable, trace_id, rec,
                                  root_sid, client_sampled,
                                  client_parent)
            return
        carried: list = []
        remaining_ms: Optional[float] = None
        absorbed_at: float = 0.0
        carried_from: Optional[str] = None   # latest dead attempt's span

        def current_remaining_ms() -> Optional[float]:
            # Time the ROUTER spends between attempts (backoff, further
            # failures) counts against the budget too — the journal
            # path gets this for free (remaining computed at read
            # time); the inline-descriptor path must age it here, or
            # every crash-hop would extend the request's wall budget.
            # TWIN: _generate_stream has the streamed variant of this
            # carry machinery (frontier vs carried; wire already
            # partially sent) — budget/descriptor semantics changed
            # here must change there too.
            if remaining_ms is None:
                return None
            return remaining_ms - (time.monotonic() - absorbed_at) * 1e3

        def dispatch_body() -> bytes:
            rem = current_remaining_ms()
            if not carried and rem is None:
                return body
            obj = dict(body_obj)
            obj["tokens"] = list(body_obj["tokens"]) + carried
            obj["max_new_tokens"] = \
                body_obj["max_new_tokens"] - len(carried)
            if rem is not None:
                # The REMAINING budget, never a fresh one: a request
                # must not live longer because it crash-hopped.
                obj["timeout_ms"] = max(1.0, rem)
            return json.dumps(obj).encode()

        def absorb(desc, rid: Optional[str] = None,
                   source: Optional[str] = None) -> None:
            """Fold one attempt's resume descriptor into the carry."""
            nonlocal remaining_ms, absorbed_at, carried_from
            if not resumable or not isinstance(desc, dict):
                return
            if desc.get("span_id"):
                carried_from = desc["span_id"]
            toks = desc.get("emitted_tokens")
            if isinstance(toks, list):
                carried.extend(int(t) for t in toks)
                if rec is not None and toks:
                    # The RESUME edge, with the carried-token
                    # accounting and (when the journal/descriptor knew
                    # it) the dead attempt's span id — the autopsy
                    # links the continuation to the attempt it
                    # continues.
                    attrs = {"carried": len(toks)}
                    if rid:
                        attrs["from_replica"] = rid
                    if source:
                        attrs["source"] = source
                    if desc.get("span_id"):
                        attrs["resumed_from_span"] = desc["span_id"]
                    rec.event(trace_id, root_sid, "resume", attrs)
            rem = desc.get("deadline_remaining_ms")
            if rem is not None:
                remaining_ms = float(rem)
                absorbed_at = time.monotonic()

        def deadline_expired() -> bool:
            rem = current_remaining_ms()
            return rem is not None and rem <= 0.0

        def carry_complete() -> Optional[str]:
            """The carried tokens may already BE the full result — the
            dead replica emitted its last token but never answered
            (killed before the end-of-journal line, or the budget was
            spent across hops).  Re-dispatching would send
            ``max_new_tokens <= 0`` (a 400) or decode past EOS; finish
            the request here instead."""
            if not resumable or not carried:
                return None
            eos = body_obj.get("eos_id")
            if eos is not None and carried[-1] == eos:
                return "eos"
            if len(carried) >= body_obj["max_new_tokens"]:
                return "length"
            return None

        def finish_from_carry(reason: str, attempts: int) -> None:
            metrics.resume_failovers.inc()
            self._json(200, {
                "tokens": list(carried),
                "finish_reason": reason,
                "resumed": True,
                "resume_carried_tokens": len(carried),
                "trace_id": trace_id,
            }, headers={obs_tracing.TRACE_ID_HEADER: trace_id,
                        "X-Router-Attempts": str(attempts)})

        def track_root() -> None:
            self._root_attrs.update({
                "attempts": attempts,
                "carried_tokens": len(carried),
                "resumed": bool(carried)})

        tried = set()
        attempts = 0
        failed_over = False
        last: Optional[Tuple[int, bytes, Dict[str, str]]] = None
        while attempts < router.max_attempts:
            rep = registry.pick(exclude=tried)
            if rep is None and tried:
                # Everything in rotation was already tried; a replica
                # may have REJOINED (or a respawn landed) — allow a
                # fresh pick rather than failing a retryable request.
                rep = registry.pick()
            if rep is None:
                break
            if attempts:
                metrics.retries.inc()
                if rec is not None:
                    rec.event(trace_id, root_sid, "retry",
                              {"attempt": attempts + 1,
                               "replica": rep.endpoint.rid})
                time.sleep(min(
                    router.retry_backoff * (2.0 ** (attempts - 1)),
                    router.retry_backoff_max))
            attempts += 1
            tried.add(rep.endpoint.rid)
            track_root()
            att_sid = None
            if rec is not None:
                att_sid = rec.begin(
                    f"attempt {attempts} -> {rep.endpoint.rid}",
                    trace_id, parent=root_sid,
                    attrs={"replica": rep.endpoint.rid,
                           **({"carried_tokens": len(carried)}
                              if carried else {})})
            t0 = time.monotonic()
            try:
                status, payload, hdrs = self._proxy_once(
                    rep, dispatch_body(), trace_id, router.proxy_timeout,
                    # The attempt span is the replica-side request
                    # span's parent; with no router recorder the
                    # client's own (validated) parent is forwarded
                    # instead, so a replicas-only span deployment
                    # still joins the upstream caller's tree.
                    # Failover/resume continuations are force-sampled
                    # end to end (module docstring) — NOT routine
                    # 429/capacity retries, which would re-introduce
                    # per-token span volume exactly at peak load.
                    parent_span=att_sid or client_parent,
                    force_sample=(client_sampled or bool(carried)
                                  or failed_over))
            except _ProxyError:
                metrics.proxy_latency.observe(time.monotonic() - t0)
                # Connection-level death: evict NOW (the poll thread
                # would take up to one interval to notice) and retry —
                # the replica resolved nothing CLIENT-VISIBLE, so the
                # retry is safe; its journal file (when the supervisor
                # armed one) tells us how far decode got, so the retry
                # RESUMES rather than re-executing.
                registry.mark_failed(rep.endpoint.rid)
                failed_over = True
                if rec is not None:
                    rec.finish(att_sid, status="error:connection")
                    rec.event(trace_id, root_sid, "failover",
                              {"replica": rep.endpoint.rid,
                               "attempt": attempts})
                absorb(router.lookup_resume(rep.endpoint, trace_id),
                       rid=rep.endpoint.rid, source="journal")
                track_root()
                reason = carry_complete()
                if reason is not None:
                    finish_from_carry(reason, attempts)
                    return
                if deadline_expired():
                    break  # typed 504 below — the budget died with it
                continue
            metrics.proxy_latency.observe(time.monotonic() - t0)
            if status in RETRYABLE_STATUS:
                last = (status, payload, hdrs)
                if rec is not None:
                    rec.finish(att_sid, status=f"http:{status}")
                # A typed engine-failure response carries the resume
                # descriptor inline — absorb it before trying elsewhere.
                try:
                    absorb(json.loads(payload).get("resume"),
                           rid=rep.endpoint.rid, source="descriptor")
                except (json.JSONDecodeError, AttributeError):
                    pass
                track_root()
                reason = carry_complete()
                if reason is not None:
                    finish_from_carry(reason, attempts)
                    return
                if deadline_expired():
                    break
                continue
            if rec is not None:
                rec.finish(att_sid, status=f"http:{status}")
            if attempts > 1 and status == 200:
                # Only a SUCCESS bought by a retry counts as a
                # failover save (the documented meaning of the family).
                metrics.failovers.inc()
            if status == 200 and carried:
                payload = self._merge_resumed(payload, carried, metrics)
            hdrs.setdefault(obs_tracing.TRACE_ID_HEADER, trace_id)
            hdrs["X-Router-Replica"] = rep.endpoint.rid
            hdrs["X-Router-Attempts"] = str(attempts)
            self._relay(status, payload, hdrs)
            return

        track_root()
        if deadline_expired():
            # The deadline lapsed MID-FAILOVER: same typed 504 the
            # replicas use for a queued-deadline lapse, with whatever
            # was decoded before the crash (token ids are authoritative
            # — a client that cares can keep them).
            self._json(504, {
                "error": "deadline expired during failover",
                "type": "deadline_exceeded",
                "trace_id": trace_id,
                "attempts": attempts,
                "tokens_so_far": carried,
            }, headers={obs_tracing.TRACE_ID_HEADER: trace_id,
                        "X-Router-Attempts": str(attempts)})
            return

        metrics.requests_failed.inc()
        if last is not None:
            # Every replica we reached answered with a typed
            # retryable error — relay the last one (it carries the
            # replica's own reason and trace id) rather than masking
            # it behind a generic router error.
            status, payload, hdrs = last
            if carried:
                # Rewrite the relayed descriptor to the FULL carry, so
                # a client that resumes upstream continues from the
                # true frontier, not just the last replica's share.
                try:
                    obj = json.loads(payload)
                    obj["resume"] = {
                        "emitted_tokens": list(carried),
                        "deadline_remaining_ms": current_remaining_ms(),
                        # the latest dead attempt's span id survives
                        # the rewrite: an upstream caller that resumes
                        # from this descriptor keeps the causal edge
                        # into ITS trace tree (stacked front tiers)
                        "span_id": carried_from,
                    }
                    payload = json.dumps(obj).encode()
                except (json.JSONDecodeError, AttributeError):
                    pass
            hdrs.setdefault(obs_tracing.TRACE_ID_HEADER, trace_id)
            hdrs.setdefault("Retry-After", str(router.retry_after))
            hdrs["X-Router-Attempts"] = str(attempts)
            self._relay(status, payload, hdrs)
            return
        self._json(503, {
            "error": "no replica in rotation"
                     if not attempts else
                     f"no replica reachable after {attempts} attempt(s)",
            "type": "no_replicas",
            "trace_id": trace_id,
            "attempts": attempts,
        }, headers={"Retry-After": str(router.retry_after),
                    obs_tracing.TRACE_ID_HEADER: trace_id})

    def _generate_stream(self, router, registry, metrics, body,
                         body_obj, resumable, trace_id, rec, root_sid,
                         client_sampled=False, client_parent=None):
        """``POST /generate`` with ``"stream": true`` — proxy the
        replica's SSE body through event-by-event, failing over
        MID-STREAM without re-emitting anything the client already has
        (module docstring).

        The carry is a FRONTIER: every token any replica is known to
        have emitted, in order.  ``sent`` counts token events on the
        client's wire — always a prefix of the frontier (the journal
        may know tokens that never reached the wire; the router
        synthesizes their events before forwarding a survivor).
        TWIN: ``_generate`` holds the non-streamed variant of this
        carry machinery — budget/descriptor semantics changed here
        must change there too (the differences are deliberate: the
        frontier keeps the LONGER of events-seen vs journal, and a
        non-resumable body can only retry before the first wire
        event).  Each
        attempt is dispatched with ``prompt + frontier`` and the
        remaining budgets; the position-keyed sampling PRNG makes the
        continuation token-identical for sampled requests too."""
        frontier: list = []       # every token any replica emitted
        sent = 0                  # token events on the client's wire
        remaining_ms: Optional[float] = None
        absorbed_at = 0.0
        carried_from: Optional[str] = None
        headers_sent = False

        class _ClientGone(Exception):
            """The CLIENT hung up — distinct from upstream death, so
            the failover loop cannot mistake one for the other."""

        def current_remaining_ms() -> Optional[float]:
            if remaining_ms is None:
                return None
            return remaining_ms - (time.monotonic() - absorbed_at) * 1e3

        def deadline_expired() -> bool:
            rem = current_remaining_ms()
            return rem is not None and rem <= 0.0

        def dispatch_body() -> bytes:
            rem = current_remaining_ms()
            # A non-resumable body (no token list / no int
            # max_new_tokens) can never be rewritten — and the loop
            # below guarantees it is only ever re-dispatched before
            # the first token event reached the client.
            if not resumable or (not frontier and rem is None):
                return body
            obj = dict(body_obj)
            obj["tokens"] = list(body_obj["tokens"]) + frontier
            obj["max_new_tokens"] = \
                body_obj["max_new_tokens"] - len(frontier)
            if rem is not None:
                obj["timeout_ms"] = max(1.0, rem)
            return json.dumps(obj).encode()

        def absorb(base: list, desc, rid=None, source=None) -> None:
            """Fold a dead attempt's resume descriptor into the
            frontier.  ``base`` is what the attempt was DISPATCHED
            with; the descriptor's ``emitted_tokens`` are the
            attempt's own share, appended after it.  The journal's
            view is a superset of what we saw as events, never a
            contradiction — keep whichever is longer."""
            nonlocal remaining_ms, absorbed_at, carried_from
            if not resumable or not isinstance(desc, dict):
                return
            if desc.get("span_id"):
                carried_from = desc["span_id"]
            toks = desc.get("emitted_tokens")
            if isinstance(toks, list):
                cand = list(base) + [int(t) for t in toks]
                if len(cand) > len(frontier):
                    frontier[:] = cand
                if rec is not None and toks:
                    attrs = {"carried": len(frontier)}
                    if rid:
                        attrs["from_replica"] = rid
                    if source:
                        attrs["source"] = source
                    if desc.get("span_id"):
                        attrs["resumed_from_span"] = desc["span_id"]
                    rec.event(trace_id, root_sid, "resume", attrs)
            rem = desc.get("deadline_remaining_ms")
            if rem is not None:
                remaining_ms = float(rem)
                absorbed_at = time.monotonic()

        def send_headers(rid: Optional[str], attempts: int) -> None:
            nonlocal headers_sent
            if headers_sent:
                return
            headers_sent = True
            self._sent_code = 200
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header(obs_tracing.TRACE_ID_HEADER, trace_id)
            if rid:
                self.send_header("X-Router-Replica", rid)
            self.send_header("X-Router-Attempts", str(attempts))
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            self.close_connection = True  # the stream owns the socket

        def emit(kind, payload) -> None:
            data = sse.event_bytes(kind, payload)
            try:
                self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
            except OSError as e:
                raise _ClientGone() from e

        def emit_token(tok: int, text=None) -> None:
            nonlocal sent
            ev = {"i": sent, "token": int(tok)}
            if text is not None:
                ev["text"] = text
            emit("token", ev)
            sent += 1

        def catch_up() -> None:
            # Tokens the journal proved emitted but the client never
            # received (the dead replica was killed between journaling
            # and the socket): synthesize their events — ids only,
            # ids are the authoritative cross-replica representation.
            while sent < len(frontier):
                emit_token(frontier[sent])

        def finish_chunks() -> None:
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass

        def carry_reason() -> Optional[str]:
            # The frontier may already BE the full result (the dead
            # replica emitted its last token but never finished the
            # stream) — re-dispatching would 400 or decode past EOS.
            if not (resumable and frontier):
                return None
            eos = body_obj.get("eos_id")
            if eos is not None and frontier[-1] == eos:
                return "eos"
            if len(frontier) >= body_obj["max_new_tokens"]:
                return "length"
            return None

        def finish_from_frontier(reason: str, attempts: int) -> None:
            send_headers(None, attempts)
            catch_up()
            metrics.resume_failovers.inc()
            emit("done", {"tokens": list(frontier),
                          "finish_reason": reason,
                          "resumed": True,
                          "resume_carried_tokens": len(frontier),
                          "trace_id": trace_id})
            finish_chunks()

        def track_root(attempts: int) -> None:
            self._root_attrs.update({
                "attempts": attempts, "streamed": True,
                "carried_tokens": len(frontier),
                "resumed": bool(frontier)})

        tried = set()
        attempts = 0
        failed_over = False
        last: Optional[Tuple[int, bytes, Dict[str, str]]] = None
        try:
            while attempts < router.max_attempts:
                rep = registry.pick(exclude=tried)
                if rep is None and tried:
                    rep = registry.pick()  # a respawn may have rejoined
                if rep is None:
                    break
                if attempts:
                    metrics.retries.inc()
                    if rec is not None:
                        rec.event(trace_id, root_sid, "retry",
                                  {"attempt": attempts + 1,
                                   "replica": rep.endpoint.rid})
                    time.sleep(min(
                        router.retry_backoff * (2.0 ** (attempts - 1)),
                        router.retry_backoff_max))
                attempts += 1
                tried.add(rep.endpoint.rid)
                track_root(attempts)
                att_sid = None
                if rec is not None:
                    att_sid = rec.begin(
                        f"attempt {attempts} -> {rep.endpoint.rid}",
                        trace_id, parent=root_sid,
                        attrs={"replica": rep.endpoint.rid,
                               "streamed": True,
                               **({"carried_tokens": len(frontier)}
                                  if frontier else {})})
                dispatched = list(frontier)
                t0 = time.monotonic()
                try:
                    conn, resp = self._proxy_open(
                        rep, dispatch_body(), trace_id,
                        router.proxy_timeout,
                        parent_span=att_sid or client_parent,
                        force_sample=(client_sampled or bool(frontier)
                                      or failed_over))
                except _ProxyError:
                    metrics.proxy_latency.observe(time.monotonic() - t0)
                    registry.mark_failed(rep.endpoint.rid)
                    failed_over = True
                    if rec is not None:
                        rec.finish(att_sid, status="error:connection")
                        rec.event(trace_id, root_sid, "failover",
                                  {"replica": rep.endpoint.rid,
                                   "attempt": attempts})
                    absorb(dispatched,
                           router.lookup_resume(rep.endpoint, trace_id),
                           rid=rep.endpoint.rid, source="journal")
                    track_root(attempts)
                    reason = carry_reason()
                    if reason is not None:
                        finish_from_frontier(reason, attempts)
                        return
                    if deadline_expired():
                        break
                    continue
                status = resp.status
                ctype = resp.getheader("Content-Type") or ""
                if status != 200 or "text/event-stream" not in ctype:
                    # A pre-stream answer: submit-time rejection (the
                    # replica never started the SSE body) — exactly the
                    # non-streamed retry/relay protocol.
                    payload = resp.read()
                    hdrs = {}
                    for h in (obs_tracing.TRACE_ID_HEADER,
                              "Retry-After"):
                        v = resp.getheader(h)
                        if v is not None:
                            hdrs[h] = v
                    conn.close()
                    metrics.proxy_latency.observe(time.monotonic() - t0)
                    if rec is not None:
                        rec.finish(att_sid, status=f"http:{status}")
                    if status in RETRYABLE_STATUS:
                        last = (status, payload, hdrs)
                        try:
                            absorb(dispatched,
                                   json.loads(payload).get("resume"),
                                   rid=rep.endpoint.rid,
                                   source="descriptor")
                        except (json.JSONDecodeError, AttributeError):
                            pass
                        track_root(attempts)
                        reason = carry_reason()
                        if reason is not None:
                            finish_from_frontier(reason, attempts)
                            return
                        if deadline_expired():
                            break
                        continue
                    if not headers_sent:
                        hdrs.setdefault(obs_tracing.TRACE_ID_HEADER,
                                        trace_id)
                        hdrs["X-Router-Replica"] = rep.endpoint.rid
                        hdrs["X-Router-Attempts"] = str(attempts)
                        self._relay(status, payload, hdrs)
                        return
                    # Mid-stream continuation met a non-retryable
                    # answer (e.g. the remaining deadline lapsed into
                    # a 504): surface it in-band and end the stream.
                    try:
                        obj = json.loads(payload)
                    except json.JSONDecodeError:
                        obj = {}
                    emit("error", {
                        "type": obj.get("type", f"http_{status}"),
                        "error": obj.get("error",
                                         f"replica answered {status}"),
                        "trace_id": trace_id})
                    finish_chunks()
                    return
                # 200 text/event-stream: forward it.
                if attempts > 1:
                    metrics.failovers.inc()
                metrics.proxy_latency.observe(time.monotonic() - t0)
                send_headers(rep.endpoint.rid, attempts)
                catch_up()
                parser = sse.SSEParser()
                outcome = None  # "done" | "error" | ("failover", desc)
                try:
                    while outcome is None:
                        # read1, not read: read(n) BLOCKS until n bytes
                        # accumulate, which would buffer the live
                        # stream into one burst — read1 returns as
                        # soon as the current chunk has data, so each
                        # token event forwards the moment it lands.
                        data = resp.read1(4096)
                        if not data:
                            break  # EOF before a terminal event
                        for kind, ev in parser.feed(data):
                            if kind == "token" and "token" in ev:
                                tok = int(ev["token"])
                                frontier.append(tok)
                                # text pieces survive only unresumed
                                # streams: a continuation replica only
                                # detokenized its own share, and a
                                # spliced text stream would lie.
                                emit_token(tok,
                                           None if dispatched
                                           else ev.get("text"))
                            elif kind == "done":
                                out = dict(ev)
                                out["tokens"] = dispatched + [
                                    int(t)
                                    for t in (ev.get("tokens") or [])]
                                out.setdefault("trace_id", trace_id)
                                if dispatched:
                                    out.pop("text", None)
                                    out["resumed"] = True
                                    out["resume_carried_tokens"] = \
                                        len(dispatched)
                                    metrics.resume_failovers.inc()
                                emit("done", out)
                                outcome = "done"
                                break
                            elif kind == "error":
                                if (ev.get("type") == "engine_failed"
                                        and resumable
                                        and attempts
                                        < router.max_attempts):
                                    # The replica's engine died under
                                    # the stream and said so, resume
                                    # descriptor attached: fail over.
                                    outcome = ("failover",
                                               ev.get("resume"))
                                else:
                                    out = dict(ev)
                                    out.setdefault("trace_id", trace_id)
                                    emit("error", out)
                                    outcome = "error"
                                break
                except (OSError, socket.timeout,
                        http.client.HTTPException):
                    outcome = None  # connection death mid-stream
                finally:
                    conn.close()
                if outcome in ("done", "error"):
                    if rec is not None:
                        rec.finish(att_sid, status=f"sse:{outcome}")
                    finish_chunks()
                    return
                failed_over = True
                if isinstance(outcome, tuple):
                    if rec is not None:
                        rec.finish(att_sid, status="sse:engine_failed")
                    absorb(dispatched, outcome[1],
                           rid=rep.endpoint.rid, source="descriptor")
                else:
                    registry.mark_failed(rep.endpoint.rid)
                    if rec is not None:
                        rec.finish(att_sid, status="error:connection")
                        rec.event(trace_id, root_sid, "failover",
                                  {"replica": rep.endpoint.rid,
                                   "attempt": attempts})
                    absorb(dispatched,
                           router.lookup_resume(rep.endpoint, trace_id),
                           rid=rep.endpoint.rid, source="journal")
                track_root(attempts)
                if not resumable and sent:
                    # The client already has token events and the body
                    # cannot express a continuation: a retry would
                    # re-emit from scratch (duplicates on the wire).
                    # End the stream with a terminal error instead.
                    emit("error", {
                        "type": "stream_interrupted",
                        "error": "replica died mid-stream and the "
                                 "request body is not resumable (a "
                                 "token-list prompt and integer "
                                 "max_new_tokens are required)",
                        "trace_id": trace_id, "attempts": attempts})
                    finish_chunks()
                    return
                reason = carry_reason()
                if reason is not None:
                    finish_from_frontier(reason, attempts)
                    return
                if deadline_expired():
                    break

            track_root(attempts)
            if deadline_expired():
                if headers_sent:
                    catch_up()
                    emit("error", {
                        "type": "deadline_exceeded",
                        "error": "deadline expired during failover",
                        "tokens_so_far": list(frontier),
                        "trace_id": trace_id, "attempts": attempts})
                    finish_chunks()
                else:
                    self._json(504, {
                        "error": "deadline expired during failover",
                        "type": "deadline_exceeded",
                        "trace_id": trace_id, "attempts": attempts,
                        "tokens_so_far": frontier,
                    }, headers={obs_tracing.TRACE_ID_HEADER: trace_id,
                                "X-Router-Attempts": str(attempts)})
                return
            metrics.requests_failed.inc()
            if headers_sent:
                # Out of options with the stream already open: one
                # terminal in-band error, full-frontier resume
                # descriptor attached (a stacked front tier can
                # continue from it).
                catch_up()
                err = {"type": "no_replicas",
                       "error": "no replica reachable after "
                                f"{attempts} attempt(s)",
                       "trace_id": trace_id, "attempts": attempts}
                if frontier:
                    err["resume"] = {
                        "emitted_tokens": list(frontier),
                        "deadline_remaining_ms": current_remaining_ms(),
                        "span_id": carried_from}
                emit("error", err)
                finish_chunks()
                return
            if last is not None:
                status, payload, hdrs = last
                if frontier:
                    try:
                        obj = json.loads(payload)
                        obj["resume"] = {
                            "emitted_tokens": list(frontier),
                            "deadline_remaining_ms":
                                current_remaining_ms(),
                            "span_id": carried_from}
                        payload = json.dumps(obj).encode()
                    except (json.JSONDecodeError, AttributeError):
                        pass
                hdrs.setdefault(obs_tracing.TRACE_ID_HEADER, trace_id)
                hdrs.setdefault("Retry-After", str(router.retry_after))
                hdrs["X-Router-Attempts"] = str(attempts)
                self._relay(status, payload, hdrs)
                return
            self._json(503, {
                "error": "no replica in rotation"
                         if not attempts else
                         f"no replica reachable after {attempts} "
                         f"attempt(s)",
                "type": "no_replicas",
                "trace_id": trace_id, "attempts": attempts,
            }, headers={"Retry-After": str(router.retry_after),
                        obs_tracing.TRACE_ID_HEADER: trace_id})
        except _ClientGone:
            # The CLIENT hung up mid-stream: the per-attempt finally
            # already closed the upstream leg, which cancels the
            # request on the replica (its own disconnect handling) —
            # nothing more to send, just give the socket back.
            self.close_connection = True

    @staticmethod
    def _merge_resumed(payload: bytes, carried: list, metrics) -> bytes:
        """Prepend the carried tokens to a successful continuation's
        payload: the client sees ONE result, byte-identical to an
        uninterrupted run.  ``text`` is dropped — the continuation
        replica detokenized only its own share, and token ids are the
        authoritative cross-replica representation."""
        try:
            obj = json.loads(payload)
            obj["tokens"] = list(carried) + list(obj.get("tokens") or [])
            obj.pop("text", None)
            obj["resumed"] = True
            obj["resume_carried_tokens"] = len(carried)
            metrics.resume_failovers.inc()
            return json.dumps(obj).encode()
        except (json.JSONDecodeError, AttributeError, TypeError):
            return payload  # pragma: no cover - malformed replica reply

    def _relay(self, status: int, payload: bytes,
               headers: Dict[str, str]) -> None:
        self._sent_code = status  # the root span's status (do_POST)
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        for k, v in headers.items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


class RouterServer:
    """Own the router HTTP listener (and optionally the registry poll
    thread) lifecycle.

    >>> rt = RouterServer(registry, port=0).start()
    >>> rt.address                       # ("127.0.0.1", 43117)
    >>> rt.stop()

    ``max_attempts`` caps placement tries per request;
    ``retry_backoff`` / ``retry_backoff_max`` shape the exponential
    backoff between them; ``proxy_timeout`` bounds one attempt — set
    it ABOVE the replicas' ``request_timeout`` so a slow-but-correct
    replica is never double-generated, and the timeout only fires for
    replicas that genuinely wedged.  ``retry_after`` is the seconds
    hint on 503s (load shedding guidance for well-behaved clients).

    ``resume_lookup`` is the post-mortem resume source for
    connection-level deaths: ``(rid, trace_id) -> resume descriptor or
    None`` (``ReplicaSupervisor.resume_lookup`` reads the dead
    replica's journal file, surviving the reap).  When None, the
    router falls back to the endpoint's advertised ``journal_path``
    (still registered until the supervisor reaps it).

    ``span_dir`` arms ``GET /trace/<id>``: the spans directory every
    process of this deployment appends its span stream to
    (``ReplicaSupervisor(span_dir=...)`` for the replicas, plus the
    router's own ``obs.tracing.start_spans(<span_dir>/router...)``);
    each autopsy re-reads the streams — cold by design, this is a
    postmortem endpoint, not a hot path.
    """

    def __init__(self, registry: ReplicaRegistry, *,
                 host: str = "127.0.0.1", port: int = 8080,
                 max_attempts: int = 3,
                 retry_backoff: float = 0.05,
                 retry_backoff_max: float = 1.0,
                 proxy_timeout: float = 150.0,
                 retry_after: int = 1,
                 resume_lookup=None,
                 span_dir: Optional[str] = None,
                 rollout=None,
                 own_registry_thread: bool = True) -> None:
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.resume_lookup = resume_lookup
        self.span_dir = span_dir
        #: RolloutController wired behind POST/GET /rollout (None =
        #: the admin surface answers a typed 503).
        self.rollout = rollout
        self.registry = registry
        self.host = host
        self.port = port
        self.max_attempts = max_attempts
        self.retry_backoff = retry_backoff
        self.retry_backoff_max = retry_backoff_max
        self.proxy_timeout = proxy_timeout
        self.retry_after = retry_after
        self._own_registry_thread = own_registry_thread
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self):
        """(host, port) actually bound (resolves port=0)."""
        if self._httpd is None:
            return (self.host, self.port)
        return self._httpd.server_address[:2]

    def lookup_resume(self, endpoint, trace_id: str) -> Optional[Dict]:
        """Resume descriptor for ``trace_id`` on a replica that died at
        the connection level, or None (→ re-execute from scratch, the
        pre-journal behavior).  Never raises: resume is an
        optimization, failover correctness does not depend on it."""
        try:
            if self.resume_lookup is not None:
                return self.resume_lookup(endpoint.rid, trace_id)
            if endpoint.journal_path:
                return RequestJournal.read_live(
                    endpoint.journal_path).get(trace_id)
        except Exception:  # pragma: no cover - post-mortem best effort
            return None
        return None

    def autopsy(self, trace_id: str) -> Optional[Dict]:
        """Assemble the cross-process span tree for ``trace_id`` from
        ``span_dir``; None when the id is unknown or no span_dir is
        configured.  Collector failures PROPAGATE (the HTTP handler
        maps them to a typed 500 ``span_store_error``) — malformed
        individual records/files are already skipped inside
        :class:`~horovod_tpu.obs.trace_store.TraceStore`, so an
        exception here means the store itself is broken and must not
        masquerade as a missing trace."""
        if self.span_dir is None:
            return None
        from horovod_tpu.obs.trace_store import TraceStore

        store = TraceStore.from_dir(self.span_dir)
        if not store.n_readable:
            # Wrong/moved directory or every stream unreadable: "store
            # is broken", not "trace never recorded" — surface the 500.
            raise FileNotFoundError(
                f"no readable span streams under {self.span_dir}")
        return store.autopsy(trace_id)

    def stats(self) -> Dict:
        out = {
            **self.registry.metrics.snapshot(),
            "policy": "join-shortest-queue",
            "max_attempts": self.max_attempts,
            "in_rotation": sorted(
                s.endpoint.rid for s in self.registry.in_rotation()),
            "replicas": {s.endpoint.rid: s.as_dict()
                         for s in self.registry.statuses()},
        }
        if self.rollout is not None:
            out["rollout"] = self.rollout.status()
        return out

    def start(self) -> "RouterServer":
        if self._httpd is not None:
            return self
        if self._own_registry_thread:
            self.registry.start()
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          _RouterHandler)
        self._httpd.daemon_threads = True
        self._httpd.router = self
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="router-http",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        if self._own_registry_thread:
            self.registry.stop()

    def __enter__(self) -> "RouterServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
