"""One serving replica: a full engine + HTTP server, as a process.

This is what :class:`~horovod_tpu.serving.router.supervisor.
ReplicaSupervisor` spawns N of — the serving analogue of an elastic
training rank.  The model comes from either ``--params`` (a pickle
written by :func:`dump_model`, e.g. the LM ``examples/serve.py``
trains) or deterministic seeded init (``--seed`` + shape flags): every
replica built from the same seed/params serves byte-identical greedy
output, which is what makes router failover invisible to clients.

Lifecycle contract with the supervisor:

* SIGTERM / SIGINT → graceful drain (``ServingServer.stop``: /healthz
  goes 503, admitted requests finish within ``--drain-timeout``), then
  exit 0;
* the engine going terminally ``failed`` (restart budget exhausted,
  terminated) → drain whatever the teardown can still resolve and
  exit :data:`~horovod_tpu.serving.router.supervisor.
  EXIT_CODE_REPLICA_FAILED` so the exit watcher respawns without
  waiting for a registry poll;
* ``--journal PATH`` arms the engine's request journal as an
  append-only JSONL file (the supervisor passes a per-generation path
  from its ``journal_dir``): it survives SIGKILL, and the router reads
  it post-mortem to RESUME this replica's in-flight requests on a
  survivor (docs/serving.md "Front tier").  ``--no-resume`` restores
  the pre-journal fail-typed restart behavior;
* ``--fault site:kind[:skip[:delay]]`` threads a deterministic
  FaultInjector through the engine for chaos tests (a ``hang`` with a
  long delay and ``--tick-timeout 0`` wedges the replica for real —
  the stale-heartbeat eviction + supervisor-drain path).

Run one by hand:

    python -m horovod_tpu.serving.router.replica_main --port 8001 \\
        --seed 0 --warm 8
"""

from __future__ import annotations

import argparse
import os
import pickle
import signal
import sys
import threading


def dump_model(path: str, params, cfg) -> None:
    """Write a trained model where ``--params`` can load it: params as
    host numpy arrays plus the TransformerConfig fields (dtype by
    name, so the pickle is jax-version-proof)."""
    import dataclasses

    import jax
    import numpy as np

    cfg_dict = dataclasses.asdict(cfg)
    cfg_dict["dtype"] = np.dtype(cfg.dtype).name
    with open(path, "wb") as f:
        pickle.dump({
            "params": jax.tree_util.tree_map(np.asarray, params),
            "cfg": cfg_dict,
        }, f)


def load_model(path: str):
    import jax.numpy as jnp

    from horovod_tpu.models import transformer as T

    with open(path, "rb") as f:
        blob = pickle.load(f)
    cfg_dict = dict(blob["cfg"])
    cfg_dict["dtype"] = getattr(jnp, cfg_dict["dtype"])
    return blob["params"], T.TransformerConfig(**cfg_dict)


def build_model(args):
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model,
        n_heads=args.n_heads, n_layers=args.n_layers, d_ff=args.d_ff,
        max_seq=args.max_seq, dtype=jnp.float32,
        attention_impl="reference", n_kv_heads=args.kv_heads)
    return T.init_params(jax.random.PRNGKey(args.seed), cfg), cfg


def parse_setting(text: str):
    """``name=value`` -> (name, typed value), same typing ladder as the
    replay CLI's settings (int → float → bool/none → str)."""
    if "=" not in text:
        raise argparse.ArgumentTypeError(
            f"--set wants name=value, got {text!r}")
    name, raw = text.split("=", 1)
    name = name.strip()
    raw = raw.strip()
    for cast in (int, float):
        try:
            return name, cast(raw)
        except ValueError:
            pass
    low = raw.lower()
    if low in ("true", "false"):
        return name, low == "true"
    if low in ("none", "null"):
        return name, None
    return name, raw


def parse_fault(text: str):
    """``site:kind[:skip[:delay]]`` -> FaultSpec."""
    from horovod_tpu.serving.faults import FaultSpec

    parts = text.split(":")
    if len(parts) < 2:
        raise argparse.ArgumentTypeError(
            f"--fault wants site:kind[:skip[:delay]], got {text!r}")
    spec = {"site": parts[0], "kind": parts[1]}
    if len(parts) > 2:
        spec["skip"] = int(parts[2])
    if len(parts) > 3:
        spec["delay"] = float(parts[3])
    return FaultSpec(**spec)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="one supervised serving replica")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--params", default="",
                    help="pickle from dump_model() (overrides the "
                         "seeded-init shape flags)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--d-ff", type=int, default=64)
    ap.add_argument("--max-seq", type=int, default=48)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree: shard this replica's "
                         "engine over a tp-device GSPMD mesh (heads + "
                         "MLP hidden split, paged KV pool head-"
                         "sharded; docs/serving.md 'Tensor-parallel "
                         "replicas').  Needs tp visible devices — on "
                         "CPU hosts the forced-host-device flag is "
                         "armed automatically when absent")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-queue-depth", type=int, default=64)
    ap.add_argument("--max-prefills-per-tick", type=int, default=2)
    ap.add_argument("--tick-timeout", type=float, default=60.0,
                    help="engine watchdog budget (0 disables)")
    ap.add_argument("--request-timeout", type=float, default=120.0)
    ap.add_argument("--drain-timeout", type=float, default=10.0)
    ap.add_argument("--journal", default="",
                    help="request-journal JSONL path (survives SIGKILL; "
                         "the router reads it post-mortem to resume "
                         "this replica's in-flight requests elsewhere)")
    ap.add_argument("--spans", default="",
                    help="span-stream JSONL path (distributed tracing; "
                         "the process label is the filename stem, e.g. "
                         "r0g1.spans.jsonl -> r0g1).  Flushed per "
                         "record, so a SIGKILL leaves the started "
                         "spans for the router's /trace autopsy")
    ap.add_argument("--span-latency-threshold", type=float, default=1.0,
                    help="tail-sampling latency threshold in seconds: "
                         "requests slower than this keep full tick-"
                         "level span detail")
    ap.add_argument("--span-head-rate", type=float, default=0.0,
                    help="deterministic head-sampling rate [0,1] for "
                         "full span detail on otherwise-boring requests")
    ap.add_argument("--no-resume", action="store_true",
                    help="disable in-engine restart-resume (in-flight "
                         "requests fail typed on a supervised restart, "
                         "the pre-journal behavior)")
    ap.add_argument("--warm", type=int, action="append", default=[],
                    help="prompt lengths to pre-compile before "
                         "accepting traffic (repeatable)")
    ap.add_argument("--autotune", action="store_true",
                    help="install the online autotuner after warmup "
                         "(GET /tuning exposes its state; needs "
                         "--warm so a warmed knob space exists — "
                         "docs/serving.md 'Autotuning')")
    ap.add_argument("--fault", type=parse_fault, action="append",
                    default=[], metavar="SITE:KIND[:SKIP[:DELAY]]",
                    help="deterministic FaultInjector spec (chaos "
                         "tests; repeatable)")
    ap.add_argument("--config-gen", type=int, default=0,
                    help="config-generation label stamped into the "
                         "engine's /stats (fleet rollouts; never read "
                         "by the engine itself)")
    ap.add_argument("--set", type=parse_setting, action="append",
                    default=[], dest="settings", metavar="NAME=VALUE",
                    help="extra EngineConfig field override, typed "
                         "like the replay CLI's settings (repeatable; "
                         "how a rollout candidate carries knobs with "
                         "no dedicated flag)")
    args = ap.parse_args(argv)

    if args.tp > 1:
        # Devices must exist BEFORE the backend spins up.  The
        # supervisor already sets the flag in every tp replica's
        # spawn env (the reliable path); this covers bare
        # `python -m ... --tp N` runs on CPU hosts.
        from horovod_tpu.serving.sharding import ensure_devices

        ensure_devices(args.tp)

    from horovod_tpu import serving
    from horovod_tpu.serving.router.supervisor import (
        EXIT_CODE_REPLICA_FAILED,
    )

    if args.spans:
        from horovod_tpu.obs import tracing as obs_tracing

        stem = os.path.basename(args.spans).split(".")[0]
        obs_tracing.start_spans(
            args.spans, proc=stem or f"pid{os.getpid()}",
            role="replica",
            sampling=obs_tracing.SpanSampling(
                latency_threshold_s=args.span_latency_threshold,
                head_rate=args.span_head_rate))

    if args.params:
        params, cfg = load_model(args.params)
    else:
        params, cfg = build_model(args)

    # Armed EMPTY here; the specs are added AFTER warmup so their
    # skips are post-warmup relative (below) — a spec present during
    # warmup could fire inside it and burn its budget (or wedge the
    # replica) before the listener even exists.
    inj = serving.FaultInjector() if args.fault else None
    cfg_kwargs = dict(
        n_slots=args.slots, max_len=cfg.max_seq,
        max_queue_depth=args.max_queue_depth,
        max_prefills_per_tick=args.max_prefills_per_tick,
        tick_timeout=args.tick_timeout,
        tp=args.tp,
        autotune=args.autotune,
        resume=not args.no_resume,
        journal_path=args.journal or None, faults=inj,
        config_generation=args.config_gen)
    # --set overrides land LAST so a rollout candidate can retarget any
    # EngineConfig field, dedicated flag or not.
    cfg_kwargs.update(dict(args.settings))
    engine = serving.InferenceEngine(
        params, cfg, serving.EngineConfig(**cfg_kwargs))
    if args.warm or args.autotune:
        # Pre-compile BEFORE the listener exists: the registry's first
        # successful poll means "routable", and a routable replica must
        # never pay XLA compilation inside a request (or a tight
        # watchdog budget).  --autotune without --warm still warms the
        # default length: the tuner installs at the END of warmup and
        # derives its compile-safe knob bounds from what it compiled.
        engine.warmup(sorted(set(args.warm)) or [1])
    if inj is not None:
        # --fault skips count from AFTER warmup (the post-warm
        # relative idiom from tests/test_chaos.py): how many probe
        # visits warmup itself spends is a pipeline internal no chaos
        # test should have to predict.
        for spec in args.fault:
            spec.skip += inj.visits(spec.site)
            inj.add(spec)

    stop_requested = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda signum, frame: stop_requested.set())

    srv = serving.ServingServer(
        engine, host=args.host, port=args.port,
        request_timeout=args.request_timeout).start()
    host, port = srv.address
    print(f"replica ready on {host}:{port} (slots={args.slots}, "
          f"tp={args.tp}, pid={os.getpid()})", flush=True)

    failed = False
    while not stop_requested.is_set():
        if engine.terminal:
            failed = True
            break
        stop_requested.wait(0.2)

    srv.stop(drain_timeout=args.drain_timeout)
    if args.spans:
        from horovod_tpu.obs import tracing as obs_tracing

        obs_tracing.stop_spans()
    print(f"replica on port {port} stopped "
          f"(engine state: {engine.health})", flush=True)
    return EXIT_CODE_REPLICA_FAILED if failed else 0


if __name__ == "__main__":
    sys.exit(main())
