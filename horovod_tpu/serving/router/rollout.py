"""RolloutController: zero-downtime fleet reconfiguration.

PR 17 made candidate configs SCORABLE (``tuning.replay.tune`` over a
journaled trace, the online tuner over a live window); this module
makes them DEPLOYABLE.  The controller composes the front tier's
existing primitives — supervised drain (the SIGTERM → graceful-drain →
``shutdown_grace`` → SIGKILL sequence), journaled resume + router
failover (in-flight requests continue byte-identical on a survivor),
and the tuning objective with its per-class TTFT-p99 guard bands —
into a rolling, canaried, automatically-rolled-back reconfiguration:

``idle → draining → rebuilding → canary → rolling → done``
``                                  ↘ rolling_back → rolled_back``
``(refused / nothing rebuilt yet → aborted)``

One replica at a time (healthy capacity never drops below N−1; a
1-replica fleet is refused without ``allow_capacity_dip``), the
controller:

1. **drains** the slot through :meth:`ReplicaSupervisor.drain_slot`
   (in-flight requests fail over with journal descriptors — zero
   dropped requests, outputs byte-identical to the oracle),
2. **rebuilds** it at the candidate spec (a per-slot override the exit
   watcher respawns into; ``config_gen`` is stamped through
   ``--config-gen`` and echoed by the replica's ``/stats``),
3. admits the FIRST rebuilt replica as a **canary**: the registry
   routes exactly ``canary_weight`` of picks to it (deterministic
   credit accumulator) while the controller diffs every replica's
   ``/stats`` counters over ``canary_windows`` scoring windows and
   scores canary vs. incumbent with :class:`~horovod_tpu.tuning.
   Objective` — any per-class TTFT-p99 past ``slo × (1 + guard_band)``
   trips, as does a canary crash/eviction or (when
   ``min_score_delta`` is set) a score materially below the
   incumbents',
4. **rolls** the remaining slots through the same drain/rebuild step,
5. **promotes** the candidate to the supervisor's base spec.

Any trip — canary SLO breach, canary crash, crash loop past
``crash_budget`` respawns, registry eviction, drain overruning its
budget, an operator :meth:`abort`, or an injected fault at any of the
four ``rollout_*`` sites — triggers **automatic rollback** through the
SAME one-at-a-time machinery: every slot already rebuilt at the
candidate is recycled back to the incumbent spec, and the terminal
state is ``rolled_back``.  The invariant the chaos suite
(tests/test_rollout.py) proves: under faults at every step the fleet
never ends in a mixed config, never drops a request, and always
converges to all-incumbent or all-candidate in bounded time.

Durability: every transition is journaled as append-only JSONL
(``rollout.journal.jsonl`` beside the request journals), so a
SIGKILL'd supervisor process can :meth:`recover` deterministically —
resume FORWARD when the canary had already been promoted (a ``rolling``
state was journaled), roll BACK otherwise — converging the fleet by
comparing each live replica's ``/stats`` config generation against the
target.

Fault sites (``FaultInjector``): ``rollout_drain``,
``rollout_rebuild``, ``rollout_canary``, ``rollout_promote`` — probed
in the CONTROLLER (supervisor process), one per step, so the chaos
suite can fail every step of the machine deterministically.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from horovod_tpu.serving.router.registry import ReplicaRegistry
from horovod_tpu.serving.router.supervisor import (
    ReplicaSpec,
    ReplicaSupervisor,
)
from horovod_tpu.tuning import Objective, WindowStats

logger = logging.getLogger("horovod_tpu")

__all__ = ["RolloutController", "RolloutError"]

#: Every state the machine can be in; terminal ones end the run thread.
STATES = ("idle", "draining", "rebuilding", "canary", "rolling", "done",
          "rolling_back", "rolled_back", "aborted")
TERMINAL_STATES = ("done", "rolled_back", "aborted")


class RolloutError(RuntimeError):
    """A rollout could not be started (already active, bad candidate,
    or a fleet shape the safety rules refuse)."""


class _Trip(Exception):
    """Internal: a trip condition fired — unwind to rollback."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def _parse_buckets(hist: Dict) -> Tuple[List[float], List[int]]:
    """``{"buckets": {"<edge>": n, "+Inf": n}}`` -> (sorted edges,
    per-bucket counts with the overflow bucket last)."""
    overflow = 0
    items: List[Tuple[float, int]] = []
    for key, count in (hist.get("buckets") or {}).items():
        if key == "+Inf":
            overflow = int(count)
        else:
            items.append((float(key), int(count)))
    items.sort()
    return ([e for e, _ in items],
            [c for _, c in items] + [overflow])


def _hist_delta_p99(now: Dict, base: Optional[Dict]) -> Optional[float]:
    """Windowed p99 from two HTTP histogram snapshots (the
    ``{"buckets": ...}`` shape every replica's ``/stats`` serves) — the
    over-the-wire twin of the online tuner's ``_Window._p99`` (same
    rank walk, same upper-edge convention; both snapshots share the
    default bucket edges)."""
    if not isinstance(now, dict):
        return None
    edges, counts = _parse_buckets(now)
    if base is not None:
        _, base_counts = _parse_buckets(base)
        if len(base_counts) == len(counts):
            counts = [a - b for a, b in zip(counts, base_counts)]
    total = sum(counts)
    if total <= 0 or not edges:
        return None
    rank, cum = 0.99 * total, 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank:
            return edges[i] if i < len(edges) else edges[-1]
    return edges[-1]


class _StatsWindow:
    """Baseline of one replica's cumulative ``/stats`` counters; diffs
    into a :class:`WindowStats` the tuning objective can score."""

    def __init__(self, snap: Dict):
        self.tokens = int(snap.get("tokens_generated", 0))
        self.ticks = int(snap.get("decode_ticks", 0))
        self.preempt = int(snap.get("preemptions", 0))
        self.ttft = dict(snap.get("ttft_seconds_by_class") or {})

    def close(self, snap: Dict) -> WindowStats:
        p99 = {}
        for cls, hist in (snap.get("ttft_seconds_by_class") or {}).items():
            v = _hist_delta_p99(hist, self.ttft.get(cls))
            if v is not None:
                p99[cls] = v
        return WindowStats(
            ticks=max(int(snap.get("decode_ticks", 0)) - self.ticks, 0),
            tokens=max(int(snap.get("tokens_generated", 0))
                       - self.tokens, 0),
            preemptions=max(int(snap.get("preemptions", 0))
                            - self.preempt, 0),
            ttft_p99=p99)


def _merge_windows(stats: List[WindowStats]) -> WindowStats:
    """Aggregate the incumbents into one fleet-side window: counters
    sum; per-class p99 takes the WORST replica (the conservative read —
    the canary must not look good merely because one incumbent had a
    quiet window)."""
    p99: Dict[str, float] = {}
    for w in stats:
        for cls, v in w.ttft_p99.items():
            p99[cls] = max(p99.get(cls, 0.0), v)
    return WindowStats(
        ticks=sum(w.ticks for w in stats),
        tokens=sum(w.tokens for w in stats),
        preemptions=sum(w.preemptions for w in stats),
        ttft_p99=p99)


class RolloutController:
    """Drive one rolling fleet reconfiguration at a time.

    Wire it between the supervisor and the router::

        ctl = RolloutController(sup, registry)
        rt = RouterServer(registry, rollout=ctl, ...)
        # POST /rollout {"candidate": {"max_prefills_per_tick": 4}}

    ``candidate`` is a flat dict of config deltas: keys naming
    :class:`ReplicaSpec` fields override the spec, everything else
    becomes an ``engine_knobs`` entry (an EngineConfig field carried as
    ``--set name=value``) — exactly the ``settings`` shape
    ``tuning.replay.tune`` returns in its ``best`` entry, so a tuned
    candidate deploys verbatim.
    """

    def __init__(self, supervisor: ReplicaSupervisor,
                 registry: Optional[ReplicaRegistry] = None, *,
                 objective: Optional[Objective] = None,
                 canary_weight: float = 0.2,
                 canary_windows: int = 2,
                 window_s: float = 1.0,
                 guard_band: float = 0.5,
                 min_score_delta: Optional[float] = None,
                 ready_timeout: float = 120.0,
                 drain_margin: float = 5.0,
                 crash_budget: int = 1,
                 allow_capacity_dip: bool = False,
                 journal_path: Optional[str] = None,
                 faults=None) -> None:
        self.sup = supervisor
        self.registry = registry if registry is not None \
            else supervisor.registry
        self.objective = objective or Objective()
        self.canary_weight = float(canary_weight)
        self.canary_windows = int(canary_windows)
        self.window_s = float(window_s)
        self.guard_band = float(guard_band)
        self.min_score_delta = min_score_delta
        self.ready_timeout = float(ready_timeout)
        self.drain_margin = float(drain_margin)
        self.crash_budget = int(crash_budget)
        self.allow_capacity_dip = bool(allow_capacity_dip)
        self.faults = faults
        if journal_path is None:
            jdir = getattr(supervisor, "_journal_dir", None)
            if jdir:
                journal_path = os.path.join(jdir, "rollout.journal.jsonl")
        self.journal_path = journal_path
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._abort = threading.Event()
        self.state = "idle"
        self.trip_reason: Optional[str] = None
        self._candidate: Dict = {}
        self._candidate_spec: Optional[ReplicaSpec] = None
        self._incumbent_spec: Optional[ReplicaSpec] = None
        self._rebuilt_slots: List[int] = []
        self._step_durations: Dict[str, float] = {}
        self._scores: Dict[str, Optional[float]] = {
            "canary": None, "incumbent": None}

    # -- public surface ----------------------------------------------------

    @property
    def active(self) -> bool:
        return self.state not in ("idle",) + TERMINAL_STATES

    def status(self) -> Dict:
        with self._lock:
            return {
                "state": self.state,
                "active": self.active,
                "candidate": dict(self._candidate),
                "config_generation": (
                    self._candidate_spec.config_gen
                    if self._candidate_spec is not None else None),
                "rebuilt_slots": list(self._rebuilt_slots),
                "trip_reason": self.trip_reason,
                "canary_score": self._scores["canary"],
                "incumbent_score": self._scores["incumbent"],
                "step_durations_s": {
                    k: round(v, 3)
                    for k, v in self._step_durations.items()},
            }

    def start(self, candidate: Dict, *,
              allow_capacity_dip: Optional[bool] = None) -> Dict:
        """Validate and launch a rollout of ``candidate``; returns the
        initial status.  Raises :class:`RolloutError` when one is
        already active or the fleet shape is refused."""
        if callable(self.sup.spec):
            raise RolloutError(
                "rollouts need a ReplicaSpec-based supervisor (callable "
                "command factories carry no config to re-render)")
        if not isinstance(candidate, dict) or not candidate:
            raise RolloutError("candidate must be a non-empty dict of "
                               "config deltas")
        dip_ok = (self.allow_capacity_dip if allow_capacity_dip is None
                  else bool(allow_capacity_dip))
        if self.sup.n_replicas < 2 and not dip_ok:
            raise RolloutError(
                "refusing to roll a 1-replica fleet (the drain step "
                "would take the whole fleet down); pass "
                "allow_capacity_dip to override")
        with self._lock:
            if self.active:
                raise RolloutError(
                    f"a rollout is already {self.state}")
            incumbent = self.sup.spec
            field_names = {f.name for f in dataclasses.fields(ReplicaSpec)}
            field_names -= {"config_gen", "engine_knobs", "extra_args"}
            spec_over = {k: v for k, v in candidate.items()
                         if k in field_names}
            knobs = {k: v for k, v in candidate.items()
                     if k not in field_names}
            self._incumbent_spec = incumbent
            self._candidate_spec = dataclasses.replace(
                incumbent, **spec_over,
                engine_knobs={**dict(incumbent.engine_knobs), **knobs},
                config_gen=incumbent.config_gen + 1)
            self._candidate = dict(candidate)
            self._rebuilt_slots = []
            self._step_durations = {}
            self._scores = {"canary": None, "incumbent": None}
            self.trip_reason = None
            self._abort.clear()
            # Journal the start BEFORE the first state transition so
            # recovery's scan sees every state event under its start.
            self._journal({"e": "start", "candidate": dict(candidate),
                           "config_gen": self._candidate_spec.config_gen,
                           "n_replicas": self.sup.n_replicas})
            self._set_state("draining", locked=True)
        self.registry.metrics.rollouts_started.inc()
        self.registry.metrics.rollout_active.set(1)
        self._instant("rollout_start", {
            "config_gen": self._candidate_spec.config_gen})
        self._thread = threading.Thread(
            target=self._run, name="rollout-controller", daemon=True)
        self._thread.start()
        return self.status()

    def abort(self) -> Dict:
        """Operator abort: trips the machine at its next step boundary
        (in-flight drain steps finish; the rollback recycles whatever
        was already rebuilt)."""
        self._abort.set()
        return self.status()

    def wait(self, timeout: float = 600.0) -> bool:
        """Block until the run thread parks in a terminal state."""
        t = self._thread
        if t is not None:
            t.join(timeout)
        return self.state in ("idle",) + TERMINAL_STATES

    def recover(self) -> Optional[Dict]:
        """Resume or roll back an unfinished rollout after a supervisor
        restart, from the journal alone.

        Deterministic rule: a journaled ``rolling`` state means the
        canary was already scored and promoted — resume FORWARD to
        all-candidate; anything earlier rolls BACK to all-incumbent.
        Either way the fleet converges to a single config generation.
        Returns the status when a recovery was launched, None when the
        journal shows no unfinished rollout."""
        events = self._read_journal()
        pending = None
        saw_rolling = False
        for ev in events:
            if ev.get("e") == "start":
                pending = ev
                saw_rolling = False
            elif ev.get("e") == "state" and ev.get("s") == "rolling":
                saw_rolling = True
            elif ev.get("e") == "end":
                pending = None
        if pending is None:
            return None
        candidate = dict(pending.get("candidate") or {})
        target_gen = int(pending.get("config_gen", 1))
        with self._lock:
            if self.active:
                raise RolloutError("cannot recover while a rollout is "
                                   f"{self.state}")
            incumbent = self.sup.spec
            field_names = {f.name for f in dataclasses.fields(ReplicaSpec)}
            field_names -= {"config_gen", "engine_knobs", "extra_args"}
            spec_over = {k: v for k, v in candidate.items()
                         if k in field_names}
            knobs = {k: v for k, v in candidate.items()
                     if k not in field_names}
            self._incumbent_spec = incumbent
            self._candidate_spec = dataclasses.replace(
                incumbent, **spec_over,
                engine_knobs={**dict(incumbent.engine_knobs), **knobs},
                config_gen=target_gen)
            self._candidate = candidate
            self._rebuilt_slots = []
            self._step_durations = {}
            self.trip_reason = None
            self._abort.clear()
            self._set_state("rolling" if saw_rolling else "rolling_back",
                            locked=True)
        self.registry.metrics.rollout_active.set(1)
        self._journal({"e": "recover",
                       "forward": saw_rolling,
                       "config_gen": target_gen})
        logger.warning(
            "rollout: recovering unfinished rollout to gen %d — %s",
            target_gen, "resuming forward" if saw_rolling
            else "rolling back")
        self._thread = threading.Thread(
            target=self._run_recovery, args=(saw_rolling,),
            name="rollout-recovery", daemon=True)
        self._thread.start()
        return self.status()

    # -- state machine internals -------------------------------------------

    def _set_state(self, state: str, locked: bool = False) -> None:
        assert state in STATES, state
        if locked:
            self.state = state
        else:
            with self._lock:
                self.state = state
        self._journal({"e": "state", "s": state})
        self._instant("rollout_state", {"state": state})

    def _probe(self, site: str) -> None:
        if self.faults is not None:
            self.faults.probe(site)

    def _check_abort(self) -> None:
        if self._abort.is_set():
            raise _Trip("operator_abort")

    def _run(self) -> None:
        t_total = time.monotonic()
        try:
            slots = list(range(self.sup.n_replicas))
            for i, slot in enumerate(slots):
                self._check_abort()
                if i == 0:
                    self._set_state("draining")
                else:
                    self._probe("rollout_promote")
                    self._set_state("rolling")
                self._roll_slot(slot, self._candidate_spec)
                if i == 0:
                    self._canary_phase(slot)
            self._promote()
        except _Trip as trip:
            self._rollback(trip.reason)
        except Exception as e:  # injected faults land here too
            self._rollback(f"{type(e).__name__}: {e}")
        finally:
            self._step_durations["total"] = time.monotonic() - t_total
            self.registry.metrics.rollout_active.set(0)
            self._journal({"e": "end", "state": self.state,
                           "trip": self.trip_reason})

    def _roll_slot(self, slot: int, spec: ReplicaSpec,
                   count_step: bool = True) -> str:
        """Drain one slot and wait for its respawn at ``spec`` to be
        routable; returns the new rid.  Raises :class:`_Trip` on drain
        overrun or a crash loop past ``crash_budget``."""
        t0 = time.monotonic()
        self._probe("rollout_drain")
        self.sup.set_slot_spec(slot, spec)
        if count_step and slot not in self._rebuilt_slots:
            # Recorded the MOMENT the override lands, not after the
            # rebuild completes: from here on any respawn of this slot
            # runs the candidate config, so a trip anywhere past this
            # line must recycle it or the fleet ends mixed.
            self._rebuilt_slots.append(slot)
        old = self.sup.handle(slot)
        old_gen = old.gen if old is not None else -1
        self._journal({"e": "slot", "slot": slot,
                       "target_gen": spec.config_gen,
                       "from_rid": old.rid if old else None})
        if old is not None:
            self.sup.drain_slot(
                slot, reason=f"rollout gen {spec.config_gen}")
        # The drain's worst case is graceful-drain + the supervisor's
        # SIGKILL escalation; past that plus a margin something is
        # genuinely stuck and the rollout must not wait on it.
        drain_budget = (getattr(spec, "drain_timeout", 10.0)
                        + getattr(self.sup, "_shutdown_grace", 5.0)
                        + self.drain_margin)
        deadline = time.monotonic() + drain_budget
        while True:
            h = self.sup.handle(slot)
            if h is not None and h.gen > old_gen:
                break
            if time.monotonic() > deadline:
                raise _Trip(f"drain_timeout slot {slot}")
            time.sleep(0.05)
        self._step_durations[f"drain_slot{slot}"] = time.monotonic() - t0
        t1 = time.monotonic()
        self._probe("rollout_rebuild")
        if self.state == "draining":
            self._set_state("rebuilding")
        base_gen = h.gen
        respawns = 0
        deadline = time.monotonic() + self.ready_timeout
        while True:
            h = self.sup.handle(slot)
            if h is None:
                raise _Trip(f"slot {slot} vanished during rebuild")
            if h.gen > base_gen:
                respawns += h.gen - base_gen
                base_gen = h.gen
                if respawns > self.crash_budget:
                    raise _Trip(
                        f"crash_loop slot {slot} "
                        f"({respawns} respawns during rebuild)")
            if self.registry.is_routable(h.rid):
                break
            if time.monotonic() > deadline:
                raise _Trip(f"rebuild_timeout slot {slot}")
            time.sleep(0.05)
        if count_step:
            self.registry.metrics.rollout_steps.inc()
        self._step_durations[f"rebuild_slot{slot}"] = \
            time.monotonic() - t1
        self._journal({"e": "rebuilt", "slot": slot, "rid": h.rid})
        return h.rid

    def _fetch_stats(self, st) -> Optional[Dict]:
        import urllib.request

        try:
            with urllib.request.urlopen(
                    st.endpoint.base_url + "/stats",
                    timeout=self.registry.poll_timeout) as r:
                return json.loads(r.read())
        except Exception:
            return None

    def _canary_phase(self, slot: int) -> None:
        """Score the first rebuilt replica against the incumbent fleet
        for ``canary_windows`` live windows; trips on SLO breach past
        the guard band, canary crash/eviction, or (when configured) a
        materially worse objective score."""
        t0 = time.monotonic()
        self._probe("rollout_canary")
        self._set_state("canary")
        h = self.sup.handle(slot)
        if h is None:
            raise _Trip("canary vanished before scoring")
        rid = h.rid
        self.registry.set_canary(rid, self.canary_weight)
        try:
            for window in range(self.canary_windows):
                self._check_abort()
                statuses = {s.endpoint.rid: s
                            for s in self.registry.in_rotation()}
                if rid not in statuses:
                    raise _Trip("canary left rotation")
                canary_st = statuses.pop(rid)
                base_snap = self._fetch_stats(canary_st)
                if base_snap is None:
                    raise _Trip("canary unreachable")
                canary_base = _StatsWindow(base_snap)
                inc_base = {}
                for r, s in statuses.items():
                    snap = self._fetch_stats(s)
                    if snap is not None:
                        inc_base[r] = (s, _StatsWindow(snap))
                time.sleep(self.window_s)
                cur = self.sup.handle(slot)
                if cur is None or cur.gen != h.gen:
                    raise _Trip("canary crashed during scoring window")
                end_snap = self._fetch_stats(canary_st)
                if end_snap is None or not self.registry.is_routable(rid):
                    raise _Trip("canary evicted during scoring window")
                cw = canary_base.close(end_snap)
                inc_windows = []
                for r, (s, base) in inc_base.items():
                    snap = self._fetch_stats(s)
                    if snap is not None:
                        inc_windows.append(base.close(snap))
                iw = _merge_windows(inc_windows) if inc_windows else None
                c_score, c_excess = self.objective.score(cw)
                self._scores["canary"] = round(c_score, 6)
                self.registry.metrics.rollout_canary_score.set(c_score)
                i_score = None
                if iw is not None:
                    i_score, _ = self.objective.score(iw)
                    self._scores["incumbent"] = round(i_score, 6)
                    self.registry.metrics.rollout_incumbent_score.set(
                        i_score)
                self._journal({"e": "score", "window": window,
                               "canary": self._scores["canary"],
                               "incumbent": self._scores["incumbent"],
                               "excess": {k: round(v, 4)
                                          for k, v in c_excess.items()}})
                violated = [cls for cls, over in c_excess.items()
                            if over > self.guard_band]
                if violated:
                    raise _Trip(
                        "canary_slo_breach: "
                        + ", ".join(f"{cls} p99 over SLO by "
                                    f"{c_excess[cls]:.0%}"
                                    for cls in violated))
                if (self.min_score_delta is not None
                        and i_score is not None
                        and c_score < i_score - self.min_score_delta):
                    raise _Trip(
                        f"canary_score {c_score:.4f} below incumbent "
                        f"{i_score:.4f} - {self.min_score_delta}")
        finally:
            self.registry.clear_canary()
            self._step_durations["canary"] = time.monotonic() - t0

    def _promote(self) -> None:
        self.sup.set_base_spec(self._candidate_spec)
        self.registry.metrics.rollout_promotions.inc()
        self._set_state("done")
        self._instant("rollout_done", {
            "config_gen": self._candidate_spec.config_gen})
        logger.info(
            "rollout: promoted config gen %d fleet-wide (%d slots)",
            self._candidate_spec.config_gen, self.sup.n_replicas)

    def _rollback(self, reason: str) -> None:
        """Converge every candidate-config slot back to the incumbent
        spec through the same one-at-a-time machinery.  Best-effort but
        bounded: a slot that cannot be recycled within its budgets is
        logged and skipped (the supervisor keeps respawning it at the
        incumbent spec regardless, because the override is cleared)."""
        with self._lock:
            self.trip_reason = reason
        rebuilt = list(self._rebuilt_slots)
        self.registry.clear_canary()
        self.registry.metrics.rollout_rollbacks.inc()
        self._journal({"e": "trip", "reason": reason,
                       "rebuilt_slots": rebuilt})
        self._instant("rollout_trip", {"reason": reason})
        logger.warning("rollout: tripped (%s); rolling back %d slot(s)",
                       reason, len(rebuilt))
        if not rebuilt:
            # Nothing ever reached the candidate config: the fleet is
            # already all-incumbent.
            for slot in range(self.sup.n_replicas):
                self.sup.clear_slot_spec(slot)
            self._set_state("aborted")
            return
        self._set_state("rolling_back")
        t0 = time.monotonic()
        for slot in range(self.sup.n_replicas):
            self.sup.clear_slot_spec(slot)
        for slot in rebuilt:
            try:
                self._roll_slot(slot, self._incumbent_spec,
                                count_step=False)
                self.registry.metrics.rollout_steps.inc()
            except _Trip as trip:
                # Keep converging the rest; the cleared override means
                # ANY future respawn of this slot lands incumbent.
                logger.warning(
                    "rollout: rollback of slot %d overran (%s); its "
                    "override is cleared, the supervisor converges it",
                    slot, trip.reason)
        for slot in rebuilt:
            # The recycle re-set an override (to the incumbent spec,
            # so it is content-identical to the base) — drop it so the
            # supervisor ends with a clean override table.
            self.sup.clear_slot_spec(slot)
        self._rebuilt_slots = []
        self._step_durations["rollback"] = time.monotonic() - t0
        self._set_state("rolled_back")
        self._instant("rollout_rolled_back", {"reason": reason})

    def _run_recovery(self, forward: bool) -> None:
        """Post-restart convergence: recycle every slot whose LIVE
        config generation (per the registry's polled ``/stats`` labels)
        differs from the target — candidate gen when resuming forward,
        incumbent gen on rollback."""
        t_total = time.monotonic()
        target_spec = (self._candidate_spec if forward
                       else self._incumbent_spec)
        try:
            # One fresh poll so config_gen labels reflect live replicas.
            if self.registry._thread is None:
                self.registry.poll_now()
            by_slot: Dict[int, int] = {}
            for st in self.registry.statuses():
                rid = st.endpoint.rid
                try:
                    slot = int(rid[1:rid.index("g")])
                except ValueError:
                    continue
                by_slot[slot] = st.config_gen
            for slot in range(self.sup.n_replicas):
                self._check_abort()
                if forward:
                    self.sup.set_slot_spec(slot, target_spec)
                live_gen = by_slot.get(slot)
                if live_gen == target_spec.config_gen:
                    continue
                self._roll_slot(slot, target_spec, count_step=forward)
                self.registry.metrics.rollout_steps.inc()
            if forward:
                self._promote()
            else:
                for slot in range(self.sup.n_replicas):
                    self.sup.clear_slot_spec(slot)
                self._set_state("rolled_back")
        except _Trip as trip:
            if forward:
                self._rollback(f"recovery: {trip.reason}")
            else:
                with self._lock:
                    self.trip_reason = trip.reason
                self._set_state("rolled_back")
        except Exception as e:  # pragma: no cover - recovery last resort
            with self._lock:
                self.trip_reason = f"{type(e).__name__}: {e}"
            self._set_state("rolled_back" if not forward else "aborted")
        finally:
            self._step_durations["total"] = time.monotonic() - t_total
            self.registry.metrics.rollout_active.set(0)
            self._journal({"e": "end", "state": self.state,
                           "trip": self.trip_reason})

    # -- journal -----------------------------------------------------------

    def _journal(self, event: Dict) -> None:
        if not self.journal_path:
            return
        event = {"t": round(time.time(), 3), **event}
        try:
            os.makedirs(os.path.dirname(self.journal_path) or ".",
                        exist_ok=True)
            with open(self.journal_path, "a") as f:
                f.write(json.dumps(event) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError:  # pragma: no cover - durability best effort
            logger.exception("rollout: journal append failed")

    def _read_journal(self) -> List[Dict]:
        if not self.journal_path:
            return []
        try:
            with open(self.journal_path) as f:
                out = []
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # torn tail write from a SIGKILL
                return out
        except OSError:
            return []

    @staticmethod
    def _instant(name: str, args: Dict) -> None:
        try:
            from horovod_tpu.obs import tracing as obs_tracing

            obs_tracing.instant(name, args)
        except Exception:  # pragma: no cover
            pass
