"""Router observability: the front tier's instrument panel.

Same shape as :class:`~horovod_tpu.serving.metrics.ServingMetrics` —
every instrument lives under a ``router_*`` Prometheus family in a
PRIVATE :class:`~horovod_tpu.obs.registry.MetricsRegistry` (tests and
benchmarks create many routers per process), surfaced verbatim through
the router's ``/stats`` and as text exposition through its
``/metrics``.  Every family is cataloged in docs/observability.md and
linted by ``tests/test_fleet.py::TestMetricsNamingLint``.
"""

from __future__ import annotations

from typing import Dict, Optional

from horovod_tpu.obs.registry import (
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
)

__all__ = ["RouterMetrics"]


class RouterMetrics:
    """The front tier's counters/gauges/histograms.

    * ``requests`` / ``requests_failed`` — proxied ``/generate``
      requests, and the ones the router could NOT place anywhere
      (attempts exhausted or no replica in rotation) — the
      zero-dropped-requests number to alert on.
    * ``retries`` / ``failovers`` — individual retry attempts after a
      replica failed mid-request, and requests that ultimately
      SUCCEEDED only because of a retry (each one is a request a
      single-replica deployment would have dropped).
    * ``resume_failovers`` — failovers that CONTINUED a partially
      decoded request from its resume descriptor (a replica's typed
      engine-failure response, or a SIGKILL'd replica's journal file)
      instead of re-executing from scratch — each one is paid-for
      prefill/decode work the failover preserved.
    * ``replicas_total`` / ``replicas_in_rotation`` — supervised
      replicas vs. replicas the balancer will actually route to;
      ``total - in_rotation`` is the capacity currently draining,
      respawning, or warming.
    * ``replica_evictions`` — times a replica left rotation (poll
      failure, stale heartbeat, failed/draining state, or a proxy
      marking it dead mid-request).
    * ``replica_restarts`` — supervisor respawns (the serving analogue
      of ``elastic_restarts_total``).
    * ``poll_errors`` — registry polls that failed (connection refused
      / timeout / bad payload); a burst of these around an eviction is
      the normal failure signature.
    * ``drain_timeouts`` — drains that blew through ``shutdown_grace``
      and escalated to SIGKILL (supervisor stop or slot recycle); each
      one means in-flight requests failed over through the journal
      instead of finishing locally.
    * ``proxy_latency`` — wall time of one proxy ATTEMPT (connect +
      replica generate + relay), success or failure.
    * ``rollout_*`` — the fleet-reconfiguration state machine
      (docs/serving.md "Fleet rollouts"): rollouts started /
      promoted / rolled back, replica recycle steps executed, whether
      one is active, and the last canary-vs-incumbent window scores.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        r = registry if registry is not None else MetricsRegistry()
        self.registry = r
        self.requests = r.counter(
            "router_requests_total", "Proxied /generate requests")
        self.requests_failed = r.counter(
            "router_requests_failed_total",
            "Requests the router could not place on any replica "
            "(attempts exhausted or rotation empty)")
        self.retries = r.counter(
            "router_retries_total",
            "Retry attempts after a replica failed mid-request")
        self.failovers = r.counter(
            "router_failovers_total",
            "Requests that succeeded only via retry on another replica")
        self.resume_failovers = r.counter(
            "router_resume_failovers_total",
            "Failovers that resumed a partially decoded request from "
            "its resume descriptor instead of re-executing from scratch")
        self.replicas_total = r.gauge(
            "router_replicas_total", "Replicas under supervision")
        self.replicas_in_rotation = r.gauge(
            "router_replicas_in_rotation",
            "Replicas currently eligible for routing")
        self.replica_evictions = r.counter(
            "router_replica_evictions_total",
            "Times a replica left rotation (stale/failed/unreachable)")
        self.replica_restarts = r.counter(
            "router_replica_restarts_total",
            "Replica processes respawned by the supervisor")
        self.poll_errors = r.counter(
            "router_poll_errors_total",
            "Registry health polls that failed")
        self.drain_timeouts = r.counter(
            "router_drain_timeouts_total",
            "Replica drains that exceeded shutdown_grace and were "
            "escalated to SIGKILL")
        self.rollouts_started = r.counter(
            "rollout_started_total",
            "Fleet rollouts accepted by the controller")
        self.rollout_promotions = r.counter(
            "rollout_promotions_total",
            "Rollouts that promoted the candidate config fleet-wide")
        self.rollout_rollbacks = r.counter(
            "rollout_rollbacks_total",
            "Rollouts rolled back to the incumbent config (canary SLO "
            "breach, crash loop, drain timeout, eviction, or operator "
            "abort)")
        self.rollout_steps = r.counter(
            "rollout_steps_total",
            "Replica recycle steps (drain + rebuild of one slot) "
            "executed by the rollout controller, rollback included")
        self.rollout_active = r.gauge(
            "rollout_active",
            "1 while a rollout (or rollback) is in flight, else 0")
        self.rollout_canary_score = r.gauge(
            "rollout_canary_score",
            "Objective score of the canary's last scoring window")
        self.rollout_incumbent_score = r.gauge(
            "rollout_incumbent_score",
            "Objective score of the incumbent fleet over the same "
            "window the canary was scored on")
        self.proxy_latency = r.histogram(
            "router_proxy_latency_seconds",
            "Wall time of one proxy attempt (connect through relay)",
            buckets=DEFAULT_LATENCY_BUCKETS)

    def snapshot(self) -> Dict:
        return {
            "requests": self.requests.value,
            "requests_failed": self.requests_failed.value,
            "retries": self.retries.value,
            "failovers": self.failovers.value,
            "resume_failovers": self.resume_failovers.value,
            "replicas_total": self.replicas_total.value,
            "replicas_in_rotation": self.replicas_in_rotation.value,
            "replica_evictions": self.replica_evictions.value,
            "replica_restarts": self.replica_restarts.value,
            "poll_errors": self.poll_errors.value,
            "drain_timeouts": self.drain_timeouts.value,
            "rollouts_started": self.rollouts_started.value,
            "rollout_promotions": self.rollout_promotions.value,
            "rollout_rollbacks": self.rollout_rollbacks.value,
            "rollout_steps": self.rollout_steps.value,
            "rollout_active": self.rollout_active.value,
            "rollout_canary_score": self.rollout_canary_score.value,
            "rollout_incumbent_score":
                self.rollout_incumbent_score.value,
            "proxy_latency_seconds": self.proxy_latency.snapshot(),
        }
