"""Continuous-batching inference engine.

The paper's core move — a background controller that fuses pending work
from many independent callers into one efficient device operation —
applied to decoding: ONE compiled ``decode_step_slots`` executable stays
hot over a fixed pool of S cache slots, and new requests land in freed
slots between ticks via a bucketed single-request prefill +
``insert_prefill``, with zero recompilation of the decode step (the
live set is data — an ``(S,)`` active mask — not structure).

Tick loop (:meth:`InferenceEngine.step`):

1. **Admit**: drain up to K requests from the scheduler into free slots
   (K = ``max_prefills_per_tick`` bounds the decode stall, so TTFT and
   tok/s are both bounded).  Each admission is a batch-1 prefill padded
   to a power-of-two bucket (one compile per bucket, reused across
   lengths), whose last-real-position logits yield the request's FIRST
   token immediately.
2. **Decode**: one masked ``decode_step_slots`` over all S slots;
   inactive slots compute on zeros (Join-style).  Each active slot's
   next greedy token streams to its future; EOS / max-token / capacity
   retirement frees the slot for the next admission.

Greedy decoding is deliberate: it makes the engine's output
TOKEN-IDENTICAL to per-request ``greedy_decode`` (the correctness oracle
in ``tests/test_serving.py``) regardless of which requests share the
batch or when they were admitted.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.models import transformer as T
from horovod_tpu.serving.cache import SlotCache, init_slot_cache  # noqa: F401
from horovod_tpu.serving.metrics import ServingMetrics
from horovod_tpu.serving.scheduler import (
    QueueFullError,
    Request,
    RequestTooLongError,
    Scheduler,
    ServingError,
)

__all__ = [
    "EngineConfig", "GenerationFuture", "InferenceEngine",
]


class GenerationFuture:
    """Per-request result sink: tokens stream in as the engine emits
    them; :meth:`result` blocks until retirement (or a typed rejection).

    ``on_token(token_id, text_piece)`` fires from the ENGINE thread for
    every emitted token (``text_piece`` is None without a detokenizer) —
    keep it cheap."""

    def __init__(self, on_token: Optional[Callable] = None,
                 detokenize: Optional[Callable[[int], str]] = None):
        self._tokens: List[int] = []
        self._text: List[str] = []
        self._done = threading.Event()
        self._exc: Optional[BaseException] = None
        self._on_token = on_token
        self._detokenize = detokenize
        self.finish_reason: Optional[str] = None
        self.ttft: Optional[float] = None

    # engine-side ----------------------------------------------------------

    def _add_token(self, tok: int) -> None:
        self._tokens.append(tok)
        piece = None
        if self._detokenize is not None:
            piece = self._detokenize(tok)
            self._text.append(piece)
        if self._on_token is not None:
            self._on_token(tok, piece)

    def _finish(self, reason: str) -> None:
        self.finish_reason = reason
        self._done.set()

    def set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._done.set()

    # caller-side ----------------------------------------------------------

    def done(self) -> bool:
        return self._done.is_set()

    def tokens_so_far(self) -> List[int]:
        return list(self._tokens)

    @property
    def text(self) -> str:
        return "".join(self._text)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Generated token ids; raises the typed rejection if the request
        never ran, TimeoutError if it is still running at ``timeout``."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation still in progress")
        if self._exc is not None:
            raise self._exc
        return list(self._tokens)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Continuous-batching knobs (tuning notes: docs/serving.md).

    ``n_slots`` (S) is the decode batch the executable is compiled for;
    ``max_len`` caps prompt + generation per slot (0 = cfg.max_seq);
    ``max_prefills_per_tick`` (K) bounds admissions between decode
    ticks; ``max_queue_depth`` bounds the burst the scheduler absorbs;
    ``min_prefill_bucket`` floors the power-of-two prompt buckets so
    tiny prompts share one compile."""

    n_slots: int = 4
    max_len: int = 0
    max_prefills_per_tick: int = 2
    max_queue_depth: int = 64
    default_max_new_tokens: int = 64
    min_prefill_bucket: int = 8


@dataclasses.dataclass
class _SlotState:
    request: Request
    last_token: int
    n_generated: int


class InferenceEngine:
    """Continuous-batching engine over one model's params + config.

    Drive it synchronously with :meth:`step` (tests, benchmarks) or as a
    background thread with :meth:`start`/:meth:`stop` (the HTTP server).
    ``detokenize`` optionally maps a token id to its text piece for
    streamed detokenization."""

    def __init__(self, params: Dict, cfg: "T.TransformerConfig",
                 engine_cfg: EngineConfig = EngineConfig(), *,
                 detokenize: Optional[Callable[[int], str]] = None):
        self.params = params
        self.cfg = cfg
        self.engine_cfg = engine_cfg
        self.detokenize = detokenize
        self.slots = SlotCache(cfg, engine_cfg.n_slots, engine_cfg.max_len)
        self.scheduler = Scheduler(
            max_queue_depth=engine_cfg.max_queue_depth,
            max_prefills_per_tick=engine_cfg.max_prefills_per_tick)
        self.metrics = ServingMetrics()
        self._states: List[Optional[_SlotState]] = \
            [None] * engine_cfg.n_slots
        self._lock = threading.Lock()  # engine-loop state (step is serial)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

        # Compile-count hook: the traced-function body runs ONLY when jax
        # (re)traces, so this counter IS the number of decode
        # compilations — the acceptance criterion asserts it stays at 1
        # after warmup.
        self._decode_traces = 0

        def _tick(params, tokens, active, cache):
            self._decode_traces += 1
            logits, cache = T.decode_step_slots(
                params, tokens, cache, self.cfg, active)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return jnp.where(active, nxt, 0), cache

        # Donate the cache: without it XLA keeps input AND output caches
        # alive across the tick (2x the KV HBM — half the servable
        # slots) and copies the whole cache every token.
        self._tick_fn = jax.jit(_tick, donate_argnums=(3,))
        self._prefill_fns: Dict[int, Callable] = {}
        self._prefill_traces = 0

    # -- submission --------------------------------------------------------

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               eos_id: Optional[int] = None,
               deadline: Optional[float] = None,
               on_token: Optional[Callable] = None) -> GenerationFuture:
        """Queue a generation request; returns its future.

        Typed rejections: :class:`RequestTooLongError` (prompt +
        max_new_tokens cannot fit a cache slot — raised immediately),
        :class:`QueueFullError` (bounded queue at capacity), and
        :class:`DeadlineExceededError` (set on the FUTURE if
        ``deadline`` — an absolute ``time.monotonic()`` instant — passes
        while queued).  A deadline that lapses AFTER admission retires
        the slot early instead: the future completes with the partial
        result and ``finish_reason == "deadline"``, so abandoned
        requests don't pin slots."""
        prompt = [int(t) for t in prompt]
        n_new = (max_new_tokens if max_new_tokens is not None
                 else self.engine_cfg.default_max_new_tokens)
        if not prompt:
            raise ServingError("empty prompt")
        if n_new < 1:
            raise ServingError(f"max_new_tokens must be >= 1, got {n_new}")
        cap = self.slots.max_len
        # First token comes from prefill logits, so a slot needs room for
        # the prompt plus the n_new - 1 decode-step writes.
        if len(prompt) + n_new - 1 > cap:
            self.metrics.rejected.inc()
            raise RequestTooLongError(
                f"prompt ({len(prompt)}) + max_new_tokens ({n_new}) "
                f"exceeds slot capacity ({cap})")
        fut = GenerationFuture(on_token=on_token,
                               detokenize=self.detokenize)
        req = Request(prompt=prompt, max_new_tokens=n_new, future=fut,
                      eos_id=eos_id, deadline=deadline)
        try:
            self.scheduler.submit(req)
        except QueueFullError:
            self.metrics.rejected.inc()
            raise
        self.metrics.queue_depth.set(self.scheduler.depth)
        return fut

    # -- the tick ----------------------------------------------------------

    def step(self) -> bool:
        """One engine tick: admit up to K requests into free slots, then
        one masked decode over all S slots.  Returns True if any work
        was done (False = idle; callers may sleep)."""
        with self._lock:
            worked = self._admit_pending()
            worked = self._decode_tick() or worked
            self.metrics.queue_depth.set(self.scheduler.depth)
            self.metrics.slot_occupancy.set(self.slots.occupancy)
            return worked

    def _admit_pending(self) -> bool:
        def on_reject(req, err):
            self.metrics.rejected.inc()

        reqs = self.scheduler.take(self.slots.free_count,
                                   on_reject=on_reject)
        for req in reqs:
            slot = self.slots.alloc()
            assert slot is not None  # take() is bounded by free_count
            self._admit(slot, req)
        return bool(reqs)

    def _prefill_fn(self, bucket: int) -> Callable:
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            def _prefill(params, padded, true_len):
                self._prefill_traces += 1
                cache = T.init_cache(self.cfg, 1, bucket)
                return T.prefill(params, padded, cache, self.cfg,
                                 true_len=true_len)

            fn = jax.jit(_prefill)
            self._prefill_fns[bucket] = fn
        return fn

    def _bucket(self, n: int) -> int:
        b = max(self.engine_cfg.min_prefill_bucket, 1)
        while b < n:
            b *= 2
        return min(b, self.slots.max_len)

    def _admit(self, slot: int, req: Request) -> None:
        """Batch-1 bucketed prefill -> insert into the slot -> emit the
        request's first token (prefill logits ARE the first greedy
        step)."""
        s0 = len(req.prompt)
        bucket = self._bucket(s0)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :s0] = req.prompt
        logits, pre_cache = self._prefill_fn(bucket)(
            self.params, jnp.asarray(padded), s0)
        self.slots.insert(slot, pre_cache)
        first = int(np.asarray(jnp.argmax(logits[0])))
        now = time.monotonic()
        ttft = now - req.submitted_at
        req.future.ttft = ttft
        self.metrics.ttft.observe(ttft)
        self.metrics.admitted.inc()
        self._states[slot] = _SlotState(request=req, last_token=first,
                                        n_generated=0)
        self._emit(slot, first)

    def _emit(self, slot: int, tok: int) -> None:
        """Stream one token to the slot's future; retire on EOS,
        max-token, or cache-capacity exhaustion."""
        st = self._states[slot]
        st.request.future._add_token(tok)
        st.last_token = tok
        st.n_generated += 1
        self.metrics.tokens_generated.inc()
        reason = None
        if st.request.eos_id is not None and tok == st.request.eos_id:
            reason = "eos"
        elif st.n_generated >= st.request.max_new_tokens:
            reason = "length"
        # Next decode tick would write at prompt + n_generated - 1 (the
        # first token came from prefill, no write) — retire at capacity.
        elif (len(st.request.prompt) + st.n_generated - 1
              >= self.slots.max_len):
            reason = "capacity"  # submit() sizing makes this unreachable
        # Deadline AFTER admission: the caller is gone (504/timeout) —
        # retire with the partial result instead of pinning the slot
        # until max_new_tokens on output nobody reads.  (A deadline that
        # lapses while QUEUED is a typed rejection — Scheduler.take.)
        elif (st.request.deadline is not None
              and time.monotonic() > st.request.deadline):
            reason = "deadline"
        if reason is not None:
            st.request.future._finish(reason)
            self.metrics.completed.inc()
            self._states[slot] = None
            self.slots.free(slot)

    def _decode_tick(self) -> bool:
        active = self.slots.active_mask()
        if not active.any():
            return False
        tokens = np.zeros(self.engine_cfg.n_slots, np.int32)
        for s, st in enumerate(self._states):
            if st is not None:
                tokens[s] = st.last_token
        t0 = time.monotonic()
        nxt, self.slots.cache = self._tick_fn(
            self.params, jnp.asarray(tokens), jnp.asarray(active),
            self.slots.cache)
        nxt = np.asarray(nxt)  # fetch = sync: the tick really finished
        dt = time.monotonic() - t0
        for s in np.nonzero(active)[0]:
            self.metrics.token_latency.observe(dt)
            self._emit(int(s), int(nxt[s]))
        return True

    # -- background loop ---------------------------------------------------

    def start(self, idle_sleep: float = 0.001) -> None:
        """Run the tick loop in a daemon thread until :meth:`stop`."""
        if self._thread is not None:
            return

        def loop():
            while not self._stop.is_set():
                if not self.step():
                    time.sleep(idle_sleep)

        self._stop.clear()
        self._thread = threading.Thread(target=loop,
                                        name="serving-engine", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout)
        self._thread = None

    def drain(self, timeout: float = 60.0, poll: float = 0.002) -> bool:
        """Block until queue and slots are empty (True) or timeout.
        Synchronous callers (no background thread) should loop
        :meth:`step` instead."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            # Sample under the step lock: between scheduler.take() and
            # slots.alloc() a request is in neither counter, and an
            # unlocked read could report "drained" mid-admission.
            with self._lock:
                idle = (self.scheduler.depth == 0
                        and self.slots.active_count == 0)
            if idle:
                return True
            if self._thread is None:
                self.step()
            else:
                time.sleep(poll)
        return False

    # -- observability -----------------------------------------------------

    @property
    def decode_compilations(self) -> int:
        """How many times the decode tick was traced/compiled — the
        zero-recompilation acceptance hook (stays 1 after warmup)."""
        return self._decode_traces

    def stats(self) -> Dict:
        return {
            **self.metrics.snapshot(),
            "n_slots": self.engine_cfg.n_slots,
            "slots_active": self.slots.active_count,
            "max_len": self.slots.max_len,
            "decode_compilations": self._decode_traces,
            "prefill_compilations": self._prefill_traces,
            "prefill_buckets": sorted(self._prefill_fns),
        }
