"""Continuous-batching inference engine.

The paper's core move — a background controller that fuses pending work
from many independent callers into one efficient device operation —
applied to decoding: ONE compiled ``decode_step_slots`` executable stays
hot over a fixed pool of S cache slots, and new requests land in freed
slots between ticks via a bucketed single-request prefill +
``insert_prefill``, with zero recompilation of the decode step (the
live set is data — an ``(S,)`` active mask — not structure).

Tick loop (:meth:`InferenceEngine.step`):

1. **Admit**: drain up to K requests from the scheduler into free slots
   (K = ``max_prefills_per_tick`` bounds the decode stall, so TTFT and
   tok/s are both bounded).  The whole group is admitted by ONE
   bucketed batch-K prefill (prompts right-padded to a shared
   power-of-two bucket, per-row ``true_len``; compile set bounded by
   buckets x K), whose last-real-position logits yield each request's
   FIRST token immediately.
2. **Decode**: one masked ``decode_step_slots`` over all S slots;
   inactive slots compute on zeros (Join-style).  Each active slot's
   next greedy token streams to its future; EOS / max-token / capacity
   retirement frees the slot for the next admission.

With ``EngineConfig.overlap`` (the default) the decode half runs as a
TWO-STAGE PIPELINE — the paper's latency-hiding move (overlap the
expensive device work with the host work that feeds it) applied to the
token loop.  ``tokens``/``active`` live on the device: tick N's output
token vector feeds tick N+1's dispatch directly (JAX async dispatch —
no host round-trip, no re-upload), and the host-side fetch + emission +
retirement bookkeeping for tick N runs while the device is already
computing tick N+1.  Retirement therefore lands with ONE TICK of lag;
a per-dispatch identity snapshot keeps the lag invisible (a slot's
token is emitted only if the slot still holds the request it was
computing for — no token after EOS, no stale row leaking into a
reused slot; see :meth:`_retire_pending`), so greedy output stays
token-identical to the synchronous path (``overlap=False``, the A/B
baseline one flag away) and to per-request ``greedy_decode``.

Greedy decoding is deliberate: it makes the engine's output
TOKEN-IDENTICAL to per-request ``greedy_decode`` (the correctness oracle
in ``tests/test_serving.py``) regardless of which requests share the
batch or when they were admitted.

Fault tolerance (docs/serving.md "Operations"; the runtime analogue of
the training side's typed rank-failure surfacing + ``Join`` + elastic
supervision):

* **Supervised tick loop with DURABLE requests** — any exception out
  of :meth:`step` triggers a supervised restart: fresh
  :class:`SlotCache` (the device cache is suspect after a failure),
  bounded consecutive attempts with exponential backoff,
  ``engine_restarts`` counter.  With ``EngineConfig.resume`` (the
  default) in-flight requests SURVIVE the restart: their decode state
  is journaled (:class:`~horovod_tpu.serving.journal.RequestJournal`
  — original prompt, params, tokens emitted so far), and ``_restart``
  re-admits each by prefilling ``prompt + emitted`` and continuing
  decode with the ORIGINAL future still live — concatenated output
  token-identical to an uninterrupted run, wasted work bounded by one
  tick plus one re-prefill.  ``resume=False`` restores the old
  fail-typed behavior
  (:class:`~horovod_tpu.serving.scheduler.EngineFailedError` on every
  in-flight future).  Queued requests survive either way; only when
  the restart budget is exhausted does the engine go terminally
  ``failed`` and resolve everything typed.
* **Watchdog** — :meth:`start` also runs a watchdog thread against a
  per-tick heartbeat; a tick exceeding ``tick_timeout`` is declared
  *stalled* (hung device call).  With ``resume``, in-flight futures
  are HELD through ``stall_grace`` — a tick that returns inside it
  resumes them token-exact — and only past budget + grace does the
  watchdog resolve everything with
  :class:`~horovod_tpu.serving.scheduler.EngineStalledError` (the
  bounded-resolution backstop).  Without ``resume``, in-flight AND
  queued futures resolve immediately at the stall, as before; either
  way a tick that does return restarts through the supervised path.
* **Lifecycle states** — ``healthy`` / ``degraded`` (just restarted) /
  ``draining`` (shutdown in progress, new submits rejected) /
  ``failed`` (restart budget exhausted or stalled), surfaced through
  :attr:`health`, :meth:`stats`, and the server's ``/healthz``.
* **Cancellation** — :meth:`GenerationFuture.cancel` marks a request;
  the engine reclaims its slot (or purges it from the queue) on the
  next tick and resolves the future with ``finish_reason
  "cancelled"`` and the tokens so far.

The one invariant all of this serves: **every submitted request
resolves, in bounded time, with tokens or a typed error** — proven
under deterministic fault injection
(:class:`~horovod_tpu.serving.faults.FaultInjector`, threaded through
:attr:`EngineConfig.faults`) by ``tests/test_chaos.py``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.models import transformer as T
from horovod_tpu.obs import tracing as obs_tracing
from horovod_tpu.serving.cache import (  # noqa: F401
    NULL_PAGE,
    PagedSlotCache,
    SlotCache,
    init_slot_cache,
)
from horovod_tpu.serving.faults import FaultInjector
from horovod_tpu.serving.journal import RequestJournal
from horovod_tpu.serving.metrics import ServingMetrics
from horovod_tpu.serving.sampling import SlotSampling, seed_key
from horovod_tpu.serving.sampling import validate as validate_sampling
from horovod_tpu.serving.scheduler import (
    CacheOutOfPagesError,
    DrainingError,
    EngineFailedError,
    EngineStalledError,
    QueueFullError,
    Request,
    RequestTooLongError,
    Scheduler,
    ServingError,
    priority_rank,
)

__all__ = [
    "EngineConfig", "GenerationFuture", "InferenceEngine",
    "HEALTHY", "DEGRADED", "DRAINING", "FAILED",
]

# Engine lifecycle states (the /healthz vocabulary).  healthy/degraded
# serve traffic (degraded = freshly restarted, not yet proven by a
# clean tick); draining/failed reject new work — load balancers should
# stop routing (non-200 /healthz).
HEALTHY = "healthy"
DEGRADED = "degraded"
DRAINING = "draining"
FAILED = "failed"


class GenerationFuture:
    """Per-request result sink: tokens stream in as the engine emits
    them; :meth:`result` blocks until retirement (or a typed rejection).

    ``on_token(token_id, text_piece)`` fires from the ENGINE thread for
    every emitted token (``text_piece`` is None without a detokenizer) —
    keep it cheap."""

    def __init__(self, on_token: Optional[Callable] = None,
                 detokenize: Optional[Callable[[int], str]] = None):
        self._tokens: List[int] = []
        self._text: List[str] = []
        self._done = threading.Event()
        self._exc: Optional[BaseException] = None
        self._on_token = on_token
        self._detokenize = detokenize
        self._cancel = False
        self._resolve_lock = threading.Lock()
        self.finish_reason: Optional[str] = None
        self.ttft: Optional[float] = None
        # Observability: the request's trace record (stamped by the
        # scheduler/engine as it moves through the stack) and the
        # tracer active at submit time — resolution emits the request
        # span + JSONL line through it, from WHICHEVER thread resolves
        # (engine, watchdog, or HTTP handler).
        self.trace: Optional["obs_tracing.RequestTrace"] = None
        self._tracer: Optional["obs_tracing.Tracer"] = None
        self._spans: Optional["obs_tracing.SpanRecorder"] = None
        # Resolution hook (the engine wires the request's journal
        # purge here): fires exactly once, from whichever thread
        # resolves the future, AFTER the resolution is visible.
        self._on_resolve: Optional[Callable[[], None]] = None

    # engine-side ----------------------------------------------------------
    # Resolution is serialized by _resolve_lock: the watchdog may fail
    # a future from its own thread at the same instant the engine
    # thread finishes it normally — whoever wins the lock resolves the
    # future, the loser is a no-op (a bare done-check would let both
    # pass the guard and leave finish_reason AND an exception set).

    def _add_token(self, tok: int) -> bool:
        """Append one emitted token; returns False if the future was
        already resolved (the caller must not journal a token the
        caller-visible result will never contain)."""
        with self._resolve_lock:
            if self._done.is_set():
                return False
            self._tokens.append(tok)
            piece = None
            if self._detokenize is not None:
                piece = self._detokenize(tok)
                self._text.append(piece)
        if self._on_token is not None:
            self._on_token(tok, piece)
        return True

    def _finish(self, reason: str) -> None:
        with self._resolve_lock:
            if self._done.is_set():
                return
            self.finish_reason = reason
            if self.trace is not None:
                self.trace.finished_at = time.monotonic()
                self.trace.finish = reason
                self.trace.tokens = len(self._tokens)
            self._done.set()
        self._emit_trace()
        self._fire_resolve()

    def set_exception(self, exc: BaseException) -> None:
        with self._resolve_lock:
            if self._done.is_set():
                return
            self._exc = exc
            if self.trace is not None:
                self.trace.finished_at = time.monotonic()
                self.trace.error = type(exc).__name__
                self.trace.tokens = len(self._tokens)
            self._done.set()
        self._emit_trace()
        self._fire_resolve()

    def _fire_resolve(self) -> None:
        # Same once-only guarantee as _emit_trace: only the resolving
        # thread gets past the done-check inside the lock.
        cb = self._on_resolve
        if cb is not None:
            try:
                cb()
            except Exception:  # pragma: no cover - cleanup must not fail work
                pass

    def _emit_trace(self) -> None:
        # Outside _resolve_lock (file/queue IO must not serialize
        # resolution); only the resolving thread reaches here, exactly
        # once — the lock's done-check gates both resolution paths.
        tp, tr = self._tracer, self.trace
        if tp is not None and tr is not None:
            try:
                tp.request_done(tr)
            except Exception:  # pragma: no cover - tracing must not fail work
                pass
        sp = self._spans
        if sp is not None and tr is not None:
            # The span stream gets the finish record + the
            # tail-sampling verdict on the buffered detail spans.
            try:
                sp.request_done(tr)
            except Exception:  # pragma: no cover - spans must not fail work
                pass

    # caller-side ----------------------------------------------------------

    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self) -> bool:
        """Request cancellation.  Returns False if the future is
        already resolved, True if cancellation was requested.  The
        engine reclaims the request's slot (or removes it from the
        queue) on its next tick and resolves the future with
        ``finish_reason == "cancelled"`` and the tokens generated so
        far — cancellation resolves, it does not raise."""
        if self._done.is_set():
            return False
        self._cancel = True
        return True

    @property
    def cancel_requested(self) -> bool:
        return self._cancel

    @property
    def cancelled(self) -> bool:
        return self.finish_reason == "cancelled"

    @property
    def trace_id(self) -> Optional[str]:
        return self.trace.trace_id if self.trace is not None else None

    def breakdown(self) -> Optional[Dict]:
        """The request's timing breakdown (queue wait, prefill, decode,
        host-sync lag) — final once the future resolves, measured
        up-to-now while it is still running."""
        return self.trace.breakdown() if self.trace is not None else None

    def tokens_so_far(self) -> List[int]:
        return list(self._tokens)

    @property
    def text(self) -> str:
        return "".join(self._text)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Generated token ids; raises the typed rejection if the request
        never ran, TimeoutError if it is still running at ``timeout``."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation still in progress")
        if self._exc is not None:
            raise self._exc
        return list(self._tokens)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Continuous-batching knobs (tuning notes: docs/serving.md).

    ``n_slots`` (S) is the decode batch the executable is compiled for;
    ``max_len`` caps prompt + generation per slot (0 = cfg.max_seq);
    ``max_prefills_per_tick`` (K) bounds admissions between decode
    ticks AND sizes the batched prefill that admits them (one batch-K
    prefill per tick, compile set buckets x K); ``max_queue_depth``
    bounds the burst the scheduler absorbs; ``min_prefill_bucket``
    floors the power-of-two prompt buckets so tiny prompts share one
    compile.

    ``overlap`` (default on) runs the decode loop as the two-stage
    device/host pipeline (device-resident tokens, one-tick-lag
    retirement — module docstring); ``overlap=False`` is the
    synchronous A/B baseline: fetch-and-apply in the same step, same
    tokens, ~the device wait slower per tick.

    Paged KV cache (``paged``, default on — docs/serving.md "Paged KV
    cache"): K/V live in a pool of ``n_pages`` fixed-size pages
    (``page_size`` tokens each; ``n_pages=0`` sizes the pool for
    capacity parity with the slot-contiguous layout, smaller pools
    trade worst-case capacity for admission headroom), resolved
    through per-slot page tables INSIDE the one compiled tick.  Pages
    are granted on demand at tick boundaries, refcounted for prefix
    sharing (:meth:`InferenceEngine.register_prefix`), and
    copy-on-write: a shared page is copied only when a slot must write
    into it.  ``kv_dtype`` selects page storage: None = the model
    dtype, "bf16" halves f32 cache bytes (exact for bf16 models),
    "int8" quarters them (per-vector scales, dequantize-on-attend —
    lossy).  ``paged=False`` keeps the slot-contiguous
    :class:`SlotCache` — the A/B oracle baseline.

    Fault tolerance: ``max_restarts`` bounds CONSECUTIVE supervised
    restarts before the engine goes terminally ``failed`` (a clean tick
    resets the count); ``restart_backoff`` / ``restart_backoff_max``
    shape the exponential backoff between attempts; ``tick_timeout`` is
    the watchdog's per-tick wall-clock budget (0 disables the watchdog;
    the budget must cover the first tick's prefill+decode COMPILATION,
    not just steady-state latency); ``watchdog_interval`` is its poll
    period; ``faults`` threads a deterministic
    :class:`~horovod_tpu.serving.faults.FaultInjector` through the
    engine's failure-prone sites (tests only — leave None in
    production).

    Durability (``resume``, default on — docs/serving.md "Operations"):
    in-flight requests survive supervised restarts.  Every live
    request is journaled (:class:`~horovod_tpu.serving.journal.
    RequestJournal`: original prompt, params, trace id, tokens emitted
    so far); a restart re-admits each one by prefilling ``prompt +
    emitted`` and continuing decode, with the original future staying
    live — concatenated output token-identical to an uninterrupted
    run, wasted work bounded by one tick plus one re-prefill.
    ``resume=False`` restores the PR 3 behavior (in-flight futures
    fail typed on any restart).  ``journal_path`` additionally writes
    the journal as an append-only JSONL file that survives SIGKILL —
    the router reads a dead replica's file to fail partially-decoded
    requests over to a surviving replica (docs/serving.md "Front
    tier").  ``stall_grace`` is how long past ``tick_timeout`` a
    STALLED tick may still return and have its requests resumed;
    beyond it the watchdog hard-fails everything typed, restoring the
    bounded-resolution guarantee (None = one extra ``tick_timeout``;
    ignored when ``resume=False`` — stalls then fail futures
    immediately, as before)."""

    n_slots: int = 4
    max_len: int = 0
    max_prefills_per_tick: int = 2
    overlap: bool = True
    paged: bool = True
    page_size: int = 16
    n_pages: int = 0
    kv_dtype: Optional[str] = None
    # Fused paged-attention decode kernel (docs/serving.md "Paged
    # decode kernel"): route every paged decode/draft/verify tick's
    # attention through the Pallas flash-decoding kernel
    # (horovod_tpu/ops/paged_attention.py) — pages stream through VMEM
    # with int8 dequant fused into the load, nothing materialized at
    # logical shape.  None = auto (engage on a real TPU backend, stay
    # on the unfused XLA path elsewhere — the CPU interpreter runs the
    # kernel faithfully but slowly); True forces it anywhere Pallas
    # imports (tests/benchmarks); False pins the unfused path.  Greedy
    # output is token-identical either way (tests/test_paged.py), and
    # the flag is a CONSTRUCTOR-level knob: it is baked into the tick
    # executables at trace time, so flipping it means a rebuild —
    # tuning/replay.py explores it offline like kv_dtype/page_size.
    paged_kernel: Optional[bool] = None
    # Tensor parallelism (docs/serving.md "Tensor-parallel replicas"):
    # tp > 1 runs EVERY compiled tick body under GSPMD over a tp mesh
    # built from parallel/meshes.MeshSpec — params sharded per
    # serving_param_specs (heads + MLP hidden over tp, embeddings at
    # the vocab dim, norms replicated), the paged KV pool head-dim
    # sharded, page tables replicated as data — so one engine serves a
    # model bigger than one chip and XLA inserts the head-gather/psum
    # collectives itself.  Sharding is an annotation on the SAME
    # executables: chunked prefill, speculative verify, sampling
    # columns, journal/resume, and SSE failover compose unchanged, and
    # output is token-identical to the tp=1 oracle.  Requires
    # paged=True, n_heads % tp == 0 and kv_heads % tp == 0 (typed
    # ShardingConfigError at construction), and tp visible devices
    # (CPU: XLA_FLAGS=--xla_force_host_platform_device_count=N).
    tp: int = 1
    # Chunked prefill (docs/serving.md "Scheduling"): cap the prompt
    # tokens one tick may spend on ingestion.  A prompt whose
    # (post-prefix-match) length exceeds the budget is admitted into a
    # slot but INGESTED chunk by chunk, one chunk riding each decode
    # tick: every chunk runs through the same ``prefill_with_prefix``
    # executable the prefix registry uses, attending the
    # already-landed pages gathered back through the slot's page table
    # — chunk boundaries are DATA (page lists + a traced prefix
    # length), so the compile set stays bounded by (page-count
    # buckets) x (chunk buckets) and the decode executable never
    # recompiles.  Decode for every OTHER slot proceeds between
    # chunks, which is the whole point: one long prompt no longer
    # stalls the batch for a full prefill (the Sarathi-Serve move).
    # The final chunk's last-position logits are bit-identical to a
    # whole-prompt prefill's, so greedy AND sampled output is
    # token-identical to the un-chunked oracle.  0 disables (whole
    # prompts, the historical behavior); requires ``paged=True``.
    prefill_chunk_tokens: int = 0
    # Speculative decoding (docs/serving.md "Speculative decoding"):
    # draft spec_k tokens per active slot inside the compiled tick,
    # verify them all in ONE batched target forward, emit the agreeing
    # prefix plus the target's correction token — 1..spec_k+1 tokens
    # per slot per tick, byte-identical to plain greedy decode (the
    # emitted tokens are always the target's own argmax picks; draft
    # quality moves only the acceptance rate).  Requires paged=True.
    # spec_draft: "model" (a shallower TransformerConfig sharing the
    # tokenizer, passed as InferenceEngine(draft_params=, draft_cfg=),
    # with its own slot-aligned paged KV pool), "ngram" (prompt-lookup
    # self-speculation over a device-resident token history — no
    # second model), or "auto" (model when draft params are given,
    # ngram otherwise).  draft_n_pages sizes the draft pool (0 =
    # capacity parity, like n_pages).  Off by default until the A/B
    # (benchmarks/serving.py --spec-ab) proves it for the workload.
    # spec_adaptive bounds the LOSING case: per-slot recent acceptance
    # is tracked over windows of spec_window speculative ticks, a slot
    # under spec_min_acceptance has speculation auto-disabled (its
    # mask is data), and a tick where NO slot speculates dispatches
    # the plain one-token executable instead — so an adversarial
    # workload decays to plain-engine throughput minus occasional
    # probes (every spec_probe_period ticks a disabled slot re-enables
    # to re-measure).  Output never depends on any of this.
    speculative: bool = False
    spec_k: int = 4
    spec_draft: str = "auto"
    draft_n_pages: int = 0
    spec_adaptive: bool = True
    spec_min_acceptance: float = 0.25
    spec_window: int = 2
    spec_probe_period: int = 256
    # Paged decode growth: grant this many pages AHEAD of the write
    # position at each tick boundary (0 = exactly the write page, the
    # historical behavior).  Pure page-table data — fewer grant calls
    # per decoded page at the price of earlier page-pressure; its main
    # role is as a compile-free online-tunable knob (tuning/params.py).
    # _ensure_write_range caps the span at the request's last real
    # write, so look-ahead never buys a page nobody keeps.
    page_grant_ahead: int = 0
    # Online autotuning (docs/serving.md "Autotuning"): after warmup()
    # the engine installs a tuning.OnlineTuner over the compile-safe
    # knob space derived from its warmed state and perturbs/scores/
    # pins serving knobs from the tick loop.  Never changes emitted
    # tokens, never compiles (the tuning/params.py contract); state in
    # /stats["tuning"] and GET /tuning.
    autotune: bool = False
    max_queue_depth: int = 64
    default_max_new_tokens: int = 64
    min_prefill_bucket: int = 8
    max_restarts: int = 3
    restart_backoff: float = 0.05
    restart_backoff_max: float = 2.0
    tick_timeout: float = 60.0
    watchdog_interval: float = 0.05
    resume: bool = True
    journal_path: Optional[str] = None
    stall_grace: Optional[float] = None
    faults: Optional[FaultInjector] = None
    # Fleet-rollout label (docs/serving.md "Fleet rollouts"): which
    # CONFIG GENERATION this engine was built at.  Purely an identity
    # tag — the RolloutController stamps candidates with
    # incumbent_gen + 1, the registry surfaces it per replica, and the
    # chaos suite proves fleet convergence ("every replica reports the
    # same config_generation") through it.  Never read by the engine.
    config_generation: int = 0
    # Model FLOPs per generated token (e.g.
    # obs.xprof.transformer_flops_per_token(params)): turns the token
    # counters into achieved FLOP/s in /stats — the honest utilization
    # number a router/capacity planner balances on.  None disables.
    model_flops_per_token: Optional[float] = None


@dataclasses.dataclass
class _SlotState:
    request: Request
    last_token: int
    n_generated: int


@dataclasses.dataclass
class _IngestState:
    """One slot mid-way through CHUNKED prompt ingestion
    (``EngineConfig.prefill_chunk_tokens``): the request, and how many
    prompt tokens are already landed in its pages (``landed`` counts
    attached shared-prefix tokens too — the next chunk starts there).
    The slot is excluded from the decode mask until the last chunk
    lands and yields the first token.  ``started`` is where ingestion
    began (the attached-prefix length) — ``landed - started`` is the
    prefill compute a suspension throws away, the honest
    wasted-token count for a preempted mid-ingest victim."""

    request: Request
    landed: int
    started: int = 0


@dataclasses.dataclass
class _PrefixEntry:
    """One registered shared prefix: its tokens, the refcount-pinned
    pages its K/V lives in, and the first greedy continuation token
    (cached so a prompt that IS the prefix admits with zero prefill
    compute).  ``epoch`` stamps which cache lifetime the pages belong
    to — a supervised restart replaces the pool, so stale entries
    lazily re-prefill on next use."""

    tokens: tuple
    pages: Optional[List[int]] = None
    first_token: int = 0
    #: the prefix's last-position LOGITS (device (V,) array), kept so a
    #: SAMPLED prompt-is-the-prefix admission can draw its first token
    #: from them (the greedy first token alone is not enough — each
    #: sampled sharer picks with its own key).
    logits: Optional[object] = None
    epoch: int = -1


class InferenceEngine:
    """Continuous-batching engine over one model's params + config.

    Drive it synchronously with :meth:`step` (tests, benchmarks) or as a
    background thread with :meth:`start`/:meth:`stop` (the HTTP server;
    this also arms the watchdog).  ``detokenize`` optionally maps a
    token id to its text piece for streamed detokenization."""

    def __init__(self, params: Dict, cfg: "T.TransformerConfig",
                 engine_cfg: EngineConfig = EngineConfig(), *,
                 detokenize: Optional[Callable[[int], str]] = None,
                 draft_params: Optional[Dict] = None,
                 draft_cfg: Optional["T.TransformerConfig"] = None):
        self.params = params
        self.cfg = cfg
        self.engine_cfg = engine_cfg
        self.detokenize = detokenize
        # Speculative decoding: resolve the draft source up front so
        # every cache/executable below is built for the right mode.
        self._spec = engine_cfg.speculative
        self._spec_model = False
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        if self._spec:
            if not engine_cfg.paged:
                raise ValueError(
                    "EngineConfig.speculative requires paged=True (the "
                    "verify kernel resolves page tables inside the "
                    "compiled tick)")
            if engine_cfg.spec_k < 1:
                raise ValueError(
                    f"spec_k must be >= 1, got {engine_cfg.spec_k}")
            mode = engine_cfg.spec_draft
            if mode == "auto":
                mode = "model" if draft_params is not None else "ngram"
            if mode not in ("model", "ngram"):
                raise ValueError(
                    f"unknown spec_draft {engine_cfg.spec_draft!r}; "
                    "expected 'model', 'ngram', or 'auto'")
            if mode == "model":
                if draft_params is None or draft_cfg is None:
                    raise ValueError(
                        "spec_draft='model' needs draft_params and "
                        "draft_cfg (a shallower TransformerConfig "
                        "sharing the tokenizer)")
                if draft_cfg.vocab_size != cfg.vocab_size:
                    raise ValueError(
                        f"draft model must share the tokenizer: vocab "
                        f"{draft_cfg.vocab_size} != {cfg.vocab_size}")
            self._spec_model = mode == "model"
        if engine_cfg.prefill_chunk_tokens:
            if not engine_cfg.paged:
                raise ValueError(
                    "EngineConfig.prefill_chunk_tokens requires "
                    "paged=True (chunks attend the already-landed "
                    "pages through prefill_with_prefix)")
            if engine_cfg.prefill_chunk_tokens < 1:
                raise ValueError(
                    f"prefill_chunk_tokens must be >= 1 (or 0 to "
                    f"disable), got {engine_cfg.prefill_chunk_tokens}")
        # Tensor-parallel mesh (EngineConfig.tp): the engine OWNS the
        # mesh — built once here, params and the page pool placed on
        # it, and every executable below jitted with in/out shardings
        # from it.  All validation is typed and happens NOW, never as
        # an XLA shape crash inside the first tick.
        from horovod_tpu.serving.sharding import (
            ServingSharding, ShardingConfigError)
        self._shard: Optional[ServingSharding] = None
        self.mesh = None
        if engine_cfg.tp < 1:
            raise ShardingConfigError(
                f"EngineConfig.tp must be >= 1, got {engine_cfg.tp}")
        if engine_cfg.tp > 1:
            if not engine_cfg.paged:
                raise ShardingConfigError(
                    "EngineConfig.tp > 1 requires paged=True (the tp "
                    "mesh shards the paged KV pool by head; the "
                    "slot-contiguous A/B cache stays single-device)")
            self._shard = ServingSharding(
                cfg, engine_cfg.tp,
                draft_cfg=draft_cfg if self._spec_model else None)
            self.mesh = self._shard.mesh
            self.params = self._shard.shard_params(self.params)
            if self._spec_model:
                self.draft_params = self._shard.shard_params(
                    self.draft_params, self.draft_cfg)
        self.slots = self._make_slots()
        self.metrics = ServingMetrics()
        self.scheduler = Scheduler(
            max_queue_depth=engine_cfg.max_queue_depth,
            max_prefills_per_tick=engine_cfg.max_prefills_per_tick,
            on_reject=lambda req, err: self.metrics.rejected.inc(),
            on_cancel=lambda req: self.metrics.cancelled.inc(),
            # A requeued (preempted/resumed) request whose deadline
            # lapses before re-admission RETIRES with its partial
            # tokens — that is a completion, not shed load.
            on_expire=lambda req: self.metrics.completed.inc())
        self._states: List[Optional[_SlotState]] = \
            [None] * engine_cfg.n_slots
        # Chunked-prefill ingestion state (prefill_chunk_tokens): slot
        # -> _IngestState for every slot whose prompt is still landing
        # chunk by chunk; such slots are allocated (pages, occupancy)
        # but excluded from the decode mask until the last chunk's
        # logits yield their first token.  _tick_prefill_spent is the
        # per-tick ingestion-token ledger the admission admit_fn and
        # _advance_ingest share.
        self._ingest: Dict[int, _IngestState] = {}
        self._tick_prefill_spent = 0
        self._tick_ingested: set = set()  # slots advanced this tick
        # Requests popped from the queue but not yet landed in a slot —
        # a tick failing mid-admission must fail these futures too.
        self._taken: List[Request] = []
        self._lock = threading.Lock()  # engine-loop state (step is serial)
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        self._stop = threading.Event()

        # Fault-tolerance state.  _hb_lock guards the tick heartbeat,
        # epoch, and stall flag — the ONLY state the watchdog touches
        # while the engine thread may be hung inside _lock (taking
        # _lock from the watchdog would deadlock recovery).
        self._hb_lock = threading.Lock()
        self._tick_started: Optional[float] = None
        self._last_tick_done: Optional[float] = None  # /healthz heartbeat age
        self._epoch = 0          # bumped on every restart
        self._stalled = False    # set by the watchdog, cleared on recovery
        self._stall_hard_failed = False  # grace spent: futures resolved typed
        self._health = HEALTHY
        self._health_lock = threading.Lock()
        self._transitions: List[str] = [HEALTHY]
        self._consec_failures = 0
        # Sticky lifecycle facts that the health STATE alone cannot
        # carry: a watchdog stall overwrites DRAINING with FAILED, and
        # a later stall-recovery must restore DRAINING (never reopen a
        # draining engine as DEGRADED); _terminal marks a failure no
        # restart may undo (budget exhausted / terminate()).
        self._draining = False
        self._terminal = False
        # Requests suspended for resume mid-_recover: in neither the
        # queue nor a slot until the requeue lands, but their futures
        # are live — drain() must not read that window as "idle".
        self._resuming = 0

        # Durability: the journal records every live request's original
        # prompt, params, and emitted-so-far tokens — what a restart
        # re-admits (resume) and what the router reads post-mortem from
        # a SIGKILL'd replica's journal file (journal_path).  Created
        # whenever either consumer exists.
        self.journal: Optional[RequestJournal] = None
        if engine_cfg.resume or engine_cfg.journal_path:
            self.journal = RequestJournal(engine_cfg.journal_path)

        # Compile-count hook: the traced-function body runs ONLY when jax
        # (re)traces, so this counter IS the number of decode
        # compilations — the acceptance criterion asserts it stays at 1
        # after warmup.
        self._decode_traces = 0

        # Online autotuner (tuning/tuner.py): installed at the END of
        # warmup() when engine_cfg.autotune — the knob space must be
        # derived from (and applied to) a fully WARMED engine, and a
        # tuner live DURING warmup could shrink the admission batch
        # mid-sweep and leave (bucket, k) shapes uncompiled.
        self._tuner = None
        self._warmed = False

        # Tensor-parallel in/out shardings for every executable below
        # (all None on a single-device engine).  The placement rule:
        # params and the page pool carry their head-sharded placements;
        # EVERYTHING the host uploads or fetches (tokens, masks,
        # tables, sampling columns, logits, acceptance) is pinned
        # REPLICATED.  Explicit shardings keep executable signatures
        # stable — a fed-back committed output and a fresh host upload
        # hit the same compiled program — so the zero-decode-recompile
        # guard holds under tp unchanged.
        # Fused paged-attention kernel engagement (paged_kernel knob):
        # resolved HERE, once, to a Python bool — it is closed over by
        # the tick bodies below at trace time, so engagement can never
        # cause a steady-state recompile (flipping it is a rebuild, the
        # same contract as kv_dtype/page_size).  None = auto: engage on
        # a real TPU backend only — the CPU interpreter runs the kernel
        # body faithfully but far slower than the unfused XLA path, so
        # auto keeps CPU ticks (and the tier-1 suite) on the fallback
        # while tests opt in explicitly with paged_kernel=True.
        if engine_cfg.paged:
            from horovod_tpu.ops._pallas_util import PALLAS_AVAILABLE
            _want = (engine_cfg.paged_kernel
                     if engine_cfg.paged_kernel is not None
                     else jax.default_backend() == "tpu")
            self._paged_kernel = bool(_want) and PALLAS_AVAILABLE
        else:
            self._paged_kernel = False
        _pk = self._paged_kernel
        _pk_mesh = self.mesh if (_pk and engine_cfg.tp > 1) else None

        shd = self._shard
        self._sh_R = _R = shd.replicated if shd else None
        self._sh_params = _psh = shd.param_shardings() if shd else None
        self._sh_draft_params = _dpsh = (
            shd.param_shardings(draft_cfg)
            if shd and self._spec_model else None)
        _poolsh = shd.pool_shardings(self.slots.quantized) if shd else None
        _dpoolsh = (shd.pool_shardings(False)
                    if shd and self._spec_model else None)
        self._sh_prefill = _kvsh = (shd.prefill_cache_shardings()
                                    if shd else None)
        self._sh_prefix = _presh = (shd.prefix_kv_sharding()
                                    if shd else None)

        if engine_cfg.paged and self._spec:
            # The SPECULATIVE tick: draft -> one batched W-position
            # verify -> accepted-prefix select, all device-resident.
            # Shapes are static in S and W = spec_k + 1; the per-slot
            # accepted length is DATA, so varying acceptance never
            # recompiles.  The device-side next-token is the bonus/
            # correction token t[s, acc[s]] — the overlap pipeline's
            # tick N+1 input, no host round-trip.
            K = engine_cfg.spec_k
            if self._spec_model:
                dcfg = draft_cfg

                def _tick(params, dparams, tokens, active, spec_on,
                          table, dtable, pool, dpool, s_t, s_k, s_p,
                          s_key):
                    self._decode_traces += 1
                    obs_tracing.record_compile("serving_decode")
                    # Draft pos follows the TARGET pos at tick entry
                    # too (not just exit): a probe-time rebuild from
                    # host state can lag the device by an in-flight
                    # tick, and drafting from a skewed position would
                    # misplace the window's K/V for the whole tenancy.
                    dpool = {**dpool, "pos": pool["pos"]}
                    drafts, dpool = T.draft_propose_paged(
                        dparams, tokens, dpool, dtable, dcfg, active, K,
                        kernel=_pk, mesh=_pk_mesh)
                    window = jnp.concatenate([tokens[:, None], drafts],
                                             axis=1)
                    t, mx, acc, pool = T.decode_verify_paged(
                        params, window, pool, table, self.cfg, active,
                        spec_on, sample=(s_t, s_k, s_p, s_key),
                        kernel=_pk, mesh=_pk_mesh)
                    # Draft rollback on rejection = reset pos to the
                    # committed depth; the rejected tail's stale draft
                    # K/V is overwritten before it is ever attended
                    # (write-before-attend, per draft page).
                    dpool = {**dpool, "pos": pool["pos"]}
                    nxt = t[jnp.arange(t.shape[0]), acc]
                    return (jnp.where(active, nxt, 0), t, mx, acc,
                            pool, dpool)

                self._tick_fn = self._jit(
                    _tick, donate=(7, 8),
                    in_s=shd and (_psh, _dpsh, _R, _R, _R, _R, _R,
                                  _poolsh, _dpoolsh, _R, _R, _R, _R),
                    out_s=shd and (_R, _R, _R, _R, _poolsh, _dpoolsh))
            else:
                def _tick(params, tokens, active, spec_on, table, pool,
                          hist, s_t, s_k, s_p, s_key):
                    self._decode_traces += 1
                    obs_tracing.record_compile("serving_decode")
                    pos = pool["pos"]
                    Th = hist.shape[1]
                    rows = jnp.arange(hist.shape[0])
                    # The last committed token joins the history first
                    # (it IS committed); mode="drop" discards inactive
                    # rows and out-of-range positions.
                    hidx = jnp.where(active & (pos < Th), pos, Th)
                    hist = hist.at[rows, hidx].set(tokens, mode="drop")
                    drafts = T.ngram_propose(hist, pos, K)
                    window = jnp.concatenate([tokens[:, None], drafts],
                                             axis=1)
                    t, mx, acc, pool = T.decode_verify_paged(
                        params, window, pool, table, self.cfg, active,
                        spec_on, sample=(s_t, s_k, s_p, s_key),
                        kernel=_pk, mesh=_pk_mesh)
                    # Accepted drafts are now committed history too.
                    j = jnp.arange(1, K + 1, dtype=jnp.int32)[None, :]
                    wp = pos[:, None] + j
                    ok = (active[:, None] & (j <= acc[:, None])
                          & (wp < Th))
                    hist = hist.at[rows[:, None],
                                   jnp.where(ok, wp, Th)].set(
                        drafts, mode="drop")
                    nxt = t[rows, acc]
                    return (jnp.where(active, nxt, 0), t, mx, acc,
                            pool, hist)

                self._tick_fn = self._jit(
                    _tick, donate=(5, 6),
                    in_s=shd and (_psh, _R, _R, _R, _R, _poolsh, _R,
                                  _R, _R, _R, _R),
                    out_s=shd and (_R, _R, _R, _R, _poolsh, _R))

            # The PLAIN one-token executable rides alongside: a tick
            # where no slot speculates (every request opted out, or
            # spec_adaptive disabled them all) dispatches this instead
            # — the losing case pays plain-engine cost, not a W-wide
            # verify for nothing.  Both executables are warmed by
            # warmup(); per-slot acceptance and the mask are data, so
            # the compile count stays constant at two.
            def _ptick(params, tokens, active, table, pool, s_t, s_k,
                       s_p, s_key):
                self._decode_traces += 1
                obs_tracing.record_compile("serving_decode")
                pos = pool["pos"]
                logits, pool = T.decode_step_paged(
                    params, tokens, pool, table, self.cfg, active,
                    kernel=_pk, mesh=_pk_mesh)
                nxt = self._pick(logits, pos, s_t, s_k, s_p, s_key)
                mx = jnp.max(logits, axis=-1)
                return jnp.where(active, nxt, 0), mx, pool

            self._plain_tick_fn = self._jit(
                _ptick, donate=(4,),
                in_s=shd and (_psh, _R, _R, _R, _poolsh, _R, _R, _R, _R),
                out_s=shd and (_R, _R, _poolsh))
            donate = None
        elif engine_cfg.paged:
            def _tick(params, tokens, active, table, pool, s_t, s_k,
                      s_p, s_key):
                self._decode_traces += 1
                obs_tracing.record_compile("serving_decode")
                pos = pool["pos"]
                logits, pool = T.decode_step_paged(
                    params, tokens, pool, table, self.cfg, active,
                    kernel=_pk, mesh=_pk_mesh)
                # The sampled pick — per-slot temperature/top-k/top-p
                # COLUMNS and PRNG key ROWS, all data: greedy rows
                # (temperature 0) are the argmax of old, sampled rows
                # draw with the position-folded key, and no parameter
                # mix ever retraces this body (the zero-recompile
                # guard covers sampling now too).
                nxt = self._pick(logits, pos, s_t, s_k, s_p, s_key)
                mx = jnp.max(logits, axis=-1)
                return jnp.where(active, nxt, 0), mx, pool

            donate = 4
        else:
            def _tick(params, tokens, active, cache, s_t, s_k, s_p,
                      s_key):
                self._decode_traces += 1
                # Runs once per (re)trace: this IS a compile event —
                # count it and mark it on the active trace/timeline.
                obs_tracing.record_compile("serving_decode")
                pos = cache["pos"]
                logits, cache = T.decode_step_slots(
                    params, tokens, cache, self.cfg, active)
                nxt = self._pick(logits, pos, s_t, s_k, s_p, s_key)
                # Per-slot max logit rides along for the host-side
                # finiteness check: NaN/Inf logits (bad params, flaky
                # hardware) must become a typed engine failure, not
                # silently-greedy garbage tokens.
                mx = jnp.max(logits, axis=-1)
                return jnp.where(active, nxt, 0), mx, cache

            donate = 3

        # Donate the cache: without it XLA keeps input AND output caches
        # alive across the tick (2x the KV HBM — half the servable
        # slots) and copies the whole cache every token.  (The page
        # TABLE is not donated — it is host-owned tick data, like the
        # active mask.)  The speculative variants jit themselves above
        # (their pool/draft-pool/history argnums differ).
        if donate is not None:
            self._tick_fn = self._jit(
                _tick, donate=(donate,),
                in_s=shd and (_psh, _R, _R, _R, _poolsh,
                              _R, _R, _R, _R),
                out_s=shd and (_R, _R, _poolsh))
        self._prefill_fns: Dict[tuple, Callable] = {}
        self._prefill_traces = 0
        self._prefill_calls = 0  # prefill FORWARD PASSES (sharing hook)

        # Paged-cache host state: _page_pos mirrors each slot's device
        # write position AT DISPATCH TIME (admission sets it to the
        # prompt length; every dispatched tick advances active rows by
        # one, exactly like the device-side pos) — page grants and COW
        # happen against this mirror at tick boundaries, BEFORE the
        # write that needs them.  _dev_table caches the device upload
        # of the page table, refreshed only when table_version moves.
        self._page_pos = np.zeros(engine_cfg.n_slots, np.int64)
        self._dev_table = None
        self._table_uploaded = -1
        # Registered shared prefixes (token tuple -> entry); epoch
        # stamps which cache lifetime the pinned pages belong to.
        self._prefixes: Dict[tuple, _PrefixEntry] = {}
        self._prefix_version = 0  # bumps on (un)register: match cache
        self._cache_epoch = 0
        if engine_cfg.paged:
            def _suffix_prefill(params, padded, lens, pk, pv, p0):
                self._prefill_traces += 1
                obs_tracing.record_compile("serving_prefill")
                return T.prefill_with_prefix(
                    params, padded, pk, pv, p0, self.cfg, true_len=lens)

            # jax.jit caches per (n_prefix_pages, bucket, k) shape; the
            # prefix length p0 is a traced scalar, so prefixes of any
            # length share the page-granular compile set.
            self._suffix_prefill = self._jit(
                _suffix_prefill,
                in_s=shd and (_psh, _R, _R, _presh, _presh, _R),
                out_s=shd and (_R, _kvsh))
            self.metrics.kv_pages_total.set(self.slots.n_pages)
            self.metrics.kv_pages_free.set(self.slots.free_pages)
            self.metrics.kv_bytes_per_token.set(self.slots.bytes_per_token)

        # Speculative host state: the per-slot enablement mask (the
        # per-request opt-out, uploaded as DATA like the active mask),
        # the draft model's PAIRED paged pool (slot-aligned with the
        # target pool; same refcount/COW machinery) or the n-gram
        # draft's device-resident token history, and the draft model's
        # own prefill compile cache.
        self._spec_host = np.ones(engine_cfg.n_slots, bool)
        # Runtime speculation gate (tuning/params.py "spec_enabled"):
        # pure admission-mask data — False routes NEW admissions down
        # the plain greedy path (both tick executables are warmed, so
        # the toggle never compiles and never changes emitted tokens).
        self._spec_runtime_enabled = True
        self._dev_spec = None
        self._dev_spec_host: Optional[np.ndarray] = None
        # Adaptive speculation state (spec_adaptive): _spec_live is the
        # auto-disable mask (False = acceptance fell below the floor),
        # _spec_win accumulates (drafted, accepted) per slot over the
        # evaluation window, _spec_idle counts ticks since disable (a
        # probe re-enables at spec_probe_period), and _spec_stale marks
        # slots whose draft state (n-gram history / draft-pool K/V)
        # missed plain ticks and must be rebuilt before re-enabling.
        self._spec_live = np.ones(engine_cfg.n_slots, bool)
        self._spec_win = np.zeros((engine_cfg.n_slots, 2), np.int64)
        self._spec_idle = np.zeros(engine_cfg.n_slots, np.int64)
        self._spec_stale = np.zeros(engine_cfg.n_slots, bool)
        self.draft_slots = self._make_draft_slots()
        self._dev_dtable = None
        self._dtable_uploaded = -1
        self._dev_history = None
        self._draft_prefill_fns: Dict[tuple, Callable] = {}
        if self._spec and not self._spec_model:
            # One scatter lands an admission group's prompt rows in the
            # history (jit caches per (k, bucket) shape).  Replicated
            # in/out under tp: the history is committed tick data, and
            # pinning it keeps its placement on the mesh device set the
            # spec tick expects.
            self._hist_land = self._jit(
                lambda hist, slots, padded: hist.at[
                    slots[:, None],
                    jnp.arange(padded.shape[1])[None, :]].set(padded),
                donate=(0,),
                in_s=shd and (_R, _R, _R), out_s=shd and _R)

        # Overlapped-pipeline state (engine_cfg.overlap).  _pending is
        # the ONE in-flight decode tick: its un-fetched device outputs
        # plus a host snapshot of which request each slot was computing
        # for at dispatch (the identity check that makes one-tick-lag
        # retirement safe).  _dev_tokens is the device-resident token
        # vector — tick N's output feeds tick N+1's dispatch without a
        # host round-trip — and _dev_active caches the device copy of
        # the active mask, re-uploaded only when the host mask changes.
        self._pending: Optional[Dict] = None
        self._dev_tokens = None
        self._dev_active = None
        self._dev_active_host: Optional[np.ndarray] = None
        # where(mask, vals, toks): lands freshly admitted slots' first
        # tokens in the device token vector (one tiny async op).
        # Replicated in/out under tp — its output IS the next tick's
        # token input, so the placement must match the tick's.
        self._merge_tokens = self._jit(
            lambda toks, vals, mask: jnp.where(mask, vals, toks),
            in_s=shd and (_R, _R, _R), out_s=shd and _R)

        # Per-slot sampling columns (serving/sampling.py): temperature /
        # top_k / top_p / PRNG key rows ride the tick as DATA — one
        # executable for every parameter mix, greedy = temperature-0
        # rows.  _first_sample picks an admission group's FIRST tokens
        # from the prefill logits with the same kernel (jit caches per
        # (k, vocab) shape — warmed by warmup(), counted separately
        # from the prefill compile set).
        self._samp = SlotSampling(engine_cfg.n_slots)
        self._sample_traces = 0

        def _first_sample(logits, s_t, s_k, s_p, s_key, positions):
            self._sample_traces += 1
            obs_tracing.record_compile("serving_sample")
            return T.sample_token_rows(
                logits, s_t, s_k, s_p, s_key, positions,
                jnp.zeros_like(positions))

        self._first_sample = self._jit(
            _first_sample,
            in_s=shd and (_R, _R, _R, _R, _R, _R), out_s=shd and _R)

        # Token-rate window for achieved FLOP/s: (monotonic, tokens)
        # samples taken at each stats() call, pruned to ~60s — the
        # scrape cadence defines the window, no hot-path cost.
        # Own lock (not self._lock): stats() is served from concurrent
        # HTTP handler threads and must not contend with the tick loop.
        self._rate_samples: List = []
        self._rate_lock = threading.Lock()
        self._rate_metrics = self.metrics
        if engine_cfg.model_flops_per_token:
            self.metrics.model_flops_per_token.set(
                engine_cfg.model_flops_per_token)
        self.metrics.tp_degree.set(engine_cfg.tp)

    # -- lifecycle / health ------------------------------------------------

    @property
    def health(self) -> str:
        """Current lifecycle state: healthy | degraded | draining |
        failed."""
        return self._health

    @property
    def state_transitions(self) -> List[str]:
        """The state-machine trail (capped), oldest first."""
        return list(self._transitions)

    @property
    def terminal(self) -> bool:
        """True once the engine can never serve again (restart budget
        exhausted or :meth:`terminate`) — a transient watchdog
        ``failed`` that a supervised restart may still recover from
        reads False.  Replica processes key their exit code on this
        (router/replica_main.py)."""
        return self._terminal

    @property
    def heartbeat_age(self) -> Optional[float]:
        """Seconds since the last COMPLETED tick (None before the
        first) — the liveness number ``/healthz`` reports so probes
        can tell a quiet engine from a wedged one without parsing
        ``/stats``."""
        with self._hb_lock:
            t = self._last_tick_done
        return time.monotonic() - t if t is not None else None

    def _set_health(self, state: str) -> None:
        with self._health_lock:
            if self._health == state:
                return
            self._health = state
            self._transitions.append(state)
            del self._transitions[:-50]  # bounded trail

    def begin_drain(self) -> None:
        """Enter ``draining``: new :meth:`submit` calls raise
        :class:`DrainingError`; admitted and queued requests keep
        running.  Draining is sticky — even a stall-recovery restart
        stays draining.  A terminally failed engine stays ``failed``
        (check-and-set under ONE lock hold: a concurrent watchdog
        FAILED must never be overwritten, or drain() would burn its
        whole budget on a dead engine)."""
        self._draining = True
        with self._health_lock:
            if self._health in (FAILED, DRAINING):
                return
            self._health = DRAINING
            self._transitions.append(DRAINING)
            del self._transitions[:-50]

    # -- submission --------------------------------------------------------

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               eos_id: Optional[int] = None,
               deadline: Optional[float] = None,
               on_token: Optional[Callable] = None,
               trace_id: Optional[str] = None,
               parent_span: Optional[str] = None,
               sampled: bool = False,
               speculative: Optional[bool] = None,
               temperature: float = 0.0,
               top_k: int = 0,
               top_p: float = 0.0,
               seed: Optional[int] = None,
               priority: str = "interactive") -> GenerationFuture:
        """Queue a generation request; returns its future.

        ``priority`` selects the request's SLO class
        (:data:`~horovod_tpu.serving.scheduler.PRIORITY_CLASSES`;
        validated here — unknown classes are a typed
        :class:`ServingError`, HTTP 400).  The scheduler serves
        classes strictly in order (``interactive`` before ``batch``)
        with EDF inside each class, and under slot/page pressure the
        engine may SUSPEND a strictly-worse-class victim (journal
        frontier kept, re-admitted later, output byte-identical) to
        bound the better class's wait — docs/serving.md
        "Scheduling".

        ``temperature`` / ``top_k`` / ``top_p`` / ``seed`` select
        per-request SAMPLING (serving/sampling.py; validated here,
        :class:`ServingError` on bad values).  ``temperature=0`` (the
        default) is greedy; a sampled request's token stream is
        token-identical to ``sample_decode`` at the same seed/params —
        including across restart-resume and router failover, because
        the PRNG key schedule depends only on (seed, token position).
        All of it rides the ONE compiled tick as per-slot data; no
        parameter mix recompiles anything.  On a speculative engine a
        sampled request decodes one token per tick through the same
        executable (drafts are verified by argmax agreement, which a
        sampled stream never satisfies).

        ``speculative`` is the per-request opt-out on a speculative
        engine (None = engine default): ``False`` pins the request to
        one-token-per-tick greedy decode AS DATA — identical output,
        predictable per-tick pacing, no recompile.  Ignored on a
        non-speculative engine.

        ``trace_id`` propagates a caller-supplied id (the server passes
        the ``X-Trace-Id`` header) into the request's
        :class:`~horovod_tpu.obs.tracing.RequestTrace`; a fresh id is
        minted when absent, so :attr:`GenerationFuture.trace_id` and
        :meth:`GenerationFuture.breakdown` are always available.
        ``parent_span`` nests this request's span under an upstream
        caller's span (the router's proxy-attempt span, via
        ``X-Parent-Span``), and ``sampled`` forces full-detail span
        retention past tail sampling (``X-Trace-Sampled``) — both
        no-ops unless a :func:`~horovod_tpu.obs.tracing.spans`
        recorder is active.

        Typed rejections: :class:`RequestTooLongError` (prompt +
        max_new_tokens cannot fit a cache slot — raised immediately),
        :class:`QueueFullError` (bounded queue at capacity),
        :class:`DrainingError` / :class:`EngineFailedError` (engine
        draining or terminally failed — nothing is ever enqueued on a
        dead engine), and :class:`DeadlineExceededError` (set on the
        FUTURE if ``deadline`` — an absolute ``time.monotonic()``
        instant — passes while queued).  A deadline that lapses AFTER
        admission retires the slot early instead: the future completes
        with the partial result and ``finish_reason == "deadline"``, so
        abandoned requests don't pin slots."""
        if self._draining:
            raise DrainingError("engine is draining; not accepting work")
        if self._health == FAILED:
            if self._terminal:
                raise EngineFailedError(
                    "engine has failed permanently "
                    "(restart budget exhausted or terminated)")
            raise EngineFailedError(
                "engine is recovering from a stalled tick; retry shortly")
        prompt = [int(t) for t in prompt]
        n_new = (max_new_tokens if max_new_tokens is not None
                 else self.engine_cfg.default_max_new_tokens)
        temperature, top_k, top_p, seed = validate_sampling(
            temperature, top_k, top_p, seed)
        priority_rank(priority)  # typed ServingError on unknown class
        if not prompt:
            raise ServingError("empty prompt")
        if n_new < 1:
            raise ServingError(f"max_new_tokens must be >= 1, got {n_new}")
        cap = self.slots.max_len
        # First token comes from prefill logits, so a slot needs room for
        # the prompt plus the n_new - 1 decode-step writes.
        if len(prompt) + n_new - 1 > cap:
            self.metrics.rejected.inc()
            raise RequestTooLongError(
                f"prompt ({len(prompt)}) + max_new_tokens ({n_new}) "
                f"exceeds slot capacity ({cap})")
        if (self.engine_cfg.paged
                and self.slots.pages_for(len(prompt) + n_new - 1)
                > self.slots.n_pages):
            # Could NEVER run, even with the whole pool to itself — a
            # typed rejection now, not an admission stall forever.
            self.metrics.rejected.inc()
            raise CacheOutOfPagesError(
                f"prompt ({len(prompt)}) + max_new_tokens ({n_new}) "
                f"needs {self.slots.pages_for(len(prompt) + n_new - 1)} "
                f"pages; the pool holds {self.slots.n_pages}")
        fut = GenerationFuture(on_token=on_token,
                               detokenize=self.detokenize)
        fut.trace = obs_tracing.RequestTrace(trace_id,
                                             parent_span_id=parent_span)
        fut.trace.sampled = bool(sampled)
        fut._tracer = obs_tracing.get()
        fut._spans = obs_tracing.spans()
        req = Request(prompt=prompt, max_new_tokens=n_new, future=fut,
                      eos_id=eos_id, deadline=deadline, trace=fut.trace,
                      speculative=speculative, temperature=temperature,
                      top_k=top_k, top_p=top_p, seed=seed,
                      priority=priority)
        if self.journal is not None:
            # Journal BEFORE the enqueue, purge-on-resolve wired first:
            # every resolution path (retire, typed error, cancel,
            # terminate, the post-enqueue race checks below) funnels
            # through the future, so an entry can never outlive its
            # request — no ghost re-admission after a later restart.
            journal, rid = self.journal, req.id
            fut._on_resolve = lambda: journal.end(rid)
            journal.begin(req)
        try:
            self.scheduler.submit(req)  # QueueFullError counts, on_reject
        except QueueFullError:
            if self.journal is not None:
                self.journal.end(req.id)  # never enqueued: nothing to resume
            raise
        if fut._spans is not None:
            # Span START is written (and flushed) the moment the
            # request is live: a SIGKILL after this instant leaves the
            # start record + every typed event in the stream — the
            # durable half of the autopsy.  (Submit-time rejections
            # above never ran; they need no span.)
            try:
                fut._spans.request_begin(fut.trace, attrs={
                    "prompt_tokens": len(prompt),
                    "max_new_tokens": n_new,
                    "request_id": req.id})
            except Exception:  # pragma: no cover - spans must not fail work
                pass
        # Post-enqueue re-checks close the submit-vs-shutdown races:
        # the pre-checks above can pass just before a terminal failure
        # drains the queue, or just before begin_drain() + drain()
        # sample an (at that instant) empty queue and stop the engine —
        # either way THIS request must not be left enqueued unresolved.
        if self._health == FAILED:
            # Resolve ONLY this request: the terminal path already
            # drained the queue, and failing it wholesale here could
            # collateral-kill requests legitimately enqueued by other
            # threads after a stall-recovery restart.  take() drops
            # already-done requests if the engine ever ticks again.
            exc = EngineFailedError("engine failed during submit")
            fut.set_exception(exc)
            raise exc
        if self._draining:
            exc = DrainingError("engine began draining during submit")
            fut.set_exception(exc)  # take() drops already-done requests
            raise exc
        self.metrics.queue_depth.set(self.scheduler.depth)
        return fut

    # -- paged cache plumbing ----------------------------------------------

    def _jit(self, fn, *, donate=(), in_s=None, out_s=None):
        """``jax.jit`` with the tp mesh's in/out shardings when the
        engine is sharded (plain jit on a single-device engine —
        ``in_s``/``out_s`` are None there by construction, and an
        EXPLICIT ``in_shardings=None`` would mean replicate-everything,
        which is not the same as unspecified)."""
        if self._shard is None or in_s is None:
            return jax.jit(fn, donate_argnums=donate)
        return jax.jit(fn, donate_argnums=donate,
                       in_shardings=in_s, out_shardings=out_s)

    def _make_slots(self):
        ec = self.engine_cfg
        if ec.paged:
            return PagedSlotCache(self.cfg, ec.n_slots, ec.max_len,
                                  page_size=ec.page_size,
                                  n_pages=ec.n_pages, kv_dtype=ec.kv_dtype,
                                  mesh=self.mesh)
        return SlotCache(self.cfg, ec.n_slots, ec.max_len)

    def _make_draft_slots(self) -> Optional[PagedSlotCache]:
        """The draft model's page pool: slot-aligned with the target
        pool (same slot ids, same max_len) so retirement and admission
        pair one-to-one.  Model dtype storage — draft quality only
        moves the acceptance rate, but there is no reason to quantize a
        pool this shallow."""
        if not (self._spec and self._spec_model):
            return None
        ec = self.engine_cfg
        return PagedSlotCache(self.draft_cfg, ec.n_slots,
                              self.slots.max_len,
                              page_size=ec.page_size,
                              n_pages=ec.draft_n_pages,
                              mesh=self.mesh)

    def _release_slot(self, slot: int) -> None:
        """Free a slot in the target pool AND its speculative
        companions: the draft pool's paired slot (its pages return to
        the draft free heap) and the opt-out mask (reset to the engine
        default for the next tenant)."""
        self.slots.free(slot)
        self._samp.clear(slot)  # greedy/zero row for the next tenant
        self._spec_host[slot] = True
        # The adaptive live/idle state deliberately SURVIVES the
        # tenancy: acceptance is a property of the workload, and on
        # homogeneous hostile traffic a slot that just proved drafts
        # useless should not re-pay the evaluation window for every
        # new request — probes still re-enable it periodically.
        self._spec_win[slot] = 0
        if (self.draft_slots is not None
                and self.draft_slots._active[slot]):
            self.draft_slots.free(slot)

    def register_prefix(self, tokens: Sequence[int]) -> None:
        """Register a SHARED PREFIX (e.g. the system prompt): its K/V
        is prefilled ONCE into refcount-pinned pages, and every future
        request whose prompt starts with it attaches those pages and
        prefills only its suffix — N concurrent requests, one prefix
        prefill.  A request whose prompt IS the prefix admits with no
        prefill at all (the first greedy token is cached here).  Pages
        stay pinned across slot churn; a supervised restart invalidates
        the entry, which lazily re-prefills on next use.  Requires a
        paged engine."""
        if not self.engine_cfg.paged:
            raise ValueError("prefix sharing requires EngineConfig.paged")
        tokens = tuple(int(t) for t in tokens)
        if not tokens:
            raise ServingError("empty prefix")
        if len(tokens) > self.slots.max_len:
            raise RequestTooLongError(
                f"prefix ({len(tokens)}) exceeds slot capacity "
                f"({self.slots.max_len})")
        with self._lock:
            fresh = tokens not in self._prefixes
            entry = self._prefixes.setdefault(tokens,
                                              _PrefixEntry(tokens=tokens))
            try:
                self._ensure_prefix(entry)
            except BaseException:
                if fresh:
                    # A failed registration must leave NOTHING behind:
                    # a phantom entry would lazily re-pin pages later
                    # for a prefix the caller was told never registered
                    # (and so will never unregister).
                    self._prefixes.pop(tokens, None)
                raise
            if fresh:
                self._prefix_version += 1

    def unregister_prefix(self, tokens: Sequence[int]) -> None:
        """Drop a registered prefix's pin; its pages return to the free
        heap once the last attached slot retires."""
        with self._lock:
            entry = self._prefixes.pop(tuple(int(t) for t in tokens), None)
            if entry is not None:
                self._prefix_version += 1
            if (entry is not None and entry.pages
                    and entry.epoch == self._cache_epoch):
                self.slots.release_raw(entry.pages)

    def _matched_prefix(self, req: Request) -> Optional[_PrefixEntry]:
        """:meth:`_match_prefix`, once per request: the match is
        needed by ``_group_key`` (scheduler take), ``_plan_pages``
        (page budget), and ``_admit_paged`` — an O(prefixes x
        prefix_len) prompt scan each, every tick the request waits
        under back-pressure.  Cached on the request, invalidated when
        the registration set changes."""
        cached = getattr(req, "_prefix_match", None)
        if cached is not None and cached[0] == self._prefix_version:
            return cached[1]
        entry = self._match_prefix(req.prompt)
        req._prefix_match = (self._prefix_version, entry)
        return entry

    def _match_prefix(self, prompt) -> Optional[_PrefixEntry]:
        """Longest registered prefix the prompt starts with."""
        best = None
        for entry in self._prefixes.values():
            n = len(entry.tokens)
            if n <= len(prompt) and tuple(prompt[:n]) == entry.tokens:
                if best is None or n > len(best.tokens):
                    best = entry
        return best

    def _ensure_prefix(self, entry: _PrefixEntry) -> None:
        """(Re-)prefill a prefix entry into pinned pages — the ONE
        prefix forward pass its sharers amortize.  Raises
        :class:`CacheOutOfPagesError` if the pool cannot pin it."""
        if entry.pages is not None and entry.epoch == self._cache_epoch:
            return
        p0 = len(entry.tokens)
        pages = self.slots.grant_raw(self.slots.pages_for(p0))
        try:
            bucket = self._bucket(p0)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :p0] = entry.tokens
            logits, pre = self._prefill_fn(bucket, 1)(
                self.params, jnp.asarray(padded),
                jnp.asarray([p0], np.int32))
            self._prefill_calls += 1
            self.slots.land_raw(pages, pre, p0)
            self.metrics.host_syncs.inc()  # the argmax fetch blocks
            entry.first_token = int(jnp.argmax(logits[0]))  # cold sync
            # Kept on device for sampled prompt-is-the-prefix sharers:
            # each draws its own first token from these logits.
            entry.logits = logits[0]
        except BaseException:
            # Unpin on ANY failure (compile OOM, device fault at the
            # blocking sync): without this the pages leak at refcount 1
            # and every retry drains the pool a little further.
            self.slots.release_raw(pages)
            raise
        entry.pages = pages
        entry.epoch = self._cache_epoch

    def _prefix_landed(self, req: Request) -> int:
        """Tokens a matched, CURRENT-epoch prefix would pre-land for
        this request (0 without one) — what chunking and page planning
        subtract from the prompt."""
        entry = self._matched_prefix(req)
        if (entry is not None and entry.pages is not None
                and entry.epoch == self._cache_epoch):
            return len(entry.tokens)
        return 0

    def _chunked(self, req: Request) -> bool:
        """Does this request's prompt ingest CHUNK BY CHUNK?  Yes when
        chunking is on and the prompt tokens that actually need
        prefill (past any matched shared prefix) exceed the per-tick
        budget."""
        chunk = self.engine_cfg.prefill_chunk_tokens
        return bool(chunk) and (len(req.prompt)
                                - self._prefix_landed(req)) > chunk

    def _prefill_cost(self, req: Request) -> int:
        """Prompt tokens admitting this request costs THIS tick: the
        un-prefixed suffix, capped at one chunk for a chunked
        ingestion (later chunks ride later ticks)."""
        suf = len(req.prompt) - self._prefix_landed(req)
        chunk = self.engine_cfg.prefill_chunk_tokens
        return min(suf, chunk) if chunk else suf

    def _plan_pages(self, req: Request) -> int:
        """Pages an admission would consume (private grants + one COW/
        growth margin page) — the scheduler back-pressure budget.
        Shared prefix pages cost nothing: attaching is a refcount.  A
        CHUNKED admission plans only its first chunk's span (later
        chunks grant on demand at their tick, preempting or waiting
        like decode growth does)."""
        ps = self.slots.page_size
        p0 = self._prefix_landed(req)
        upto = len(req.prompt)
        if self._chunked(req):
            upto = p0 + self.engine_cfg.prefill_chunk_tokens
        n_idx = (upto - 1) // ps + 1
        if p0 > 0:
            if len(req.prompt) == p0:
                return 1  # attach-only; margin covers the first COW/grant
            return n_idx - p0 // ps + 1
        return n_idx + 1

    def _group_key(self, req: Request):
        """Admission-group key for :meth:`Scheduler.take`: groups must
        share one prefill executable, so the key is the prompt bucket —
        and, when paged, the matched prefix (one shared-prefix gather +
        suffix prefill serves the whole group) with the SUFFIX bucket.
        A CHUNKED request is taken ALONE (singleton key): its
        ingestion spans many ticks and shares no prefill shape with
        anyone."""
        if not self.engine_cfg.paged:
            return self._bucket(len(req.prompt))
        if self._chunked(req):
            return ("chunk", req.id)
        entry = self._matched_prefix(req)
        if entry is None:
            return ("full", self._bucket(len(req.prompt)))
        suf = len(req.prompt) - len(entry.tokens)
        if suf == 0:
            return ("attach", entry.tokens)
        return ("suffix", entry.tokens, self._bucket(suf))

    def _occupants(self) -> List:
        """Every occupied slot as ``(priority rank, request id, slot,
        request)`` — decoding slots and mid-ingestion slots alike (an
        ingesting slot holds pages too)."""
        occ = [(st.request.priority_rank, st.request.id, s, st.request)
               for s, st in enumerate(self._states) if st is not None]
        occ += [(ing.request.priority_rank, ing.request.id, s,
                 ing.request)
                for s, ing in self._ingest.items()]
        return occ

    def _build_resume(self, req: Request) -> Optional[Request]:
        """A RESUME request for ``req`` from its journal frontier —
        prompt + emitted tokens as the new prompt, the remaining
        decode budget, and the ORIGINAL id/deadline/trace/class/
        sampling/future — or None when no trustworthy frontier exists
        (no journal entry, resume off, or nothing left to decode).
        Shared by the restart-resume path (:meth:`_resume_or_fail`)
        and preemption (:meth:`_preempt`): both re-admissions are the
        same re-prefill-and-continue operation, so their output is
        byte-identical to an uninterrupted run by the same argument."""
        if not self.engine_cfg.resume or self.journal is None:
            return None
        entry = self.journal.get(req.id)
        if entry is None or entry.remaining < 1:
            return None
        new = Request(prompt=list(entry.prompt) + list(entry.emitted),
                      max_new_tokens=entry.remaining, future=req.future,
                      eos_id=entry.eos_id, deadline=req.deadline,
                      trace=req.trace, speculative=req.speculative,
                      # Sampling params survive verbatim: the key
                      # schedule is position-based, so the re-prefill
                      # of prompt + emitted continues the exact stream.
                      temperature=entry.temperature, top_k=entry.top_k,
                      top_p=entry.top_p, seed=entry.seed,
                      priority=req.priority)
        # The ORIGINAL id is kept: it is the journal key, and it
        # preserves the request's age in the scheduling order
        # (preemption picks victims by id — surviving a crash or a
        # preemption must not mark old work as young).
        new.id = req.id
        new.submitted_at = req.submitted_at
        # Wasted work = tokens RE-prefilled that were already computed
        # once.  A request that never landed a prefill (no emitted
        # tokens) re-queues for free.
        new._resume_wasted = len(new.prompt) if entry.emitted else 0
        return new

    def _preempt(self, slot: int, reason: str) -> bool:
        """SUSPEND the request occupying ``slot`` — journal frontier
        kept, pages and slot freed, request requeued for ordinary
        re-admission with its future still live (output byte-identical
        to an uninterrupted run: the re-prefill of prompt + emitted
        continues the exact token stream, greedy or sampled).  Falls
        back to the legacy typed :class:`CacheOutOfPagesError` when no
        resume frontier exists (``resume=False``).  Returns True if
        the slot was vacated."""
        st = self._states[slot]
        ing = self._ingest.get(slot)
        if st is None and ing is None:
            return False
        req = st.request if st is not None else ing.request
        fut = req.future
        # The SUBMIT-TIME recorder handle (not the global): begin and
        # finish went through fut._spans, so events must too — a
        # recorder swapped mid-request (the A/B seam) must not orphan
        # an event onto a stream that never saw the span start.
        srec = fut._spans
        if srec is not None and req.trace is not None:
            try:
                srec.request_event(req.trace, "eviction",
                                   {"slot": slot, "reason": reason})
            except Exception:  # pragma: no cover - spans must not fail
                pass
        self._states[slot] = None
        self._ingest.pop(slot, None)
        self._release_slot(slot)
        if fut.done():
            return True
        if fut.cancel_requested:
            fut._finish("cancelled")
            self.metrics.cancelled.inc()
            return True
        new = self._build_resume(req)
        if new is None:
            fut.set_exception(CacheOutOfPagesError(
                f"preempted ({reason}); no resume frontier — retry "
                f"with backoff"))
            self.metrics.rejected.inc()
            return True
        if ing is not None:
            # A mid-ingestion victim emitted nothing, but its landed
            # chunks were real prefill compute the re-ingestion
            # repeats — count them (the journal alone cannot see
            # them).
            new._resume_wasted = max(getattr(new, "_resume_wasted", 0),
                                     ing.landed - ing.started)
        self.metrics.preemptions.inc()
        wasted = getattr(new, "_resume_wasted", 0)
        if wasted:
            self.metrics.resume_wasted_tokens.inc(wasted)
        self.journal.note_resume(req.id)
        # Back into the queue (depth-exempt — the caller is still
        # waiting on a live future); the scheduling order places it by
        # class/EDF/id, and the paged admit_fn keeps it waiting until
        # the pressure that evicted it clears.
        self.scheduler.requeue_front([new])
        self.metrics.queue_depth.set(self.scheduler.depth)
        return True

    def _evict_for_pages(self) -> bool:
        """Preempt one victim to reclaim pages: the WORST class first,
        youngest within it (highest request id — oldest work keeps
        its progress; a batch-class slot always pays before an
        interactive one).  The victim SUSPENDS through the resume path
        (see :meth:`_preempt`) rather than failing, so its output
        stays byte-identical.  False when nothing is left to evict."""
        occ = self._occupants()
        if not occ:
            return False
        _, _, s, _ = max(occ)
        return self._preempt(s, "out_of_pages")

    def _preempt_for_slots(self) -> bool:
        """SLOT-pressure preemption: when every slot is busy and a
        STRICTLY better-class request waits, suspend the worst
        occupant (worst class, youngest within it) so the winner
        admits this tick — bounded wait for the winner, suspended (not
        lost) work for the victim.  Never fires within a class (equal
        peers wait FCFS, as ever) and never without a resume frontier
        to suspend onto."""
        if not (self.engine_cfg.resume and self.journal is not None):
            return False
        if self.slots.free_count > 0 or self.scheduler.depth == 0:
            return False
        best = self.scheduler.peek_best_rank()
        if best is None:
            return False
        occ = self._occupants()
        if not occ:
            return False
        worst = max(occ)
        if worst[0] <= best:
            return False  # nothing strictly better is waiting
        return self._preempt(worst[2], "slot_pressure")

    def _ensure_write_page(self, s: int) -> bool:
        """Grant (or copy-on-write) slot ``s``'s write page for the
        next dispatch — the one-token point case of
        :meth:`_ensure_write_range` (which, like chunk ingestion,
        routes through the ONE :meth:`_claim_page` grant/COW/evict
        protocol).  ``page_grant_ahead`` widens the span by that many
        pages past the write position (capped by the range method at
        the request's last real write — look-ahead never buys a page
        nobody keeps).  Returns False if ``s`` itself was evicted
        paying for its page."""
        wp = int(self._page_pos[s])
        ahead = self.engine_cfg.page_grant_ahead
        hi = wp + ahead * self.slots.page_size if ahead > 0 else wp
        return self._ensure_write_range(s, wp, hi)

    def _prepare_paged_tick(self) -> None:
        """Tick-boundary page maintenance: every active slot gets a
        PRIVATE page under its write position (grant on demand, COW on
        sharing, preemption on exhaustion), then the page table is
        re-uploaded iff it changed — table updates are host bookkeeping
        plus one async upload, never a device sync."""
        for s in range(self.engine_cfg.n_slots):
            if self._states[s] is not None:
                self._ensure_write_page(s)
        if (self._dev_table is None
                or self._table_uploaded != self.slots.table_version):
            self._dev_table = jnp.asarray(self.slots.table)
            self._table_uploaded = self.slots.table_version

    def _ensure_write_range(self, s: int, lo: int, hi: int) -> bool:
        """Grant/COW PRIVATE pages under every write position in
        ``[lo, hi]`` — the speculative tick writes a WINDOW, not a
        point.  Positions past the request's last real write (or the
        table's capacity) are left unmapped: the kernel routes those
        writes to the NULL page, so no page is ever bought for a token
        nobody keeps.  Evicts youngest-first on exhaustion; returns
        False if slot ``s`` itself was the victim."""
        st = self._states[s]
        if st is None:
            return False
        last_real = (len(st.request.prompt)
                     + st.request.max_new_tokens - 2)
        hi = min(hi, last_real, self.slots.max_len - 1)
        if hi < lo:
            return True
        ps = self.slots.page_size
        for idx in range(max(lo, 0) // ps, hi // ps + 1):
            if not self._claim_page(
                    s, idx, lambda: self._states[s] is not None):
                return False  # s itself was the victim — it paid
        return True

    def _claim_page(self, slot: int, idx: int, still_mine) -> bool:
        """THE grant/COW/evict protocol, in one copy (decode growth,
        speculative windows, and chunk ingestion all route here):
        ensure ``slot`` owns a PRIVATE page at table index ``idx`` —
        grant when unmapped, copy-on-write when present-but-shared
        (no-op when already private) — preempting victims on
        exhaustion.  ``still_mine()`` is the caller's occupancy check;
        returns False when the caller itself was evicted paying for
        its page."""
        while True:
            try:
                if self.slots.table[slot, idx] == NULL_PAGE:
                    self.slots.grant(slot, idx)
                else:
                    self.slots.cow(slot, idx)
                return True
            except CacheOutOfPagesError:
                self._evict_for_pages()
                if not still_mine():
                    return False

    def _ensure_draft_range(self, s: int, lo: int, hi: int) -> None:
        """Draft-pool companion of :meth:`_ensure_write_range`.  Draft
        pages never evict anyone: on exhaustion the slot's speculation
        is simply DISABLED (acceptance forced to 0 as data — the plain
        greedy path through the same executable) and its draft pages
        return to the heap; correctness never depends on the draft."""
        draft = self.draft_slots
        st = self._states[s]
        if (st is None or not self._spec_host[s]
                or not self._spec_live[s] or not draft._active[s]):
            return
        last_real = (len(st.request.prompt)
                     + st.request.max_new_tokens - 2)
        hi = min(hi, last_real, draft.max_len - 1)
        if hi < lo:
            return
        ps = draft.page_size
        try:
            for idx in range(max(lo, 0) // ps, hi // ps + 1):
                if draft.table[s, idx] == NULL_PAGE:
                    draft.grant(s, idx)
        except CacheOutOfPagesError:
            draft.free(s)
            self._spec_host[s] = False

    def _prepare_spec_tick(self) -> None:
        """Tick-boundary maintenance for the SPECULATIVE tick.  The
        window writes positions ``[pos, pos + K]``; with the overlap
        pipeline, one dispatched-but-unfetched tick may have advanced
        the device pos by up to ``K + 1`` already — the host learns the
        accepted length one tick late — so grants cover the worst case
        (``_page_pos`` is the FETCH-time mirror here, unlike the
        non-speculative dispatch-time advance).  Over-granted pages are
        not waste: pos only grows, so they are used within a few ticks
        or freed at retirement."""
        W = self.engine_cfg.spec_k + 1
        pend = self._pending
        for s in range(self.engine_cfg.n_slots):
            st = self._states[s]
            if st is None:
                continue
            base = int(self._page_pos[s])
            inflight = (pend is not None and bool(pend["active"][s])
                        and pend["reqs"][s] is st.request)
            hi = base + (2 if inflight else 1) * W - 1
            if (self._ensure_write_range(s, base, hi)
                    and self._spec_model):
                self._ensure_draft_range(s, base, hi)
        if (self._dev_table is None
                or self._table_uploaded != self.slots.table_version):
            self._dev_table = jnp.asarray(self.slots.table)
            self._table_uploaded = self.slots.table_version
        if self._spec_model:
            d = self.draft_slots
            if (self._dev_dtable is None
                    or self._dtable_uploaded != d.table_version):
                self._dev_dtable = jnp.asarray(d.table)
                self._dtable_uploaded = d.table_version
        spec = (self._spec_host & self._spec_live
                & self._decode_mask())
        if (self._dev_spec_host is None
                or not np.array_equal(spec, self._dev_spec_host)):
            self._dev_spec = jnp.asarray(spec)
            self._dev_spec_host = spec

    def _draft_prefill_fn(self, bucket: int, k: int) -> Callable:
        fn = self._draft_prefill_fns.get((bucket, k))
        if fn is None:
            dcfg = self.draft_cfg

            def _prefill(params, padded, true_lens):
                self._prefill_traces += 1
                obs_tracing.record_compile("serving_draft_prefill")
                cache = T.init_cache(dcfg, k, bucket)
                return T.prefill(params, padded, cache, dcfg,
                                 true_len=true_lens)

            fn = self._jit(
                _prefill,
                in_s=self._shard and (self._sh_draft_params, self._sh_R,
                                      self._sh_R),
                out_s=self._shard and (self._sh_R, self._sh_prefill))
            self._draft_prefill_fns[(bucket, k)] = fn
        return fn

    def _spec_admit(self, slots: List[int], reqs: List[Request]) -> None:
        """Per-admission speculative bookkeeping.  The per-request
        opt-out lands in the slot mask; the n-gram draft gets the
        prompt row scattered into the device history; the model draft
        prefills its own paged pool with the FULL prompt (the draft
        has no prefix registry — one extra shallow forward per
        admission group, never fetched, so no host sync).  A draft
        pool that cannot hold the prompt disables speculation for the
        slot, never the request."""
        if not self._spec:
            return
        for slot, req in zip(slots, reqs):
            # A SAMPLED request never speculates: drafts are verified
            # by argmax agreement, which a sampled stream would reject
            # every tick — the kernel also forces its acceptance to 0
            # as defense in depth, this just skips paying for drafts.
            self._spec_host[slot] = (req.speculative is not False
                                     and req.temperature <= 0.0
                                     and self._spec_runtime_enabled)
        if not self._spec_model:
            # FULL-WIDTH rows: zero the whole row, not just the prompt
            # bucket — a previous tenant's committed tokens beyond the
            # bucket would otherwise survive in the history and could
            # be gathered into this request's drafts once its pos
            # grows past them (wasted verify width, and no request's
            # tokens should transit another's draft path).  Compile
            # set: one (k, max_len) shape per admission size k.
            k = len(slots)
            padded = np.zeros((k, self.slots.max_len), np.int32)
            for i, r in enumerate(reqs):
                padded[i, :len(r.prompt)] = r.prompt
            self._dev_history = self._hist_land(
                self._history(), np.asarray(slots, np.int32), padded)
            for slot in slots:
                self._spec_stale[slot] = False
            return
        draft = self.draft_slots
        for slot, req in zip(slots, reqs):
            if not self._spec_host[slot]:
                continue
            if not self._spec_live[slot]:
                # Adaptively disabled: skip the draft prefill now; a
                # probe rebuilds from prompt + emitted if it re-enables.
                self._spec_stale[slot] = True
                continue
            draft.acquire(slot)
            try:
                for idx in range(
                        (len(req.prompt) - 1) // draft.page_size + 1):
                    draft.grant(slot, idx)
            except CacheOutOfPagesError:
                draft.free(slot)
                self._spec_host[slot] = False
        live = [(s, r) for s, r in zip(slots, reqs)
                if self._spec_host[s] and draft._active[s]]
        if not live:
            return
        k = len(live)
        bucket = self._bucket(max(len(r.prompt) for _, r in live))
        padded = np.zeros((k, bucket), np.int32)
        lens = np.zeros((k,), np.int32)
        for i, (_, r) in enumerate(live):
            padded[i, :len(r.prompt)] = r.prompt
            lens[i] = len(r.prompt)
        _, pre = self._draft_prefill_fn(bucket, k)(
            self.draft_params, jnp.asarray(padded), jnp.asarray(lens))
        self._prefill_calls += 1
        draft.land([s for s, _ in live], pre, lens, start=0)
        for s, _ in live:
            self._spec_stale[s] = False

    def _reset_spec_state(self) -> None:
        """Reset ALL per-slot speculative state (opt-out mask, adaptive
        live/idle/window, staleness) — the ONE copy the restart,
        terminal, and post-warmup paths share."""
        self._spec_host[:] = True
        self._spec_live[:] = True
        self._spec_win[:] = 0
        self._spec_idle[:] = 0
        self._spec_stale[:] = False

    def _history(self):
        """The n-gram draft's device-resident committed-token buffer,
        created on first use (ONE definition of its shape)."""
        if self._dev_history is None:
            self._dev_history = jnp.zeros(
                (self.engine_cfg.n_slots, self.slots.max_len), jnp.int32)
        return self._dev_history

    def _spec_adapt(self, s: int, accepted: int) -> None:
        """Window the slot's acceptance; auto-disable speculation when
        it falls under the floor (spec_adaptive).  Disabling is pure
        data — output is identical either way — it just stops paying
        draft+verify for a stream the draft cannot predict."""
        if not self.engine_cfg.spec_adaptive:
            return
        self._spec_win[s, 0] += self.engine_cfg.spec_k
        self._spec_win[s, 1] += accepted
        if (self._spec_win[s, 0]
                >= self.engine_cfg.spec_window * self.engine_cfg.spec_k):
            rate = self._spec_win[s, 1] / self._spec_win[s, 0]
            if rate < self.engine_cfg.spec_min_acceptance:
                self._spec_live[s] = False
                self._spec_idle[s] = 0
                st = self._states[s]
                # submit-time handle, same reason as _evict_for_pages
                srec = st.request.future._spans if st is not None \
                    else None
                if (srec is not None and st is not None
                        and st.request.trace is not None):
                    try:
                        srec.request_event(
                            st.request.trace, "spec_fallback",
                            {"slot": s, "acceptance": round(rate, 4)})
                    except Exception:  # pragma: no cover
                        pass
                if self._spec_model:
                    # A disabled slot's draft POOL decays even during
                    # spec ticks (no pages are granted for it, so its
                    # writes route to the NULL page) — the probe must
                    # rebuild it or re-enabling would draft against a
                    # garbage gap and re-disable forever.  The n-gram
                    # HISTORY stays current through spec ticks (the
                    # kernel commits every active row's tokens), so it
                    # only goes stale on all-plain fallback ticks.
                    self._spec_stale[s] = True
            self._spec_win[s] = 0

    def _spec_probe_clock(self, s: int) -> None:
        """Tick the disabled slot's probe clock; at spec_probe_period
        re-enable speculation for one evaluation window (rebuilding
        any draft state plain ticks staled) so a stream that BECOMES
        predictable gets speculation back."""
        if not self._spec_live[s] and self._spec_host[s]:
            self._spec_idle[s] += 1
            if self._spec_idle[s] >= self.engine_cfg.spec_probe_period:
                if self._spec_stale[s] and not self._respec_slot(s):
                    self._spec_idle[s] = 0  # rebuild failed: try later
                    return
                self._spec_stale[s] = False
                self._spec_live[s] = True
                self._spec_idle[s] = 0
                self._spec_win[s] = 0

    def _respec_slot(self, s: int) -> bool:
        """Rebuild slot ``s``'s draft state after plain ticks staled it
        — the committed stream is ``prompt + tokens emitted this
        tenancy``: re-land the n-gram history row, or re-prefill the
        draft pool up to (but excluding) the pending input token, just
        like admission does."""
        st = self._states[s]
        if st is None:
            return False
        fut = st.request.future
        toks = fut.tokens_so_far()
        gen = toks[len(toks) - st.n_generated:] if st.n_generated else []
        committed = list(st.request.prompt) + [int(t) for t in gen]
        if not self._spec_model:
            # FULL-WIDTH row (not the prompt's bucket): committed
            # length grows with every probe, and a bucketed landing
            # here would JIT-compile a new shape mid-serving for each
            # new length class — one (1, max_len) shape serves every
            # probe forever.
            padded = np.zeros((1, self.slots.max_len), np.int32)
            padded[0, :len(committed)] = committed
            self._dev_history = self._hist_land(
                self._history(), np.asarray([s], np.int32), padded)
            return True
        draft = self.draft_slots
        # The probe fires at FETCH time, after _page_pos advanced for
        # the tick being retired but before its token is emitted — at
        # that instant the cache-committed set is exactly prompt + all
        # tokens emitted so far (the incoming token, this tick's, is
        # the next pending input and is NOT in `committed` yet).  So
        # the FULL list re-prefills, landing draft pos = len(committed)
        # = the device pos; the in-kernel entry sync covers any
        # overlap-pipeline skew beyond that.
        body = committed
        if not body:
            return False
        if not draft._active[s]:
            draft.acquire(s)
        try:
            for idx in range((len(body) - 1) // draft.page_size + 1):
                if draft.table[s, idx] == NULL_PAGE:
                    draft.grant(s, idx)
        except CacheOutOfPagesError:
            draft.free(s)
            return False
        # FIXED full-width prefill shape (max_len, 1), like the n-gram
        # branch: the committed length grows past every warmed prompt
        # bucket, and a bucketed call here would JIT-compile inside a
        # serving step (and inside the watchdog budget) at probe time.
        # warmup() pre-compiles this one shape.
        width = self.slots.max_len
        padded = np.zeros((1, width), np.int32)
        padded[0, :len(body)] = body
        lens = np.asarray([len(body)], np.int32)
        _, pre = self._draft_prefill_fn(width, 1)(
            self.draft_params, jnp.asarray(padded), jnp.asarray(lens))
        self._prefill_calls += 1
        draft.land([s], pre, lens, start=0)
        return True

    @staticmethod
    def _pick(logits, pos, s_t, s_k, s_p, s_key):
        """The ONE in-tick next-token pick, shared by every tick body:
        the token being chosen sits at logical position ``pos + 1``
        (``pos`` = the pool position at tick ENTRY — the input token's
        slot), so its PRNG key is ``fold_in(fold_in(key, pos + 1), 0)``
        — exactly the per-request ``sample_decode`` oracle's schedule
        for row 0 (tests/test_sampling.py).  Greedy rows short to
        argmax inside the kernel."""
        return T.sample_token_rows(logits, s_t, s_k, s_p, s_key,
                                   pos + 1, jnp.zeros_like(pos))

    def _run_tick(self, tokens_dev, active_dev):
        """Dispatch ONE compiled decode tick.  Returns ``(next-token
        device vector, pending extras)`` — the extras are what
        :meth:`_retire_pending` fetches: plain ticks carry ``nxt``
        ``(S,)`` / ``mx`` ``(S,)``; speculative ticks carry the full
        target-token window ``nxt`` ``(S, W)``, ``mx`` ``(S, W)``, the
        per-slot accepted length ``acc`` ``(S,)``, and the dispatch-
        time speculation mask."""
        s_t, s_k, s_p, s_key = self._samp.device()
        if self._spec:
            if not self._dev_spec_host.any():
                # Nobody speculating this tick: the plain one-token
                # executable earns the same greedy token at plain cost.
                # Draft state (history / draft cache) goes stale for
                # the slots it skips — marked for rebuild at re-probe.
                self._spec_stale |= self.slots.active_mask()
                nxt, mx, cache = self._plain_tick_fn(
                    self.params, tokens_dev, active_dev,
                    self._dev_table, self.slots.cache,
                    s_t, s_k, s_p, s_key)
                self.slots.cache = cache
                return nxt, {"nxt": nxt, "mx": mx}
            if self._spec_model:
                nxt, t, mx, acc, pool, dpool = self._tick_fn(
                    self.params, self.draft_params, tokens_dev,
                    active_dev, self._dev_spec, self._dev_table,
                    self._dev_dtable, self.slots.cache,
                    self.draft_slots.cache, s_t, s_k, s_p, s_key)
                self.draft_slots.cache = dpool
            else:
                nxt, t, mx, acc, pool, hist = self._tick_fn(
                    self.params, tokens_dev, active_dev, self._dev_spec,
                    self._dev_table, self.slots.cache,
                    self._history(), s_t, s_k, s_p, s_key)
                self._dev_history = hist
            self.slots.cache = pool
            return nxt, {"nxt": t, "mx": mx, "acc": acc,
                         "spec": self._dev_spec_host.copy()}
        if self.engine_cfg.paged:
            nxt, mx, cache = self._tick_fn(
                self.params, tokens_dev, active_dev, self._dev_table,
                self.slots.cache, s_t, s_k, s_p, s_key)
        else:
            nxt, mx, cache = self._tick_fn(
                self.params, tokens_dev, active_dev, self.slots.cache,
                s_t, s_k, s_p, s_key)
        self.slots.cache = cache
        return nxt, {"nxt": nxt, "mx": mx}

    def _update_page_gauges(self) -> None:
        if not self.engine_cfg.paged:
            return
        # Statics re-asserted too: benchmarks swap in a fresh
        # ServingMetrics after warmup, which would otherwise zero them.
        self.metrics.kv_pages_total.set(self.slots.n_pages)
        self.metrics.kv_bytes_per_token.set(self.slots.bytes_per_token)
        self.metrics.kv_pages_free.set(self.slots.free_pages)
        self.metrics.kv_pages_shared.set(self.slots.pages_shared)

    # -- the tick ----------------------------------------------------------

    def step(self) -> bool:
        """One SUPERVISED engine tick: admit up to K requests into free
        slots, then one masked decode over all S slots.  Returns True
        if any work was done (False = idle; callers may sleep).

        An exception anywhere in the tick does not propagate: every
        in-flight future is resolved with a typed
        :class:`EngineFailedError` and the engine restarts (fresh slot
        cache, bounded attempts, exponential backoff) — or goes
        terminally ``failed`` when the budget is exhausted."""
        if self._health == FAILED:
            return False
        with self._hb_lock:
            self._tick_started = time.monotonic()
        try:
            faults = self.engine_cfg.faults
            if faults is not None:
                faults.probe("watchdog")  # a "hang" here stalls the tick
            with self._lock:
                worked = self._reclaim_cancelled()
                worked = self._admit_pending() or worked
                if self.engine_cfg.overlap:
                    worked = self._decode_tick_overlapped() or worked
                else:
                    worked = self._decode_tick() or worked
                self.metrics.queue_depth.set(self.scheduler.depth)
                self.metrics.slot_occupancy.set(self.slots.occupancy)
                self._update_page_gauges()
        except Exception as exc:  # supervised: ANY tick failure recovers
            with self._hb_lock:
                self._tick_started = None
                stalled = self._stalled
            # A stalled tick that ends by RAISING is still one incident:
            # the watchdog already counted it when it declared the stall.
            self._recover(exc, counted=stalled)
            return True
        with self._hb_lock:
            self._tick_started = None
            self._last_tick_done = time.monotonic()
            stalled = self._stalled
        if stalled:
            # The watchdog declared us dead mid-tick but the tick DID
            # return: futures are already resolved; restart the engine
            # through the same supervised path (no double-counting —
            # the watchdog already counted the failure).
            self._recover(EngineStalledError(
                f"tick exceeded the {self.engine_cfg.tick_timeout}s "
                f"watchdog budget"), counted=True)
            return True
        # Clean tick: recover health, reset the consecutive-failure
        # budget the supervised restarts draw from.
        if self._consec_failures or self._health == DEGRADED:
            self._consec_failures = 0
            if self._health == DEGRADED:
                self._set_health(HEALTHY)
        # Autotuner hook, OUTSIDE the step lock: a knob apply
        # re-acquires it, which makes every swap a clean tick-boundary
        # transaction (tuning/tuner.py).  Clean ticks only — a
        # recovering tick's window would score restart noise.
        if self._tuner is not None:
            try:
                self._tuner.on_tick(self, worked)
            except Exception:  # tuning must never take serving down
                self._tuner = None
        return worked

    def _reclaim_cancelled(self) -> bool:
        """Free slots whose requests were cancelled caller-side — their
        futures resolve with the tokens so far (reason "cancelled") —
        or whose futures were already resolved externally (a submit
        that raced a drain); either way the slot must not leak."""
        worked = False
        for s, st in enumerate(self._states):
            if st is None:
                continue
            fut = st.request.future
            if fut.done():
                self._states[s] = None
                self._release_slot(s)
                worked = True
                continue
            if fut.cancel_requested:
                fut._finish("cancelled")
                self.metrics.cancelled.inc()
                self._states[s] = None
                self._release_slot(s)
                worked = True
        for s in list(self._ingest):
            worked = self._reap_ingest(s) or worked
        return worked

    def _reap_ingest(self, slot: int) -> bool:
        """Release an ingesting slot whose request can no longer run
        — future already resolved (raced a drain) or cancellation
        pending — in ONE copy (shared by the per-tick reclaim sweep
        and the chunk step's entry check).  Returns True if the slot
        was reaped."""
        ing = self._ingest.get(slot)
        if ing is None:
            return False
        fut = ing.request.future
        if not (fut.done() or fut.cancel_requested):
            return False
        if not fut.done():
            fut._finish("cancelled")
            self.metrics.cancelled.inc()
        self._ingest.pop(slot, None)
        self._release_slot(slot)
        return True

    def _admit_pending(self) -> bool:
        # Tick-boundary deadline sweep: resolve EVERY dead queued
        # request (lapsed deadline, cancel, raced drain) wherever it
        # sits — a doomed request's 504 must not wait behind a long
        # admission stall for take() to reach it.
        swept = self.scheduler.sweep()
        self._tick_prefill_spent = 0
        self._tick_ingested = set()
        # Slot-pressure preemption BEFORE the take: a strictly
        # better-class arrival claims a slot from the worst occupant
        # (suspended, never lost) instead of waiting out its decode.
        preempted = self._preempt_for_slots()
        pages_fn = None
        if self.engine_cfg.paged:
            # Page back-pressure: the take stops (requests WAIT,
            # scheduling order intact) when the next admission's
            # private pages would overdraw the free heap — typed
            # starvation-free admission control instead of silent
            # over-allocation.
            budget = self.slots.free_pages
            # Clamp the plan to the deepest the free heap can ever get
            # (pool minus registry-pinned prefix pages): the plan's
            # growth-margin page is a heuristic, and an unclamped
            # demand above that depth would park a request the
            # submit-time fit check accepted at the FCFS head FOREVER
            # — admit it when the pool is as free as it gets and let
            # on-demand grant/preemption resolve the tail instead.
            pinned = sum(
                len(e.pages) for e in self._prefixes.values()
                if e.pages is not None and e.epoch == self._cache_epoch)
            attainable = max(self.slots.n_pages - pinned, 1)
            reserved = 0

            def pages_fn(req):
                nonlocal reserved
                need = min(self._plan_pages(req), attainable)
                if reserved + need > budget:
                    return False
                reserved += need
                return True

        # Per-tick prefill TOKEN budget (chunked prefill): admissions
        # past the first stop once the tick's ingestion budget is
        # spent — they wait one tick, bounding how long the decode
        # batch stalls on prompt ingestion.  The FIRST admission is
        # always allowed (liveness: a chunked one costs <= one chunk
        # by construction, and a short over-budget prompt must not
        # park forever).
        tok_budget = self.engine_cfg.prefill_chunk_tokens
        n_admit = 0

        def admit_fn(req):
            nonlocal n_admit
            if pages_fn is not None and not pages_fn(req):
                return False
            if tok_budget:
                cost = self._prefill_cost(req)
                if n_admit and self._tick_prefill_spent + cost \
                        > tok_budget:
                    return False
                if not self._chunked(req):
                    # A chunked admission's spend is counted by its
                    # _ingest_step — counting it here too would
                    # double-charge the tick.
                    self._tick_prefill_spent += cost
            n_admit += 1
            return True

        reqs = self.scheduler.take(
            self.slots.free_count, bucket_fn=self._group_key,
            admit_fn=admit_fn if (pages_fn or tok_budget) else None)
        if not reqs and self.scheduler.depth \
                and self.engine_cfg.resume and self.journal is not None:
            # PAGE-pressure preemption: an empty take with a non-empty
            # queue means the scheduling-order head was blocked — by
            # the page budget (slot pressure already ran pre-take; the
            # token budget and bucket truncation never block the FIRST
            # candidate).  If the head outranks the worst occupant,
            # suspend that occupant so its pages free the head next
            # tick; within a class the head keeps waiting, as ever.
            best = self.scheduler.peek_best_rank()
            occ = self._occupants()
            if best is not None and occ:
                worst = max(occ)
                if worst[0] > best:
                    self._preempt(worst[2], "page_pressure")
        self._taken = list(reqs)
        live: List[Request] = []
        for req in reqs:
            if req.future.done():  # resolved while taken (raced drain)
                self._taken.remove(req)
                continue
            if req.future.cancel_requested:
                req.future._finish("cancelled")
                self.metrics.cancelled.inc()
                self._taken.remove(req)
                continue
            live.append(req)
        if live:
            self._admit_batch(live)
        self._taken = []
        advanced = self._advance_ingest()
        return bool(reqs) or advanced or bool(swept) or preempted

    def _prefill_fn(self, bucket: int, k: int) -> Callable:
        fn = self._prefill_fns.get((bucket, k))
        if fn is None:
            def _prefill(params, padded, true_lens):
                self._prefill_traces += 1
                obs_tracing.record_compile("serving_prefill")
                cache = T.init_cache(self.cfg, k, bucket)
                return T.prefill(params, padded, cache, self.cfg,
                                 true_len=true_lens)

            fn = self._jit(
                _prefill,
                in_s=self._shard and (self._sh_params, self._sh_R,
                                      self._sh_R),
                out_s=self._shard and (self._sh_R, self._sh_prefill))
            self._prefill_fns[(bucket, k)] = fn
        return fn

    def _bucket(self, n: int) -> int:
        b = max(self.engine_cfg.min_prefill_bucket, 1)
        while b < n:
            b *= 2
        return min(b, self.slots.max_len)

    def _first_tokens(self, reqs: List[Request], logits) -> np.ndarray:
        """An admission group's FIRST tokens from its prefill logits —
        the prefill IS the first decode step.  All-greedy groups keep
        the plain argmax fetch; any sampled member routes the whole
        group through the shared sampling kernel (greedy rows still
        argmax inside it), each row drawing with its own seed at key
        index ``len(prompt)`` — for a RESUMED request the prompt
        already includes the emitted tokens, so the index continues
        the stream exactly where the last life stopped."""
        if all(r.temperature <= 0.0 for r in reqs):
            return np.asarray(jnp.argmax(logits, axis=-1))
        k = len(reqs)
        temp = np.array([r.temperature for r in reqs], np.float32)
        tk = np.array([r.top_k for r in reqs], np.int32)
        tp = np.array([r.top_p for r in reqs], np.float32)
        keys = np.stack([seed_key(r.seed) for r in reqs])
        pos = np.array([len(r.prompt) for r in reqs], np.int32)
        return np.asarray(self._first_sample(
            logits, jnp.asarray(temp), jnp.asarray(tk), jnp.asarray(tp),
            jnp.asarray(keys), jnp.asarray(pos)))

    def _admit_batch(self, reqs: List[Request]) -> None:
        """ONE bucketed batch-K prefill admits the whole group (the
        burst-TTFT lever: K prompts cost one forward pass, not K) ->
        one insert scatter lands all K in their slots -> one host
        fetch yields the K first tokens (prefill logits ARE the first
        greedy step).  The scheduler's bucket-uniform take keeps the
        group on one bucket, so the compile set is buckets x K."""
        if (self.engine_cfg.paged and len(reqs) == 1
                and self._chunked(reqs[0])):
            # Long prompt: chunked ingestion (singleton group by
            # construction of _group_key) — it rides the tick, it
            # does not stall it.
            self._admit_chunked(reqs[0])
            return
        faults = self.engine_cfg.faults
        if faults is not None:
            faults.probe("prefill")
        t_adm = time.monotonic()
        for req in reqs:
            if req.trace is not None and req.trace.admitted_at is None:
                # queue-wait ends here; a RESUMED re-admission keeps
                # its first life's stamps (prefill_s would otherwise
                # go negative against the original first_token_at)
                req.trace.admitted_at = t_adm
                self.metrics.observe_queue_wait(
                    req.priority, t_adm - req.submitted_at)
        if self.engine_cfg.paged:
            slots, reqs, firsts, synced = self._admit_paged(reqs)
            if not reqs:
                return
        else:
            slots, reqs, firsts = self._admit_contiguous(reqs)
            synced = True
        if synced:
            # Attach-only paged admission (prompt == prefix) fetches
            # nothing — the counter tracks real blocking syncs only.
            self.metrics.host_syncs.inc()
        now = time.monotonic()
        for slot, req, first in zip(slots, reqs, firsts):
            if req.future.ttft is None:
                # A RESUMED request already served its first token in a
                # previous life — its TTFT was honest then and must not
                # be rewritten by the re-admission.
                ttft = now - req.submitted_at
                req.future.ttft = ttft
                self.metrics.observe_ttft(req.priority, ttft)
            if req.trace is not None:
                req.trace.slot = slot
                if req.trace.first_token_at is None:
                    req.trace.first_token_at = now
            self.metrics.admitted.inc()
            # The slot's sampling columns land BEFORE the next decode
            # dispatch (step() admits first) — an async re-upload of
            # four (S,)-rows, no sync.  Greedy requests write zeros,
            # which IS the greedy row.
            self._samp.set(slot, temperature=req.temperature,
                           top_k=req.top_k, top_p=req.top_p,
                           seed=req.seed)
            self._states[slot] = _SlotState(request=req,
                                            last_token=int(first),
                                            n_generated=0)
            self._emit(slot, int(first))
            self._taken.remove(req)  # landed: _states[slot] owns it now
        if self._dev_tokens is not None:
            # Land the first tokens in the device-resident token vector
            # (a slot retired by its own first token — EOS at admission
            # — is inactive in the mask; its value is a don't-care).
            vals = np.zeros(self.engine_cfg.n_slots, np.int32)
            mask = np.zeros(self.engine_cfg.n_slots, bool)
            for slot, first in zip(slots, firsts):
                vals[slot] = int(first)
                mask[slot] = True
            self._dev_tokens = self._merge_tokens(
                self._dev_tokens, jnp.asarray(vals), jnp.asarray(mask))

    def _admit_contiguous(self, reqs: List[Request]):
        """Slot-contiguous admission: one batch-K prefill + one
        insert scatter (the pre-paging layout, kept as the A/B
        oracle)."""
        k = len(reqs)
        bucket = max(self._bucket(len(r.prompt)) for r in reqs)
        padded = np.zeros((k, bucket), np.int32)
        lens = np.zeros((k,), np.int32)
        for i, req in enumerate(reqs):
            padded[i, :len(req.prompt)] = req.prompt
            lens[i] = len(req.prompt)
        logits, pre_cache = self._prefill_fn(bucket, k)(
            self.params, jnp.asarray(padded), jnp.asarray(lens))
        self._prefill_calls += 1
        slots: List[int] = []
        for _ in reqs:
            slot = self.slots.alloc()
            assert slot is not None  # take() is bounded by free_count
            slots.append(slot)
        self.slots.insert_batch(slots, pre_cache)
        firsts = self._first_tokens(reqs, logits)  # one sync for K
        return slots, reqs, firsts

    def _map_pages(self, slot: int, req: Request,
                   entry: Optional[_PrefixEntry]) -> None:
        """Build one slot's page table for admission: attach the shared
        prefix pages (refcount, no copy), COW the partially-filled
        prefix page if the suffix must write into it, grant fresh
        private pages for the rest of the prompt."""
        ps = self.slots.page_size
        n_idx = (len(req.prompt) - 1) // ps + 1
        if entry is None:
            for idx in range(n_idx):
                self.slots.grant(slot, idx)
            return
        p0 = len(entry.tokens)
        self.slots.attach(slot, entry.pages)
        if len(req.prompt) == p0:
            return  # attach-only; decode growth grants/COWs at dispatch
        first_new = p0 // ps
        if p0 % ps:
            # The last prefix page is partial and the suffix lands
            # inside it: copy-on-write BEFORE any write targets it.
            self.slots.cow(slot, first_new)
            first_new += 1
        for idx in range(first_new, n_idx):
            self.slots.grant(slot, idx)

    def _admit_paged(self, reqs: List[Request]):
        """Paged admission.  The group key guarantees every request
        here shares one prefill shape AND one matched prefix, so the
        whole group costs: zero prefill (prompt == prefix: attach pages
        + cached first token), or ONE suffix prefill attending the
        shared prefix pages, or ONE full prefill — then one landing
        scatter into granted pages.  A request whose page plumbing
        overdraws the pool (the admission budget is a heuristic, not a
        reservation) is resolved with the typed
        :class:`CacheOutOfPagesError` and the rest of the group
        proceeds."""
        entry = self._matched_prefix(reqs[0])
        if entry is not None:
            try:
                self._ensure_prefix(entry)
            except CacheOutOfPagesError:
                entry = None  # degrade: full prefill, no sharing
        p0 = len(entry.tokens) if entry is not None else 0
        slots: List[int] = []
        live: List[Request] = []
        for req in reqs:
            slot = self.slots.alloc()
            assert slot is not None  # take() is bounded by free_count
            try:
                self._map_pages(slot, req, entry)
            except CacheOutOfPagesError as e:
                self._release_slot(slot)  # releases whatever got mapped
                req.future.set_exception(e)
                self.metrics.rejected.inc()
                self._taken.remove(req)
                continue
            slots.append(slot)
            live.append(req)
        if not live:
            return [], [], [], False
        k = len(live)
        synced = True  # a prefill's argmax fetch — except attach-only
        if entry is not None:
            suf_lens = np.asarray([len(r.prompt) - p0 for r in live],
                                  np.int32)
            if int(suf_lens.max()) == 0:
                # The prompt IS the prefix: its K/V already exists —
                # admission is pure bookkeeping, and GREEDY sharers
                # reuse the cached first token.  SAMPLED sharers each
                # draw their own first token from the prefix's cached
                # last-position logits (one kernel call, same (k, V)
                # executable as a regular sampled admission).
                self.slots.set_pos(slots, [p0] * k)
                if any(r.temperature > 0.0 for r in live):
                    firsts = self._first_tokens(live, jnp.broadcast_to(
                        entry.logits, (k, entry.logits.shape[-1])))
                else:
                    firsts = np.asarray([entry.first_token] * k)
                    synced = False
            else:
                bucket = self._bucket(int(suf_lens.max()))
                padded = np.zeros((k, bucket), np.int32)
                for i, r in enumerate(live):
                    padded[i, :len(r.prompt) - p0] = r.prompt[p0:]
                pk, pv = self.slots.gather_prefix(entry.pages)
                logits, suf = self._suffix_prefill(
                    self.params, jnp.asarray(padded),
                    jnp.asarray(suf_lens), pk, pv, jnp.int32(p0))
                self._prefill_calls += 1
                self.slots.land(slots, suf, suf_lens, start=p0)
                firsts = self._first_tokens(live, logits)
        else:
            bucket = max(self._bucket(len(r.prompt)) for r in live)
            padded = np.zeros((k, bucket), np.int32)
            lens = np.zeros((k,), np.int32)
            for i, r in enumerate(live):
                padded[i, :len(r.prompt)] = r.prompt
                lens[i] = len(r.prompt)
            logits, pre = self._prefill_fn(bucket, k)(
                self.params, jnp.asarray(padded), jnp.asarray(lens))
            self._prefill_calls += 1
            self.slots.land(slots, pre, lens, start=0)
            firsts = self._first_tokens(live, logits)
        for slot, req in zip(slots, live):
            self._page_pos[slot] = len(req.prompt)
        self._spec_admit(slots, live)
        return slots, live, firsts, synced

    # -- chunked prefill (EngineConfig.prefill_chunk_tokens) ---------------

    def _decode_mask(self) -> np.ndarray:
        """Active mask for the DECODE tick: allocated slots minus
        those still ingesting their prompt chunk by chunk — an
        ingesting slot holds pages and occupancy but has no token
        stream to decode yet."""
        active = self.slots.active_mask()
        if self._ingest:
            active = active.copy()
            for s in self._ingest:
                active[s] = False
        return active

    def _admit_chunked(self, req: Request) -> None:
        """Admit ONE long-prompt request into a slot for CHUNKED
        ingestion: attach any matched shared prefix (refcount, no
        compute), open the ingest state, and land the first chunk on
        this tick's budget.  The slot decodes nothing until the last
        chunk's logits yield the first token
        (:meth:`_finish_ingest`)."""
        t_adm = time.monotonic()
        if req.trace is not None and req.trace.admitted_at is None:
            req.trace.admitted_at = t_adm
            self.metrics.observe_queue_wait(
                req.priority, t_adm - req.submitted_at)
        entry = self._matched_prefix(req)
        if entry is not None:
            try:
                self._ensure_prefix(entry)
            except CacheOutOfPagesError:
                entry = None  # degrade: chunk the whole prompt
        slot = self.slots.alloc()
        assert slot is not None  # take() is bounded by free_count
        p0 = 0
        if entry is not None:
            self.slots.attach(slot, entry.pages)
            p0 = len(entry.tokens)
        self._ingest[slot] = _IngestState(request=req, landed=p0,
                                          started=p0)
        self._page_pos[slot] = p0
        self.metrics.admitted.inc()
        self._taken.remove(req)  # the ingest state owns it now
        self._ingest_step(slot)

    def _ensure_ingest_pages(self, slot: int, lo: int, hi: int) -> bool:
        """Grant/COW the pages a chunk landing on ``[lo, hi]`` will
        write — the ingestion face of the ONE :meth:`_claim_page`
        protocol (COW covers the partially-filled last page of an
        attached prefix; grants cover the fresh chunk span).  Evicts
        through the preemption policy on exhaustion; returns False if
        ``slot`` itself was the victim."""
        ps = self.slots.page_size
        for idx in range(max(lo, 0) // ps, hi // ps + 1):
            if not self._claim_page(
                    slot, idx, lambda: slot in self._ingest):
                return False  # we were the youngest — we paid
        return True

    def _gather_landed(self, slot: int, lo: int):
        """The slot's already-landed K/V as a PREFIX block for its
        next chunk: the first ``pages_for(lo)`` table pages, padded to
        a power-of-two page count with NULL pages (their junk is
        masked out by the traced prefix length ``lo``), so the gather
        + suffix-prefill compile set is bounded by page-count buckets
        — chunk boundaries stay pure data."""
        n_pg = self.slots.pages_for(lo)
        pages = [int(self.slots.table[slot, i]) for i in range(n_pg)]
        padded = 1
        while padded < n_pg:
            padded *= 2
        pages += [NULL_PAGE] * (padded - n_pg)
        return self.slots.gather_prefix(pages)

    def _ingest_step(self, slot: int) -> bool:
        """Land ONE chunk of ``slot``'s prompt: grant/COW the chunk's
        pages, run the chunk through ``prefill_with_prefix`` attending
        the already-landed pages (position-wise bit-identical to a
        whole-prompt prefill), and scatter the chunk K/V into the
        slot's pages.  The final chunk's logits ARE the whole-prompt
        logits — :meth:`_finish_ingest` turns them into the first
        token.  Returns True if any work was done."""
        ing = self._ingest.get(slot)
        if ing is None:
            return False
        if self._reap_ingest(slot):
            return True
        req = ing.request
        fut = req.future
        if req.deadline is not None and time.monotonic() > req.deadline:
            # The caller is gone (504/timeout): retire with whatever a
            # previous life emitted instead of finishing an ingestion
            # nobody reads.
            fut._finish("deadline")
            self.metrics.completed.inc()
            self._ingest.pop(slot, None)
            self._release_slot(slot)
            return True
        faults = self.engine_cfg.faults
        if faults is not None:
            faults.probe("prefill_chunk")
        lo = ing.landed
        n = min(len(req.prompt) - lo,
                self.engine_cfg.prefill_chunk_tokens)
        if not self._ensure_ingest_pages(slot, lo, lo + n - 1):
            return True  # preempted paying for its own chunk
        # ONE bucket for every chunk — the full chunk width, with the
        # tail chunk right-padded and its real length as data
        # (true_len): a partial last chunk must not mint its own
        # compile shape mid-serving.
        bucket = self._bucket(self.engine_cfg.prefill_chunk_tokens)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = req.prompt[lo:lo + n]
        lens = jnp.asarray([n], jnp.int32)
        if lo == 0:
            logits, pre = self._prefill_fn(bucket, 1)(
                self.params, jnp.asarray(padded), lens)
            self._prefill_calls += 1
            self.slots.land([slot], pre, np.asarray([n]), start=0)
        else:
            pk, pv = self._gather_landed(slot, lo)
            logits, suf = self._suffix_prefill(
                self.params, jnp.asarray(padded), lens, pk, pv,
                jnp.int32(lo))
            self._prefill_calls += 1
            self.slots.land([slot], suf, np.asarray([n]), start=lo)
        self._tick_prefill_spent += n
        self._tick_ingested.add(slot)
        ing.landed = lo + n
        self._page_pos[slot] = ing.landed
        if ing.landed >= len(req.prompt):
            # Non-final chunks never fetch their logits (no host
            # sync); only this last one pays the first-token fetch.
            self._finish_ingest(slot, ing, logits)
        return True

    def _finish_ingest(self, slot: int, ing: _IngestState,
                       logits) -> None:
        """The last chunk landed: the chunk logits are the
        whole-prompt last-position logits, so the first token (greedy
        argmax or the sampled draw at key index ``len(prompt)``) is
        token-identical to an un-chunked admission's — from here the
        slot joins the decode mask like any other."""
        req = ing.request
        self._ingest.pop(slot, None)
        firsts = self._first_tokens([req], logits)
        self.metrics.host_syncs.inc()  # the first-token fetch blocks
        now = time.monotonic()
        first = int(firsts[0])
        if req.future.ttft is None:
            # A RESUMED request already served its first token in a
            # previous life — its TTFT was honest then.
            ttft = now - req.submitted_at
            req.future.ttft = ttft
            self.metrics.observe_ttft(req.priority, ttft)
        if req.trace is not None:
            req.trace.slot = slot
            if req.trace.first_token_at is None:
                req.trace.first_token_at = now
        self._samp.set(slot, temperature=req.temperature,
                       top_k=req.top_k, top_p=req.top_p, seed=req.seed)
        self._states[slot] = _SlotState(request=req, last_token=first,
                                        n_generated=0)
        self._page_pos[slot] = len(req.prompt)
        # Speculative bookkeeping BEFORE the emit — the same order as
        # the batch path (_spec_admit inside _admit_paged precedes
        # _emit): the first token may retire the request (max_new 1,
        # EOS) and free the slot, and acquiring a draft slot AFTER
        # that would re-activate a freed slot with no owner.
        if self._spec and self._spec_model:
            # A MODEL draft would prefill the entire long prompt in
            # one tick (and mint a draft compile shape per long-prompt
            # bucket) — exactly the stall chunking removes.  Degrade
            # the SLOT to plain greedy instead (output identical; the
            # n-gram draft keeps speculating — its history landing is
            # one cheap full-width scatter).
            self._spec_host[slot] = False
        else:
            self._spec_admit([slot], [req])
        self._emit(slot, first)
        if self._dev_tokens is not None:
            # Land the first token in the device-resident token vector
            # (a slot retired by its own first token is inactive in
            # the mask; its value is a don't-care).
            vals = np.zeros(self.engine_cfg.n_slots, np.int32)
            mask = np.zeros(self.engine_cfg.n_slots, bool)
            vals[slot] = first
            mask[slot] = True
            self._dev_tokens = self._merge_tokens(
                self._dev_tokens, jnp.asarray(vals), jnp.asarray(mask))

    def _advance_ingest(self) -> bool:
        """Advance in-progress chunked ingestions with this tick's
        remaining prefill-token budget, oldest request first.  The
        oldest ingestion gets a STARVATION GUARD: it advances one
        chunk even on a tick whose budget admissions already spent —
        unless a strictly better class is waiting for next tick's
        budget (per-tick prefill work then stays <= 2x the budget in
        the worst case, and ingestion can never be starved by
        equal-or-worse-class arrivals)."""
        if not self._ingest:
            return False
        chunk = self.engine_cfg.prefill_chunk_tokens
        worked = False
        oldest = True
        for slot in sorted(self._ingest,
                           key=lambda s: self._ingest[s].request.id):
            ing = self._ingest.get(slot)
            if ing is None:
                continue  # evicted by an earlier step's grant
            if self._tick_prefill_spent >= chunk:
                if not oldest or slot in self._tick_ingested:
                    break  # one chunk per slot per tick, budget spent
                best = self.scheduler.peek_best_rank()
                if (best is not None
                        and best < ing.request.priority_rank):
                    break  # yield the next tick's budget to the winner
            worked = self._ingest_step(slot) or worked
            oldest = False
        return worked

    def _emit(self, slot: int, tok: int) -> None:
        """Stream one token to the slot's future; retire on EOS,
        max-token, or cache-capacity exhaustion."""
        st = self._states[slot]
        if st is None:
            return
        if st.request.future.done():
            # Resolved externally: by the watchdog (stall declared while
            # the tick was in flight — recovery rebuilds slot state
            # anyway) or by a submit that raced a drain.  Reclaim the
            # slot here so it cannot leak and pin drain() forever.
            self._states[slot] = None
            self._release_slot(slot)
            return
        if st.request.future._add_token(tok) and self.journal is not None:
            # The journal mirrors the future EXACTLY: a token is
            # recorded iff the caller will see it, so a resume's
            # re-prefill (prompt + emitted) reproduces precisely the
            # oracle's state — never a token from a stale or
            # already-resolved row.
            self.journal.append(st.request.id, tok)
        st.last_token = tok
        st.n_generated += 1
        self.metrics.tokens_generated.inc()
        reason = None
        if st.request.eos_id is not None and tok == st.request.eos_id:
            reason = "eos"
        elif st.n_generated >= st.request.max_new_tokens:
            reason = "length"
        # Next decode tick would write at prompt + n_generated - 1 (the
        # first token came from prefill, no write) — retire at capacity.
        elif (len(st.request.prompt) + st.n_generated - 1
              >= self.slots.max_len):
            reason = "capacity"  # submit() sizing makes this unreachable
        # Deadline AFTER admission: the caller is gone (504/timeout) —
        # retire with the partial result instead of pinning the slot
        # until max_new_tokens on output nobody reads.  (A deadline that
        # lapses while QUEUED is a typed rejection — Scheduler.take.)
        elif (st.request.deadline is not None
              and time.monotonic() > st.request.deadline):
            reason = "deadline"
        if reason is not None:
            st.request.future._finish(reason)
            self.metrics.completed.inc()
            self._states[slot] = None
            self._release_slot(slot)

    def _decode_tick(self) -> bool:
        """The SYNCHRONOUS decode tick (``overlap=False``, the A/B
        baseline): upload tokens + mask, dispatch, fetch, and apply the
        bookkeeping all in the same step — the device idles through the
        host half, which is exactly what the pipeline hides."""
        if self.engine_cfg.paged and self.slots.active_count:
            if self._spec:
                self._prepare_spec_tick()  # window grants; may preempt
            else:
                self._prepare_paged_tick()  # grants/COWs; may preempt
        active = self._decode_mask()
        if not active.any():
            return False
        faults = self.engine_cfg.faults
        kind = faults.probe("decode_tick") if faults is not None else None
        tokens = np.zeros(self.engine_cfg.n_slots, np.int32)
        for s, st in enumerate(self._states):
            if st is not None:
                tokens[s] = st.last_token
        t0 = time.monotonic()
        nxt, extra = self._run_tick(
            jnp.asarray(tokens), jnp.asarray(active))
        if not self._spec:
            # Speculative ticks advance the mirror at FETCH (the
            # accepted length is data the host learns there).
            self._page_pos += active
        self.metrics.decode_ticks.inc()
        dt = time.monotonic() - t0
        self.metrics.tick_dispatch.observe(dt)
        tp = obs_tracing.get()
        if tp is not None:
            tp.tick_phase("tick_dispatch", t0, dt)
        # Same fetch-and-apply tail as the pipeline, just not deferred.
        self._retire_pending({
            **extra, "active": active,
            "reqs": [st.request if st is not None else None
                     for st in self._states],
            "kind": kind, "dispatched_at": t0,
        })
        return True

    def _decode_tick_overlapped(self) -> bool:
        """One PIPELINED decode step (``overlap=True``): dispatch tick
        N+1 FIRST — its token input is tick N's device-resident output,
        so no host value gates the dispatch — then fetch and apply tick
        N's results while the device is already computing N+1.  Host
        bookkeeping runs one tick behind the device; the identity
        snapshot in ``_pending`` keeps the lag safe
        (:meth:`_retire_pending`)."""
        worked = False
        faults = self.engine_cfg.faults
        if self.engine_cfg.paged and self.slots.active_count:
            # Page maintenance BEFORE the mask snapshot: a preemption
            # here must not be dispatched, and a grant/COW is host
            # bookkeeping + async uploads — nothing blocks on device.
            if self._spec:
                self._prepare_spec_tick()
            else:
                self._prepare_paged_tick()
        active = self._decode_mask()
        new_pending: Optional[Dict] = None
        if active.any():
            kind = (faults.probe("decode_tick")
                    if faults is not None else None)
            t0 = time.monotonic()
            if self._dev_tokens is None:
                # Pipeline (re)start: seed the device token vector from
                # host slot state.  After this the ONLY recurring
                # upload is the active mask, and only when it changes.
                tokens = np.zeros(self.engine_cfg.n_slots, np.int32)
                for s, st in enumerate(self._states):
                    if st is not None:
                        tokens[s] = st.last_token
                self._dev_tokens = jnp.asarray(tokens)
            if (self._dev_active_host is None
                    or not np.array_equal(active, self._dev_active_host)):
                self._dev_active = jnp.asarray(active)
                self._dev_active_host = active
            nxt, extra = self._run_tick(self._dev_tokens,
                                        self._dev_active)
            if not self._spec:
                self._page_pos += active  # spec: advanced at fetch
            self._dev_tokens = nxt  # tick N+2's input — never fetched
            self.metrics.decode_ticks.inc()
            dt = time.monotonic() - t0
            self.metrics.tick_dispatch.observe(dt)
            tp = obs_tracing.get()
            if tp is not None:
                tp.tick_phase("tick_dispatch", t0, dt)
            new_pending = {
                **extra, "active": active,
                "reqs": [st.request if st is not None else None
                         for st in self._states],
                "kind": kind, "dispatched_at": t0,
            }
            worked = True
        prev, self._pending = self._pending, new_pending
        if prev is not None:
            self._retire_pending(prev)
            worked = True
        return worked

    def _retire_pending(self, p: Dict) -> None:
        """Fetch a dispatched tick's results — THE one host sync of a
        steady-state step — and apply its bookkeeping.  The ONE copy of
        the nonfinite check and the emission rules, shared by the
        synchronous tick (applied immediately) and the overlapped
        pipeline (applied one tick late), so the two paths cannot
        diverge.

        Why the pipeline's lag preserves the greedy oracle: a slot's
        token is emitted only if the slot still holds the request it
        was computing for at dispatch time (the ``reqs`` identity
        snapshot).  A slot retired by EOS/length/deadline, cancelled,
        or re-admitted between dispatch and fetch fails that check and
        its stale row is DROPPED — so no token is ever emitted after
        EOS, and a freed slot can never leak a token into its next
        tenant.  The stale row's device write is harmless by the same
        write-before-attend argument as bucketed prefill padding
        (``decode_step_slots``).  (In the synchronous path the snapshot
        always matches — nothing can retire a slot between dispatch and
        this call within one locked step.)"""
        faults = self.engine_cfg.faults
        if faults is not None:
            faults.probe("decode_fetch")
        t0 = time.monotonic()
        nxt = np.asarray(p["nxt"])           # (S,) — or (S, W) spec
        mx = np.asarray(p["mx"])
        acc = np.asarray(p["acc"]) if "acc" in p else None
        self.metrics.host_syncs.inc()
        t1 = time.monotonic()
        self.metrics.tick_device_wait.observe(t1 - t0)
        active = p["active"]
        if p["kind"] == "nonfinite":  # injected: NaN logits
            mx = np.where(active if mx.ndim == 1 else active[:, None],
                          np.nan, mx)
        if not np.isfinite(mx[active]).all():
            raise EngineFailedError(
                "non-finite logits from decode tick (bad params or "
                "device fault)")
        lat = t1 - p["dispatched_at"]
        spec_k = self.engine_cfg.spec_k
        for s in np.nonzero(active)[0]:
            s = int(s)
            st = self._states[s]
            if st is None or st.request is not p["reqs"][s]:
                continue  # retired / re-admitted since dispatch: stale
            self.metrics.token_latency.observe(lat)
            tr = st.request.trace
            # Per-request tick DETAIL is buffered only when the
            # request's SUBMIT-TIME recorder is live (same handle its
            # begin/finish go through — one attribute read per slot);
            # whether the tuples ever leave the process is the
            # tail-sampling verdict at resolution.
            srec = st.request.future._spans
            if tr is not None:
                tr.decode_ticks += 1
                # dispatch-to-fetch latency of the tick that produced
                # this token: with the overlapped pipeline this is the
                # one-tick lag made visible in the breakdown.
                tr.host_sync_lag = lat
            if acc is None:
                self.metrics.tokens_per_tick.observe(1)
                if srec is not None and tr is not None:
                    if len(tr.ticks) < tr.MAX_TICKS:
                        tr.ticks.append((p["dispatched_at"], t1, 1))
                    else:
                        tr.ticks_overflow += 1
                if self._spec:
                    # A plain tick dispatched by the speculative
                    # engine (nobody speculating): pos advanced by
                    # exactly one — mirror it, and let the slot's
                    # probe clock run toward re-enabling.
                    self._page_pos[s] += 1
                    self._spec_probe_clock(s)
                self._emit(s, int(nxt[s]))
                continue
            # Speculative: the device committed acc+1 positions for
            # this slot whatever the host emits below (EOS/length may
            # truncate the run) — mirror the advance before emission
            # can retire the slot.
            n = int(acc[s]) + 1
            self._page_pos[s] += n
            if p["spec"][s]:
                self.metrics.spec_drafted.inc(spec_k)
                self.metrics.spec_accepted.inc(int(acc[s]))
                self.metrics.spec_wasted.inc(spec_k - int(acc[s]))
                self.metrics.spec_acceptance.observe(
                    int(acc[s]) / spec_k)
                self._spec_adapt(s, int(acc[s]))
            elif self._spec_host[s] and not self._spec_live[s]:
                # Speculating for OTHERS this tick while this slot sat
                # disabled: the n-gram history stays current (the
                # kernel commits every active row's tokens) and the
                # model draft was already marked stale at disable —
                # only the probe clock moves here.
                self._spec_probe_clock(s)
            # The tick-detail entry is appended BEFORE the emit loop —
            # the final _emit may retire the request and synchronously
            # run request_done, which writes tr.ticks — as a MUTABLE
            # list whose count is bumped per emission, so it records
            # the EMITTED count (EOS inside the accepted run truncates
            # what the caller sees; the autopsy's tick detail must sum
            # to the response, not to the device-committed acc+1).
            tick_entry = None
            if srec is not None and tr is not None:
                if len(tr.ticks) < tr.MAX_TICKS:
                    tick_entry = [p["dispatched_at"], t1, 0]
                    tr.ticks.append(tick_entry)
                else:
                    tr.ticks_overflow += 1
            emitted = 0
            for jt in range(n):
                if self._states[s] is not st:
                    # EOS / length / deadline retired the slot inside
                    # the accepted run: the greedy oracle would never
                    # emit the tail — drop it.
                    break
                if tick_entry is not None:
                    tick_entry[2] += 1
                self._emit(s, int(nxt[s, jt]))
                emitted += 1
            self.metrics.tokens_per_tick.observe(emitted)
        t2 = time.monotonic()
        self.metrics.tick_host.observe(t2 - t1)
        tp = obs_tracing.get()
        if tp is not None:
            tp.tick_phase("tick_device_wait", t0, t1 - t0)
            tp.tick_phase("tick_host", t1, t2 - t1)

    # -- failure recovery --------------------------------------------------

    def _fail_inflight(self, exc: BaseException) -> None:
        """Resolve every in-flight future (slots + taken-but-unlanded)
        with ``exc`` and reset slot bookkeeping — the TERMINAL path
        (and :meth:`terminate`): nothing will resume, so every future
        fails typed (which also purges its journal entry).  Idempotent
        per future (set_exception no-ops once done)."""
        for st in self._states:
            if st is not None:
                st.request.future.set_exception(exc)
        for req in self._taken:
            req.future.set_exception(exc)
        for ing in self._ingest.values():
            ing.request.future.set_exception(exc)
        self._clear_inflight_state()

    def _suspend_inflight(self, exc: BaseException) -> List[Request]:
        """The NON-terminal restart path: collect every in-flight
        request (slots + taken-but-unlanded) as a RESUME request —
        original prompt + journaled emitted tokens as the new prompt,
        the remaining decode budget, the original deadline, trace, and
        (crucially) the original live future — then reset slot
        bookkeeping exactly like :meth:`_fail_inflight`.  Requests
        that cannot resume (future already resolved, cancellation
        pending, no journal entry, or ``resume=False``) are resolved
        in place.  Returned in original FCFS order (by request id),
        ready for :meth:`Scheduler.requeue_front`."""
        resumed: List[Request] = []
        pending = [st.request for st in self._states if st is not None]
        pending += list(self._taken)
        # Mid-ingestion requests suspend too: no tokens were emitted
        # yet, so their journal frontier is the original prompt — the
        # resume re-ingests from scratch, oracle-exact (the chunk
        # boundary a crash interrupted is not observable in the
        # output).  Their landed chunks were real prefill compute the
        # re-ingestion repeats — record the honest wasted count
        # before the ingest map is cleared.
        pending += [ing.request for ing in self._ingest.values()]
        ingest_wasted = {ing.request.id: ing.landed - ing.started
                         for ing in self._ingest.values()}
        for req in pending:
            # The typed engine_restart edge on every interrupted
            # request's span, BEFORE its resolution/suspension is
            # decided — this is the restart path specifically, so
            # terminate()/drain force-resolves (plain _fail_inflight)
            # never mislabel themselves as restarts.
            srec = req.future._spans
            if srec is not None and req.trace is not None:
                try:
                    srec.request_event(req.trace, "engine_restart",
                                       {"epoch": self._epoch})
                except Exception:  # pragma: no cover
                    pass
            r = self._resume_or_fail(req, exc)
            if r is not None:
                if r.id in ingest_wasted:
                    r._resume_wasted = max(
                        getattr(r, "_resume_wasted", 0),
                        ingest_wasted[r.id])
                resumed.append(r)
        self._clear_inflight_state()
        resumed.sort(key=lambda r: r.id)
        self._resuming = len(resumed)
        return resumed

    def _resume_or_fail(self, req: Request,
                        exc: BaseException) -> Optional[Request]:
        fut = req.future
        if fut.done():
            return None  # resolved elsewhere (drain race, hard fail)
        if fut.cancel_requested:
            fut._finish("cancelled")
            self.metrics.cancelled.inc()
            return None
        entry = self.journal.get(req.id) if self.journal is not None \
            else None
        if entry is not None and self.engine_cfg.resume \
                and entry.remaining < 1:
            # Fully emitted: only the retirement bookkeeping was lost
            # — finish now.
            fut._finish("length")
            self.metrics.completed.inc()
            return None
        # Decode — greedy AND sampled (the PRNG key schedule is a pure
        # function of seed + token position) — is a pure function of
        # the token sequence, so prefilling prompt + emitted and
        # continuing yields output token-identical to an uninterrupted
        # run (_build_resume, shared with preemption).
        new = self._build_resume(req)
        if new is None:
            fut.set_exception(exc)
        return new

    def _clear_inflight_state(self) -> None:
        """Reset slot bookkeeping after a failure — including the slot
        allocator, so terminal states (no _restart to rebuild it) don't
        report phantom occupancy forever."""
        self._taken = []
        self._states = [None] * self.engine_cfg.n_slots
        self._ingest = {}
        self.slots.release_all()
        if self.draft_slots is not None:
            self.draft_slots.release_all()
        self._reset_spec_state()
        # release_all zeroed every page refcount, including the prefix
        # registry's pins: bump the epoch HERE (not just in _restart)
        # so stale entries can neither attach freed pages to a new
        # admission in the failing/terminal window nor underflow a
        # refcount on unregister — they lazily re-prefill instead.
        self._cache_epoch += 1
        self._reset_pipeline()

    def _reset_pipeline(self) -> None:
        """Drop the in-flight tick and the device-resident token state
        (restart/terminal paths — the old device arrays belong to a
        suspect cache lineage); the next dispatch reseeds from host
        slot state."""
        self._pending = None
        self._dev_tokens = None
        self._dev_active = None
        self._dev_active_host = None
        self._dev_table = None
        self._table_uploaded = -1
        self._page_pos[:] = 0
        self._dev_spec = None
        self._dev_spec_host = None
        self._dev_dtable = None
        self._dtable_uploaded = -1
        self._dev_history = None
        # Sampling columns: zero the host rows and drop the device
        # copy (it belonged to the dead lineage); re-admissions — the
        # resume path included — repopulate before the next dispatch.
        self._samp.reset()

    def _fail_queue(self, exc: BaseException) -> None:
        for req in self.scheduler.drain_pending():
            req.future.set_exception(exc)

    def _recover(self, exc: BaseException, *, counted: bool = False) -> None:
        """The supervised-restart path.  With ``resume`` (default),
        in-flight requests are SUSPENDED — journaled state, live
        futures — and re-admitted at the queue head after the restart,
        so a crash costs one tick plus one re-prefill instead of the
        request; without it (or at a terminal failure) they fail with
        the typed error, as before.  Either way the engine restarts
        (fresh SlotCache, exponential backoff) or goes terminally
        ``failed`` when ``max_restarts`` consecutive attempts are
        spent."""
        if not isinstance(exc, EngineFailedError):
            wrapped = EngineFailedError(f"engine tick failed: {exc!r}")
            wrapped.__cause__ = exc
            exc = wrapped
        with self._hb_lock:
            self._stalled = False
        if not counted:
            self.metrics.engine_failures.inc()
        with self._lock:
            self._consec_failures += 1
            attempt = self._consec_failures
            if (self._terminal
                    or attempt > self.engine_cfg.max_restarts):
                self._terminal = True
                self._fail_inflight(exc)
                self._set_health(FAILED)
                obs_tracing.instant("engine_failed", {
                    "consecutive_failures": attempt,
                    "max_restarts": self.engine_cfg.max_restarts})
                self._fail_queue(exc)
                self.metrics.queue_depth.set(0)
                self.metrics.slot_occupancy.set(0.0)
                return
            resume_ok = True
            faults = self.engine_cfg.faults
            if faults is not None:
                try:
                    faults.probe("restart_resume")
                except Exception:
                    # The resume machinery itself failed (chaos site:
                    # unreadable journal, corrupted state): degrade to
                    # the legacy fail-typed restart — never replay
                    # from state the engine cannot trust.
                    resume_ok = False
            if resume_ok:
                resumed = self._suspend_inflight(exc)
            else:
                resumed = []
                self._fail_inflight(exc)
        backoff = min(
            self.engine_cfg.restart_backoff * (2.0 ** (attempt - 1)),
            self.engine_cfg.restart_backoff_max)
        time.sleep(backoff)
        with self._lock:
            # terminate() may have landed during the backoff sleep — a
            # terminal declaration is never undone by a restart, and
            # the suspended requests must not dangle on it.
            if self._terminal:
                for req in resumed:
                    req.future.set_exception(exc)
                self._resuming = 0
                self._set_health(FAILED)
                self._fail_queue(exc)
                return
            self._restart()
            self._resuming = 0
            # The tuner's scoring window must not straddle the
            # restart: its baseline predates the crash, so the first
            # post-restart window would score the dead time + the
            # resume re-prefills against the knob setting — garbage
            # that can trip a spurious SLO rollback (and GET /tuning
            # would serve it).  Drop the baseline; the next worked
            # tick opens a fresh window.
            reset = getattr(self._tuner, "reset_window", None)
            if reset is not None:
                try:
                    reset()
                except Exception:  # pragma: no cover - tuner never
                    pass           # gates recovery
            if resumed:
                # Back to the HEAD of the queue in original FCFS order:
                # the next tick re-prefills prompt + emitted through the
                # ordinary bucketed batch admission (pages re-granted,
                # prefix sharing re-applied) and decode continues where
                # it left off.
                self.scheduler.requeue_front(resumed)
                for req in resumed:
                    self.metrics.resumed.inc()
                    wasted = getattr(req, "_resume_wasted",
                                     len(req.prompt))
                    if wasted:
                        self.metrics.resume_wasted_tokens.inc(wasted)
                    if self.journal is not None:
                        self.journal.note_resume(req.id)
                    # submit-time handle (begin/finish used it too)
                    srec = req.future._spans
                    if srec is not None and req.trace is not None:
                        # The typed resume edge on the request's own
                        # span: a postmortem sees WHICH requests the
                        # restart interrupted and what the re-prefill
                        # cost, not just the engine-wide instant.
                        try:
                            srec.request_event(
                                req.trace, "resume",
                                {"epoch": self._epoch,
                                 "wasted_tokens": wasted})
                        except Exception:  # pragma: no cover
                            pass
                obs_tracing.instant("requests_resumed", {
                    "count": len(resumed), "epoch": self._epoch})
                self.metrics.queue_depth.set(self.scheduler.depth)

    def _restart(self) -> None:
        """Fresh SlotCache + slot bookkeeping (the old device cache is
        suspect after a failure); queued requests survive and are
        admitted by the next tick.  Caller holds ``_lock``.

        A stall overwrites the health state with FAILED, so the
        restart target comes from the sticky ``_draining`` flag, not
        from the state it is replacing — a draining engine restarts
        DRAINING (still rejecting new work), everything else restarts
        DEGRADED."""
        self.slots = self._make_slots()
        self.draft_slots = self._make_draft_slots()
        self._reset_spec_state()
        self._states = [None] * self.engine_cfg.n_slots
        self._reset_pipeline()
        # The page pool is fresh: registered prefixes' pinned pages
        # died with the old cache — bump the epoch so entries lazily
        # re-prefill (once) on their next use.
        self._cache_epoch += 1
        if self.engine_cfg.paged:
            self.metrics.kv_pages_free.set(self.slots.free_pages)
            self.metrics.kv_pages_shared.set(0)
        with self._hb_lock:
            self._epoch += 1
            self._stalled = False
            self._stall_hard_failed = False
        self.metrics.engine_restarts.inc()
        obs_tracing.instant("engine_restart", {
            "epoch": self._epoch,
            "restarts": self.metrics.engine_restarts.value})
        self._set_health(DRAINING if self._draining else DEGRADED)

    # -- watchdog ----------------------------------------------------------

    def _stall_grace_s(self) -> float:
        g = self.engine_cfg.stall_grace
        return g if g is not None else self.engine_cfg.tick_timeout

    def _watchdog_loop(self) -> None:
        budget = self.engine_cfg.tick_timeout
        while not self._stop.is_set():
            time.sleep(self.engine_cfg.watchdog_interval)
            with self._hb_lock:
                started = self._tick_started
                epoch = self._epoch
                stalled = self._stalled
                hard = self._stall_hard_failed
            if started is None:
                continue
            age = time.monotonic() - started
            if not stalled:
                if age > budget:
                    self._declare_stalled(epoch, started)
            elif (self.engine_cfg.resume and not hard
                    and age > budget + self._stall_grace_s()):
                # The stall outlived its resume grace: presume the tick
                # never returns and restore the bounded-resolution
                # guarantee.
                self._stall_hard_fail(epoch, started)

    def _declare_stalled(self, epoch: int, started: float) -> None:
        """The tick has been running past its budget — a hung device
        call.  Runs on the WATCHDOG thread, which must never take
        ``_lock`` (the hung engine thread holds it): it only resolves
        futures (thread-safe, idempotent) and flips flags.  Slot
        bookkeeping is rebuilt by the engine thread if/when the hung
        tick returns; if it never returns, the engine stays ``failed``
        and nothing is left waiting on it.

        With ``resume`` the in-flight futures are NOT resolved here:
        their decode state is journaled, and a tick that returns
        within ``stall_grace`` resumes them token-exact through the
        supervised restart.  Only past budget + grace does
        :meth:`_stall_hard_fail` resolve everything typed."""
        with self._hb_lock:
            if (self._stalled or self._epoch != epoch
                    or self._tick_started != started):
                return  # the tick finished or recovery already ran
            self._stalled = True
        self.metrics.engine_failures.inc()
        obs_tracing.instant("watchdog_stall", {
            "epoch": epoch,
            "budget_s": self.engine_cfg.tick_timeout,
            "tick_age_s": round(time.monotonic() - started, 3)})
        self._set_health(FAILED)
        if self.engine_cfg.resume:
            return  # futures held for resume; hard fail at budget+grace
        exc = EngineStalledError(
            f"engine stalled: tick exceeded the "
            f"{self.engine_cfg.tick_timeout}s watchdog budget")
        # The engine thread is hung inside _lock, so _states is frozen —
        # snapshot-read it without the lock and resolve every future a
        # hung tick would otherwise strand (in-flight AND queued).
        for st in list(self._states):
            if st is not None:
                st.request.future.set_exception(exc)
        for req in list(self._taken):
            req.future.set_exception(exc)
        for ing in list(self._ingest.values()):
            ing.request.future.set_exception(exc)
        self._fail_queue(exc)

    def _stall_hard_fail(self, epoch: int, started: float) -> None:
        """Resume-mode backstop, still on the watchdog thread: the
        stalled tick spent its grace too.  Resolve every future typed
        — resolution purges each journal entry, so a zombie tick that
        returns even later finds nothing to resume and the restart
        comes up empty rather than replaying ghosts."""
        with self._hb_lock:
            if (self._stall_hard_failed or not self._stalled
                    or self._epoch != epoch
                    or self._tick_started != started):
                return
            self._stall_hard_failed = True
        exc = EngineStalledError(
            f"engine stalled: tick exceeded the "
            f"{self.engine_cfg.tick_timeout}s watchdog budget plus the "
            f"{self._stall_grace_s()}s resume grace")
        obs_tracing.instant("stall_hard_fail", {
            "epoch": epoch, "grace_s": self._stall_grace_s()})
        for st in list(self._states):
            if st is not None:
                st.request.future.set_exception(exc)
        for req in list(self._taken):
            req.future.set_exception(exc)
        for ing in list(self._ingest.values()):
            ing.request.future.set_exception(exc)
        self._fail_queue(exc)

    # -- background loop ---------------------------------------------------

    def start(self, idle_sleep: float = 0.001) -> None:
        """Run the tick loop in a daemon thread until :meth:`stop`; arm
        the watchdog when ``tick_timeout > 0``."""
        if self._thread is not None:
            return

        def loop():
            while not self._stop.is_set():
                if not self.step():
                    time.sleep(idle_sleep)

        self._stop.clear()
        self._thread = threading.Thread(target=loop,
                                        name="serving-engine", daemon=True)
        self._thread.start()
        if self.engine_cfg.tick_timeout > 0:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="serving-watchdog",
                daemon=True)
            self._watchdog.start()

    def stop(self, timeout: float = 10.0) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout)
        self._thread = None
        if self._watchdog is not None:
            self._watchdog.join(timeout)
            self._watchdog = None

    def warmup(self, prompt_lens: Sequence[int] = (1,)) -> None:
        """Drive the engine SYNCHRONOUSLY until every compile the given
        prompt lengths can demand exists: one prefill + cache-insert
        executable per (bucket, admission-batch-k) shape for k up to
        ``max_prefills_per_tick``, plus the decode tick (and, with
        ``overlap``, the token-merge op).  Call before :meth:`start` so
        first-request latency — and a tight watchdog ``tick_timeout`` —
        never pays XLA compilation (docs/serving.md "Watchdog tuning").
        The ONE definition of the warm sweep, shared by the chaos
        suite and ``benchmarks/serving.py``, so warm coverage tracks
        the engine's compile-set shape."""
        kmax = min(self.engine_cfg.max_prefills_per_tick,
                   self.engine_cfg.n_slots)
        # The warm sweep's synthetic prompts are not traffic: keep
        # them out of the journal so a journaled trace replays real
        # requests only (tuning/replay.py), then restore it.
        journal, self.journal = self.journal, None
        try:
            self._warm_sweep(prompt_lens, kmax)
        finally:
            self.journal = journal
        self._warmed = True
        if self.engine_cfg.autotune and self._tuner is None:
            # Install AFTER the warm sweep: the knob space's compile-
            # safe bounds are derived from what warmup just compiled,
            # and a tuner live during warmup could shrink the
            # admission batch mid-sweep and leave shapes uncompiled.
            from horovod_tpu.tuning.tuner import OnlineTuner

            OnlineTuner.install(self)

    def _warm_sweep(self, prompt_lens: Sequence[int], kmax: int) -> None:
        prompts = [[0] * max(int(n), 1) for n in prompt_lens]
        # Registered prefixes compile their own executables (suffix
        # prefill per (prefix pages, suffix bucket, k), prefix-page
        # gather): warm those too, with prompt_lens as the SUFFIX
        # lengths — otherwise the first shared-prefix admission after
        # start() pays XLA compilation inside the watchdog's budget.
        for entry in list(self._prefixes.values()):
            prompts += [list(entry.tokens) + [0] * max(int(n), 1)
                        for n in prompt_lens
                        if len(entry.tokens) + int(n) + 2
                        <= self.slots.max_len]
        for prompt in prompts:
            for k in range(1, kmax + 1):
                # max_new_tokens=2: the second token exercises the
                # decode tick (the first comes from prefill logits).
                futs = [self.submit(prompt, max_new_tokens=2)
                        for _ in range(k)]
                while not all(f.done() for f in futs):
                    self.step()
        # Sampled admissions compile the (k, vocab) first-token sampler
        # (the tick executables already contain the sampling kernel —
        # parameters are data — so only this admission-side shape set
        # needs warming; one sampled group per k covers it).
        for k in range(1, kmax + 1):
            futs = [self.submit(prompts[0], max_new_tokens=2,
                                temperature=1.0, seed=i)
                    for i in range(k)]
            while not all(f.done() for f in futs):
                self.step()
        if self._spec:
            # The speculative engine owns TWO decode executables — the
            # draft/verify tick and the plain one-token tick it falls
            # back to when no slot speculates (opt-outs, adaptive
            # disable).  Warm the plain one too: an adaptive disable
            # mid-serving must not pay XLA compilation inside the
            # watchdog budget.
            futs = [self.submit(prompts[0], max_new_tokens=2,
                                speculative=False)]
            while not all(f.done() for f in futs):
                self.step()
            # Warm the probe-path executables (both shape-stable at
            # (1, max_len) by construction): history re-landing for
            # the n-gram draft, the full-width draft re-prefill for
            # the model draft.
            if not self._spec_model:
                self._dev_history = self._hist_land(
                    self._history(), np.zeros((1,), np.int32),
                    np.zeros((1, self.slots.max_len), np.int32))
            else:
                width = self.slots.max_len
                self._draft_prefill_fn(width, 1)(
                    self.draft_params,
                    jnp.zeros((1, width), jnp.int32),
                    jnp.ones((1,), jnp.int32))
            # Warmup's synthetic zero-token prompts can legitimately
            # measure poor acceptance — that must not carry a
            # persistent adaptive disable into real traffic.
            self._reset_spec_state()

    def drain(self, timeout: float = 60.0, poll: float = 0.002) -> bool:
        """Block until queue and slots are empty (True) or timeout.
        Synchronous callers (no background thread) should loop
        :meth:`step` instead."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._health == FAILED:
                with self._hb_lock:
                    hard = self._stall_hard_failed
                if (self._terminal or hard
                        or not self.engine_cfg.resume):
                    return True  # recovery already resolved everything
                # (a non-terminal FAILED with resume on is a stall
                # window: journaled requests may still resume — keep
                # waiting; the caller's terminate() bounds the worst
                # case.  After a hard fail everything IS resolved, so
                # waiting out the hung tick would be pure delay.)
            # Sample under the step lock: between scheduler.take() and
            # slots.alloc() a request is in neither counter, and an
            # unlocked read could report "drained" mid-admission.  A
            # TIMED acquire, not a blocking one — a hung tick holds
            # _lock indefinitely, and drain must keep re-checking its
            # own deadline (and the FAILED the watchdog sets) instead
            # of inheriting the hang.
            if self._lock.acquire(timeout=poll):
                try:
                    idle = (self.scheduler.depth == 0
                            and self.slots.active_count == 0
                            and not self._taken
                            # suspended-for-resume requests are in
                            # neither counter until the requeue lands
                            and self._resuming == 0)
                finally:
                    self._lock.release()
                if idle:
                    return True
            if self._thread is None:
                self.step()
            else:
                time.sleep(poll)
        return False

    def terminate(self, reason: str = "engine terminated") -> None:
        """Force-resolve EVERYTHING (slots, taken, queue) with a typed
        :class:`EngineFailedError` and go terminally ``failed`` — the
        drain-timeout escape hatch: teardown must finish in bounded
        time even if requests cannot.  If the step lock cannot be
        acquired (a hung tick holds it — possibly with the watchdog
        disabled), futures are resolved WITHOUT it: the hung engine
        thread is not mutating slot state, and ``_terminal`` guarantees
        a late-returning tick can only land in the terminal branch of
        ``_recover``, never a restart."""
        self._terminal = True
        exc = EngineFailedError(reason)
        locked = self._lock.acquire(timeout=1.0)
        try:
            self._fail_inflight(exc)
            self._fail_queue(exc)
        finally:
            if locked:
                self._lock.release()
        self._set_health(FAILED)

    # -- observability -----------------------------------------------------

    @property
    def decode_compilations(self) -> int:
        """How many times the decode tick was traced/compiled — the
        zero-recompilation acceptance hook (stays 1 after warmup)."""
        return self._decode_traces

    def _update_achieved_flops(self) -> None:
        """Refresh ``serving_achieved_flops_per_sec`` from the token
        rate between stats() samples (window capped at ~60s so the
        number tracks current load, not job-lifetime average)."""
        fpt = self.engine_cfg.model_flops_per_token
        if not fpt:
            return
        # Re-assert the configured gauge: benchmarks swap in a fresh
        # ServingMetrics after warmup, which would otherwise leave it 0.
        metrics = self.metrics
        metrics.model_flops_per_token.set(fpt)
        now = time.monotonic()
        with self._rate_lock:
            if metrics is not self._rate_metrics:
                # A fresh ServingMetrics restarts the token counter at
                # 0; a window base from the old counter would make the
                # next rate negative.
                self._rate_samples.clear()
                self._rate_metrics = metrics
            self._rate_samples.append((now, metrics.tokens_generated.value))
            while (len(self._rate_samples) > 2
                   and now - self._rate_samples[0][0] > 60.0):
                self._rate_samples.pop(0)
            t0, n0 = self._rate_samples[0]
            n1 = self._rate_samples[-1][1]
        if now <= t0:
            return
        metrics.achieved_flops.set((n1 - n0) / (now - t0) * fpt)

    def refresh_windowed_gauges(self) -> None:
        """Refresh rate-windowed gauges (achieved FLOP/s) without
        building a /stats snapshot — the cheap hook a /metrics scrape
        wants."""
        self._update_achieved_flops()

    def stats(self) -> Dict:
        age = self.heartbeat_age
        self._update_achieved_flops()
        # Re-assert on the CURRENT metrics object: benchmarks swap in a
        # fresh ServingMetrics after warmup, which would zero the gauge.
        self.metrics.tp_degree.set(self.engine_cfg.tp)
        return {
            **self.metrics.snapshot(),
            "state": self._health,
            # The ROUTING CONTRACT (docs/serving.md "HTTP API"): these
            # four keys are always present and typed — the front tier
            # balances and evicts on them, so their absence or a None
            # must never be a reachable state.  heartbeat_age_s is
            # -1.0 until the first tick completes (a warming engine,
            # not a wedged one).
            "queue_depth": int(self.scheduler.depth),
            "occupancy": float(self.slots.occupancy),
            "engine_state": str(self._health),
            "heartbeat_age_s": round(age, 3) if age is not None else -1.0,
            # Routing-contract additions (docs/serving.md
            # "Tensor-parallel replicas"): always present, always
            # typed — tp is the replica's tensor-parallel degree
            # (int >= 1), mesh its axis/device layout (str; "" on an
            # unsharded engine) — so the registry and the router's
            # per-replica fleet view surface serving topology.
            "tp": int(self.engine_cfg.tp),
            "mesh": self._shard.describe() if self._shard is not None
            else "",
            # Fleet-rollout contract addition (docs/serving.md "Fleet
            # rollouts"): the config generation this engine was built
            # at — always present, always int, so the registry and the
            # rollout controller can tell incumbent from candidate
            # replicas without parsing knobs.
            "config_generation": int(self.engine_cfg.config_generation),
            "state_transitions": self.state_transitions,
            "n_slots": self.engine_cfg.n_slots,
            "slots_active": self.slots.active_count,
            "max_len": self.slots.max_len,
            "overlap": self.engine_cfg.overlap,
            "resume": self.engine_cfg.resume,
            "journal_inflight":
                len(self.journal) if self.journal is not None else 0,
            "decode_compilations": self._decode_traces,
            "prefill_compilations": self._prefill_traces,
            "prefill_calls": self._prefill_calls,
            # The admission-side first-token sampler's compile count
            # ((k, vocab) shapes, warmed by warmup()) — the decode
            # guard stays on decode_compilations: sampling parameters
            # are data and never retrace the tick.
            "sample_compilations": self._sample_traces,
            # (bucket, batch) shape pairs the prefill has compiled for
            # — bounded by buckets x max_prefills_per_tick.
            "prefill_buckets": sorted(self._prefill_fns),
            "paged": self.engine_cfg.paged,
            # SLO scheduling (docs/serving.md "Scheduling"): the chunk
            # budget (0 = whole-prompt prefill) and how many slots are
            # mid-ingestion right now; per-class TTFT/queue-wait and
            # the preemption counter ride the metrics snapshot above.
            "prefill_chunk_tokens": self.engine_cfg.prefill_chunk_tokens,
            "slots_ingesting": len(self._ingest),
            "speculative": self._spec,
            # Online autotuning (docs/serving.md "Autotuning"):
            # enabled flag always present; full tuner state (phase,
            # current/best knobs, trajectory) rides along — and is
            # served standalone at GET /tuning — once a tuner exists.
            "autotune": self._tuner is not None,
            **({"tuning": self._tuner.snapshot()}
               if self._tuner is not None else {}),
            **({
                "spec_k": self.engine_cfg.spec_k,
                "spec_draft": "model" if self._spec_model else "ngram",
                "spec_slots_live": int(self._spec_live.sum()),
                "draft_pages_free":
                    self.draft_slots.free_pages
                    if self.draft_slots is not None else None,
            } if self._spec else {}),
            **({
                "page_size": self.slots.page_size,
                "kv_dtype": str(jnp.dtype(self.slots._storage_dtype).name),
                "kv_pages_high_water": self.slots.pages_high_water,
                "prefixes_registered": len(self._prefixes),
                # Whether the decode/draft/verify ticks were built on
                # the fused Pallas paged-attention kernel (resolved at
                # construction from EngineConfig.paged_kernel; see
                # docs/serving.md "Paged decode kernel").
                "paged_kernel_engaged": self._paged_kernel,
            } if self.engine_cfg.paged else {}),
        }
