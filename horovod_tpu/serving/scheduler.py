"""Request admission and scheduling for the continuous-batching engine.

The paper's background-controller pattern applied to inference: callers
submit independent requests; a bounded FCFS queue absorbs bursts; the
engine drains it into free cache slots between decode ticks.  Admission
control is explicit and typed — a full queue raises
:class:`QueueFullError` at submit time, a request whose deadline lapsed
while queued is rejected with :class:`DeadlineExceededError` when it
reaches the head, and a request that cannot fit the cache raises
:class:`RequestTooLongError` before it ever queues — so backpressure is
a protocol, not an OOM.

The prefill/decode interleave policy lives here too:
:meth:`Scheduler.take` hands the engine at most ``max_prefills_per_tick``
admissions per decode tick, bounding how long the active batch stalls on
prompt ingestion (time-to-first-token vs decode tok/s — both stay
bounded; see docs/serving.md for tuning).

Admission ORDER is SLO-aware (docs/serving.md "Scheduling"), not plain
FCFS: every request carries a :attr:`Request.priority` class
(``"interactive"`` before ``"batch"``), and within a class requests
are ordered earliest-deadline-first (EDF), submission order breaking
ties — so a latency-budgeted request overtakes best-effort work
without starving it (class order is strict, but a class is only
consulted when every higher class is empty, and preemption — the
engine's side of the contract — only ever claims resources DOWN the
class order).  Requests with no deadline sort after deadlined peers in
their class, in FCFS order.  With every request in one class and no
deadlines this degenerates to exactly the old FCFS behavior.
"""

from __future__ import annotations

import collections
import dataclasses
import heapq
import itertools
import threading
import time
from typing import Any, Callable, List, Optional, Sequence


class ServingError(Exception):
    """Base class for typed serving rejections."""


class QueueFullError(ServingError):
    """The bounded request queue is at capacity — retry with backoff."""


class DeadlineExceededError(ServingError):
    """The request's deadline passed before it could be admitted."""


class RequestTooLongError(ServingError):
    """prompt + max_new_tokens exceeds the cache slot capacity."""


class CacheOutOfPagesError(ServingError):
    """The paged KV cache cannot supply the pages a request needs.

    Raised at submit time when ``prompt + max_new_tokens`` could never
    fit the whole page pool; set on an ADMITTED request's future when
    decode-time page growth exhausts the pool and the request is
    preempted to keep older requests progressing.  Requests that merely
    have to WAIT for pages are not rejected — they stay queued (the
    scheduler's ``admit_fn`` back-pressure) until retirements recycle
    pages.  HTTP maps this to 429 (shed load, retry with backoff)."""


class EngineFailedError(ServingError):
    """The engine tick failed (device exception, non-finite logits) and
    every in-flight request was resolved with this error.  The engine
    restarts itself (bounded attempts); callers may retry — unless the
    restart budget is exhausted, in which case new submits raise this
    too and ``/healthz`` reports ``failed``."""


class EngineStalledError(EngineFailedError):
    """The watchdog declared the engine stalled: a tick exceeded its
    wall-clock budget (hung device call).  In-flight AND queued
    requests are resolved with this error — a hung tick may never
    return, so nothing is left waiting on it."""


class DrainingError(ServingError):
    """The server is draining for shutdown — new requests are rejected
    (HTTP 503 ``draining``); admitted requests run to completion."""


_req_ids = itertools.count()

#: Priority classes, best first.  The tuple order IS the scheduling
#: order: class i is served before any request of class i+1, and the
#: engine's preemption policy only ever suspends a victim of a
#: STRICTLY worse class than the winner (docs/serving.md
#: "Scheduling").
PRIORITY_CLASSES = ("interactive", "batch")
_PRIORITY_RANK = {c: i for i, c in enumerate(PRIORITY_CLASSES)}


def priority_rank(priority: str) -> int:
    """Numeric rank of a priority class (lower = served first).
    Raises :class:`ServingError` for an unknown class — the one
    validation every ingress (engine submit, HTTP ``"priority"``
    field, journal resume) shares."""
    try:
        return _PRIORITY_RANK[priority]
    except KeyError:
        raise ServingError(
            f"unknown priority class {priority!r}; expected one of "
            f"{PRIORITY_CLASSES}") from None


@dataclasses.dataclass
class Request:
    """One generation request as the scheduler sees it.

    ``prompt`` is a token-id sequence; ``deadline`` is an ABSOLUTE
    ``time.monotonic()`` instant (None = no deadline); ``future`` is the
    engine's per-request result sink (tokens stream into it, typed
    rejections land on it as exceptions); ``trace`` is the request's
    :class:`~horovod_tpu.obs.tracing.RequestTrace` — the trace id and
    timing stamps ride the request through every stage, so the
    breakdown survives rejection, cancellation, stall, and restart
    paths alike."""

    prompt: Sequence[int]
    max_new_tokens: int
    future: Any
    eos_id: Optional[int] = None
    deadline: Optional[float] = None
    submitted_at: float = 0.0
    trace: Any = None
    # Per-request speculative-decoding opt-out (None = engine default):
    # False pins the slot to the plain one-token-per-tick greedy path
    # inside the same compiled speculative tick (acceptance forced to
    # zero as data) — output is identical either way, this is a
    # latency-predictability knob, not a correctness one.
    speculative: Optional[bool] = None
    # Per-request sampling (horovod_tpu/serving/sampling.py; validated
    # at submit): temperature=0 is greedy — the default, and what every
    # pre-sampling caller gets.  The engine rides these through the
    # compiled tick as per-slot data columns; a resumed request keeps
    # them verbatim (the PRNG key schedule is position-based, so the
    # re-prefilled continuation lands on the identical key stream).
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0
    # SLO class (PRIORITY_CLASSES; validated at the engine/HTTP
    # ingress): scheduling order is class-then-EDF-then-FCFS, and the
    # engine may preempt a strictly worse class under slot/page
    # pressure.  Survives journaling, restart-resume, and preemption
    # verbatim — a request never changes class mid-life.
    priority: str = "interactive"
    id: int = dataclasses.field(default_factory=lambda: next(_req_ids))

    @property
    def sampled(self) -> bool:
        return self.temperature > 0.0

    @property
    def priority_rank(self) -> int:
        return _PRIORITY_RANK.get(self.priority, len(PRIORITY_CLASSES))


class Scheduler:
    """Bounded priority queue + prefill/decode interleave policy.

    Admission order is (priority class, deadline-EDF, submission id) —
    see the module docstring; with one class and no deadlines this is
    exactly the historical FCFS scheduler.

    Thread-safe: callers submit from any thread; the engine thread
    drains with :meth:`take`.

    ``on_reject`` (constructor) is the ONE metrics hook for shed load:
    it fires for submit-time :class:`QueueFullError` AND for
    :class:`DeadlineExceededError` rejections inside :meth:`take`, so a
    counter wired here sees every rejection path (the engine wires
    ``metrics.rejected``).  ``on_cancel`` fires when a queued request
    is resolved because its future was cancelled before admission.
    """

    def __init__(self, *, max_queue_depth: int = 64,
                 max_prefills_per_tick: int = 2,
                 clock: Callable[[], float] = time.monotonic,
                 on_reject: Optional[
                     Callable[[Request, ServingError], None]] = None,
                 on_cancel: Optional[Callable[[Request], None]] = None,
                 on_expire: Optional[Callable[[Request], None]] = None):
        if max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1, got "
                             f"{max_queue_depth}")
        if max_prefills_per_tick < 1:
            raise ValueError(f"max_prefills_per_tick must be >= 1, got "
                             f"{max_prefills_per_tick}")
        self.max_queue_depth = max_queue_depth
        self.max_prefills_per_tick = max_prefills_per_tick
        self._clock = clock
        self._on_reject = on_reject
        self._on_cancel = on_cancel
        self._on_expire = on_expire
        self._q: collections.deque = collections.deque()
        self._lock = threading.Lock()

    @property
    def depth(self) -> int:
        return len(self._q)

    def submit(self, req: Request) -> None:
        """Enqueue FCFS; raises :class:`QueueFullError` at capacity (the
        caller's future is untouched — the submit call itself fails —
        but the constructor's ``on_reject`` IS notified, so shed load
        at submit time counts the same as shed load in :meth:`take`)."""
        req.submitted_at = self._clock()
        if req.trace is not None:
            req.trace.submitted_at = req.submitted_at
        err: Optional[QueueFullError] = None
        with self._lock:
            if len(self._q) >= self.max_queue_depth:
                err = QueueFullError(
                    f"request queue at capacity ({self.max_queue_depth})")
            else:
                self._q.append(req)
        if err is not None:
            if self._on_reject is not None:
                self._on_reject(req, err)
            raise err

    @staticmethod
    def _order_key(req: Request):
        """The ONE scheduling order: priority class, then the
        requeue boost, then EDF within the class (no deadline sorts
        after every deadline), then submission id (FCFS tie-break).
        The boost is what makes :meth:`requeue_front` a guarantee
        rather than a deque position: a suspended victim WITHOUT a
        deadline would otherwise sort behind every deadlined
        same-class arrival forever — a live future nothing could ever
        expire — so requeued requests go ahead of everything
        non-requeued in their class, ids ordering them among
        themselves."""
        return (req.priority_rank,
                0 if getattr(req, "_front", False) else 1,
                req.deadline if req.deadline is not None else float("inf"),
                req.id)

    def _remove(self, reqs: Sequence[Request]) -> None:
        if not reqs:
            return
        gone = set(id(r) for r in reqs)
        with self._lock:
            self._q = collections.deque(
                r for r in self._q if id(r) not in gone)

    def _resolve_dead(self, req: Request,
                      on_reject: Optional[Callable] = None) -> bool:
        """Resolve a queued request that can never be admitted —
        already done (raced a drain), cancelled, or deadline-lapsed.
        Returns True when the request was resolved (and must leave the
        queue)."""
        fut = req.future
        if getattr(fut, "done", lambda: False)():
            # Already resolved elsewhere (e.g. a submit that raced
            # a drain/terminal failure set its exception after
            # enqueuing) — drop it, nothing to admit or notify.
            return True
        if getattr(fut, "cancel_requested", False):
            fut._finish("cancelled")
            if self._on_cancel is not None:
                self._on_cancel(req)
            return True
        if req.deadline is not None and self._clock() > req.deadline:
            admitted_once = (
                getattr(fut, "ttft", None) is not None
                # ttft alone misses a victim preempted MID-INGESTION
                # (admitted, no token yet) — its uninterrupted twin
                # would lapse in-slot and finish "deadline" too, so
                # preemption must not change the observable outcome.
                or getattr(req.trace, "admitted_at", None) is not None)
            if admitted_once:
                # Admitted ONCE already (a preempted/resumed victim
                # waiting to re-admit): the deadline-AFTER-admission
                # contract applies — finish with the partial tokens a
                # previous life emitted (reason "deadline"), never a
                # 504 that discards paid-for output.
                fut._finish("deadline")
                if self._on_expire is not None:
                    self._on_expire(req)
                return True
            err = DeadlineExceededError(
                f"request {req.id} deadline passed while queued "
                f"({self._clock() - req.submitted_at:.3f}s in queue)")
            fut.set_exception(err)
            if self._on_reject is not None:
                self._on_reject(req, err)
            if on_reject is not None:
                on_reject(req, err)
            return True
        return False

    def sweep(self, on_reject: Optional[Callable] = None) -> int:
        """Resolve EVERY dead queued request (deadline lapsed,
        cancelled, already done) wherever it sits in the queue — not
        just the ones :meth:`take` happens to scan past.  The engine
        calls this at each tick boundary, so a doomed request's future
        (and its HTTP 504) resolves within one tick even when a long
        admission stall keeps :meth:`take` from ever reaching it.
        Returns how many requests it resolved."""
        with self._lock:
            snap = list(self._q)  # unsorted: sweep order is irrelevant
        dead = [r for r in snap if self._resolve_dead(r, on_reject)]
        self._remove(dead)
        return len(dead)

    def peek_best_rank(self) -> Optional[int]:
        """The best (lowest) priority rank among queued, still-live
        requests — what the engine's slot-pressure preemption compares
        against the worst active slot.  None when nothing admissible
        waits."""
        now = self._clock()
        best: Optional[int] = None
        with self._lock:
            for req in self._q:
                fut = req.future
                if getattr(fut, "done", lambda: False)():
                    continue
                if getattr(fut, "cancel_requested", False):
                    continue
                if req.deadline is not None and now > req.deadline:
                    continue
                r = req.priority_rank
                if best is None or r < best:
                    best = r
                    if best == 0:
                        break  # nothing outranks the best class
        return best

    def take(self, free_slots: int,
             on_reject: Optional[Callable[[Request, ServingError], None]]
             = None,
             bucket_fn: Optional[Callable[[Request], int]] = None,
             admit_fn: Optional[Callable[[Request], bool]] = None
             ) -> List[Request]:
        """Up to ``min(max_prefills_per_tick, free_slots)`` admissible
        requests in SCHEDULING ORDER (priority class, EDF within
        class, then submission order — :meth:`_order_key`).  Requests
        whose deadline lapsed — or whose future was cancelled — while
        queued are resolved in place (:class:`DeadlineExceededError`
        on the future / finished with reason ``"cancelled"``) without
        consuming a slot or a prefill budget entry, EVEN when the
        budget is zero: dead heads never block the queue.  Both the
        constructor's ``on_reject`` and the per-call one (if given)
        are notified of rejections.

        ``bucket_fn`` makes the batch UNIFORM: after the head of the
        scheduling order is taken, the take stops at the first request
        whose bucket differs from the head's (it stays queued, still
        ahead of everything behind it — the order is never violated,
        only truncated).  The engine uses this so one batched prefill
        serves the whole admission group without padding short prompts
        to a long prompt's bucket, and the compile set stays bounded
        by buckets x K.

        ``admit_fn`` is resource BACK-PRESSURE (the paged KV cache's
        page budget, the chunked-prefill per-tick token budget): a
        request it declines stays queued and the take stops — it is
        neither rejected nor reordered, it just WAITS until the
        resource frees.  Typed rejection is reserved for requests that
        could never run (:class:`CacheOutOfPagesError` at submit
        time)."""
        budget = min(self.max_prefills_per_tick, free_slots)
        if budget <= 0:
            # Nothing can be admitted: return without paying the sort
            # (all slots busy under a deep backlog is the steady state
            # the SLO scheduler targets).  Dead entries are
            # :meth:`sweep`'s job — the engine runs it at every tick
            # boundary, so dead heads still never block the queue.
            return []
        out: List[Request] = []
        removed: List[Request] = []
        bucket: Optional[int] = None
        # The scan only ever needs the first few candidates (budget is
        # small), so a deep queue pays O(n log k) selection, not a
        # full O(n log n) sort; dead entries past the window are
        # sweep's job, same as above.
        with self._lock:
            snap = list(self._q)
        k = max(4 * budget, 16)
        if len(snap) > k:
            cand = heapq.nsmallest(k, snap, key=self._order_key)
        else:
            cand = sorted(snap, key=self._order_key)
        for req in cand:
            if self._resolve_dead(req, on_reject):
                removed.append(req)
                continue
            if budget <= 0:
                break  # everything behind stays queued, order intact
            if bucket_fn is not None:
                b = bucket_fn(req)
                if bucket is None:
                    bucket = b
                elif b != bucket:
                    break  # next tick's head; never reordered past
            if admit_fn is not None and not admit_fn(req):
                break  # waits for the resource, still ahead in order
            out.append(req)
            removed.append(req)
            budget -= 1
        self._remove(removed)
        return out

    def requeue_front(self, reqs: Sequence[Request]) -> None:
        """Put RESUMED (or preempted) requests back into the queue —
        the engine's restart-resume and preemption paths.  Each is
        marked with the requeue BOOST, so :meth:`_order_key` places
        it ahead of everything non-requeued in its class — deadlined
        or not — with original ids ordering requeued peers among
        themselves (the "front" the name promises, now an ordering
        property rather than a deque position).  Deliberately exempt
        from ``max_queue_depth``: these requests were already
        admitted once and their callers are still waiting on live
        futures; bouncing them as :class:`QueueFullError` after
        surviving a crash would make durability depend on queue
        pressure."""
        reqs = list(reqs)
        for r in reqs:
            r._front = True
        with self._lock:
            self._q.extendleft(reversed(reqs))

    def drain_pending(self) -> List[Request]:
        """Atomically remove and return every queued request — the
        terminal-failure / forced-shutdown path, where the caller must
        resolve each future itself so nothing is left hanging."""
        with self._lock:
            out = list(self._q)
            self._q.clear()
        return out
