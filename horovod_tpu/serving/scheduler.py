"""Request admission and scheduling for the continuous-batching engine.

The paper's background-controller pattern applied to inference: callers
submit independent requests; a bounded FCFS queue absorbs bursts; the
engine drains it into free cache slots between decode ticks.  Admission
control is explicit and typed — a full queue raises
:class:`QueueFullError` at submit time, a request whose deadline lapsed
while queued is rejected with :class:`DeadlineExceededError` when it
reaches the head, and a request that cannot fit the cache raises
:class:`RequestTooLongError` before it ever queues — so backpressure is
a protocol, not an OOM.

The prefill/decode interleave policy lives here too:
:meth:`Scheduler.take` hands the engine at most ``max_prefills_per_tick``
admissions per decode tick, bounding how long the active batch stalls on
prompt ingestion (time-to-first-token vs decode tok/s — both stay
bounded; see docs/serving.md for tuning).
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
import time
from typing import Any, Callable, List, Optional, Sequence


class ServingError(Exception):
    """Base class for typed serving rejections."""


class QueueFullError(ServingError):
    """The bounded request queue is at capacity — retry with backoff."""


class DeadlineExceededError(ServingError):
    """The request's deadline passed before it could be admitted."""


class RequestTooLongError(ServingError):
    """prompt + max_new_tokens exceeds the cache slot capacity."""


class CacheOutOfPagesError(ServingError):
    """The paged KV cache cannot supply the pages a request needs.

    Raised at submit time when ``prompt + max_new_tokens`` could never
    fit the whole page pool; set on an ADMITTED request's future when
    decode-time page growth exhausts the pool and the request is
    preempted to keep older requests progressing.  Requests that merely
    have to WAIT for pages are not rejected — they stay queued (the
    scheduler's ``admit_fn`` back-pressure) until retirements recycle
    pages.  HTTP maps this to 429 (shed load, retry with backoff)."""


class EngineFailedError(ServingError):
    """The engine tick failed (device exception, non-finite logits) and
    every in-flight request was resolved with this error.  The engine
    restarts itself (bounded attempts); callers may retry — unless the
    restart budget is exhausted, in which case new submits raise this
    too and ``/healthz`` reports ``failed``."""


class EngineStalledError(EngineFailedError):
    """The watchdog declared the engine stalled: a tick exceeded its
    wall-clock budget (hung device call).  In-flight AND queued
    requests are resolved with this error — a hung tick may never
    return, so nothing is left waiting on it."""


class DrainingError(ServingError):
    """The server is draining for shutdown — new requests are rejected
    (HTTP 503 ``draining``); admitted requests run to completion."""


_req_ids = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request as the scheduler sees it.

    ``prompt`` is a token-id sequence; ``deadline`` is an ABSOLUTE
    ``time.monotonic()`` instant (None = no deadline); ``future`` is the
    engine's per-request result sink (tokens stream into it, typed
    rejections land on it as exceptions); ``trace`` is the request's
    :class:`~horovod_tpu.obs.tracing.RequestTrace` — the trace id and
    timing stamps ride the request through every stage, so the
    breakdown survives rejection, cancellation, stall, and restart
    paths alike."""

    prompt: Sequence[int]
    max_new_tokens: int
    future: Any
    eos_id: Optional[int] = None
    deadline: Optional[float] = None
    submitted_at: float = 0.0
    trace: Any = None
    # Per-request speculative-decoding opt-out (None = engine default):
    # False pins the slot to the plain one-token-per-tick greedy path
    # inside the same compiled speculative tick (acceptance forced to
    # zero as data) — output is identical either way, this is a
    # latency-predictability knob, not a correctness one.
    speculative: Optional[bool] = None
    # Per-request sampling (horovod_tpu/serving/sampling.py; validated
    # at submit): temperature=0 is greedy — the default, and what every
    # pre-sampling caller gets.  The engine rides these through the
    # compiled tick as per-slot data columns; a resumed request keeps
    # them verbatim (the PRNG key schedule is position-based, so the
    # re-prefilled continuation lands on the identical key stream).
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0
    id: int = dataclasses.field(default_factory=lambda: next(_req_ids))

    @property
    def sampled(self) -> bool:
        return self.temperature > 0.0


class Scheduler:
    """Bounded FCFS queue + prefill/decode interleave policy.

    Thread-safe: callers submit from any thread; the engine thread
    drains with :meth:`take`.

    ``on_reject`` (constructor) is the ONE metrics hook for shed load:
    it fires for submit-time :class:`QueueFullError` AND for
    :class:`DeadlineExceededError` rejections inside :meth:`take`, so a
    counter wired here sees every rejection path (the engine wires
    ``metrics.rejected``).  ``on_cancel`` fires when a queued request
    is resolved because its future was cancelled before admission.
    """

    def __init__(self, *, max_queue_depth: int = 64,
                 max_prefills_per_tick: int = 2,
                 clock: Callable[[], float] = time.monotonic,
                 on_reject: Optional[
                     Callable[[Request, ServingError], None]] = None,
                 on_cancel: Optional[Callable[[Request], None]] = None):
        if max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1, got "
                             f"{max_queue_depth}")
        if max_prefills_per_tick < 1:
            raise ValueError(f"max_prefills_per_tick must be >= 1, got "
                             f"{max_prefills_per_tick}")
        self.max_queue_depth = max_queue_depth
        self.max_prefills_per_tick = max_prefills_per_tick
        self._clock = clock
        self._on_reject = on_reject
        self._on_cancel = on_cancel
        self._q: collections.deque = collections.deque()
        self._lock = threading.Lock()

    @property
    def depth(self) -> int:
        return len(self._q)

    def submit(self, req: Request) -> None:
        """Enqueue FCFS; raises :class:`QueueFullError` at capacity (the
        caller's future is untouched — the submit call itself fails —
        but the constructor's ``on_reject`` IS notified, so shed load
        at submit time counts the same as shed load in :meth:`take`)."""
        req.submitted_at = self._clock()
        if req.trace is not None:
            req.trace.submitted_at = req.submitted_at
        err: Optional[QueueFullError] = None
        with self._lock:
            if len(self._q) >= self.max_queue_depth:
                err = QueueFullError(
                    f"request queue at capacity ({self.max_queue_depth})")
            else:
                self._q.append(req)
        if err is not None:
            if self._on_reject is not None:
                self._on_reject(req, err)
            raise err

    def take(self, free_slots: int,
             on_reject: Optional[Callable[[Request, ServingError], None]]
             = None,
             bucket_fn: Optional[Callable[[Request], int]] = None,
             admit_fn: Optional[Callable[[Request], bool]] = None
             ) -> List[Request]:
        """Up to ``min(max_prefills_per_tick, free_slots)`` admissible
        requests, FCFS.  Requests whose deadline lapsed — or whose
        future was cancelled — while queued are resolved in place
        (:class:`DeadlineExceededError` on the future / finished with
        reason ``"cancelled"``) without consuming a slot or a prefill
        budget entry, EVEN when the budget is zero: dead heads never
        block the queue.  Both the constructor's ``on_reject`` and the
        per-call one (if given) are notified of rejections.

        ``bucket_fn`` makes the batch UNIFORM: after the FCFS head is
        taken, the take stops at the first queued request whose bucket
        differs from the head's (it stays queued, still the head for
        the next tick — FCFS order is never reordered).  The engine
        uses this so one batched prefill serves the whole admission
        group without padding short prompts to a long prompt's bucket,
        and the compile set stays bounded by buckets x K.

        ``admit_fn`` is resource BACK-PRESSURE (the paged KV cache's
        page budget): a request it declines goes back to the head and
        the take stops — it is neither rejected nor reordered, it just
        WAITS until retirements free the resource.  Typed rejection is
        reserved for requests that could never run
        (:class:`CacheOutOfPagesError` at submit time)."""
        budget = min(self.max_prefills_per_tick, free_slots)
        out: List[Request] = []
        bucket: Optional[int] = None
        while True:
            with self._lock:
                if not self._q:
                    break
                req = self._q.popleft()
            fut = req.future
            if getattr(fut, "done", lambda: False)():
                # Already resolved elsewhere (e.g. a submit that raced
                # a drain/terminal failure set its exception after
                # enqueuing) — drop it, nothing to admit or notify.
                continue
            if getattr(fut, "cancel_requested", False):
                fut._finish("cancelled")
                if self._on_cancel is not None:
                    self._on_cancel(req)
                continue
            if req.deadline is not None and self._clock() > req.deadline:
                err = DeadlineExceededError(
                    f"request {req.id} deadline passed while queued "
                    f"({self._clock() - req.submitted_at:.3f}s in queue)")
                fut.set_exception(err)
                if self._on_reject is not None:
                    self._on_reject(req, err)
                if on_reject is not None:
                    on_reject(req, err)
                continue
            if budget <= 0:
                with self._lock:
                    self._q.appendleft(req)  # still the FCFS head
                break
            if bucket_fn is not None:
                b = bucket_fn(req)
                if bucket is None:
                    bucket = b
                elif b != bucket:
                    with self._lock:
                        self._q.appendleft(req)  # next tick's FCFS head
                    break
            if admit_fn is not None and not admit_fn(req):
                with self._lock:
                    self._q.appendleft(req)  # waits for pages, still head
                break
            out.append(req)
            budget -= 1
        return out

    def requeue_front(self, reqs: Sequence[Request]) -> None:
        """Put RESUMED requests back at the head of the queue, in the
        given order (``reqs[0]`` becomes the next head) — the engine's
        restart-resume path.  Deliberately exempt from
        ``max_queue_depth``: these requests were already admitted once
        and their callers are still waiting on live futures; bouncing
        them as :class:`QueueFullError` after surviving a crash would
        make durability depend on queue pressure."""
        with self._lock:
            self._q.extendleft(reversed(list(reqs)))

    def drain_pending(self) -> List[Request]:
        """Atomically remove and return every queued request — the
        terminal-failure / forced-shutdown path, where the caller must
        resolve each future itself so nothing is left hanging."""
        with self._lock:
            out = list(self._q)
            self._q.clear()
        return out
