"""Request admission and scheduling for the continuous-batching engine.

The paper's background-controller pattern applied to inference: callers
submit independent requests; a bounded FCFS queue absorbs bursts; the
engine drains it into free cache slots between decode ticks.  Admission
control is explicit and typed — a full queue raises
:class:`QueueFullError` at submit time, a request whose deadline lapsed
while queued is rejected with :class:`DeadlineExceededError` when it
reaches the head, and a request that cannot fit the cache raises
:class:`RequestTooLongError` before it ever queues — so backpressure is
a protocol, not an OOM.

The prefill/decode interleave policy lives here too:
:meth:`Scheduler.take` hands the engine at most ``max_prefills_per_tick``
admissions per decode tick, bounding how long the active batch stalls on
prompt ingestion (time-to-first-token vs decode tok/s — both stay
bounded; see docs/serving.md for tuning).
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
import time
from typing import Any, Callable, List, Optional, Sequence


class ServingError(Exception):
    """Base class for typed serving rejections."""


class QueueFullError(ServingError):
    """The bounded request queue is at capacity — retry with backoff."""


class DeadlineExceededError(ServingError):
    """The request's deadline passed before it could be admitted."""


class RequestTooLongError(ServingError):
    """prompt + max_new_tokens exceeds the cache slot capacity."""


_req_ids = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request as the scheduler sees it.

    ``prompt`` is a token-id sequence; ``deadline`` is an ABSOLUTE
    ``time.monotonic()`` instant (None = no deadline); ``future`` is the
    engine's per-request result sink (tokens stream into it, typed
    rejections land on it as exceptions)."""

    prompt: Sequence[int]
    max_new_tokens: int
    future: Any
    eos_id: Optional[int] = None
    deadline: Optional[float] = None
    submitted_at: float = 0.0
    id: int = dataclasses.field(default_factory=lambda: next(_req_ids))


class Scheduler:
    """Bounded FCFS queue + prefill/decode interleave policy.

    Thread-safe: callers submit from any thread; the engine thread
    drains with :meth:`take`.
    """

    def __init__(self, *, max_queue_depth: int = 64,
                 max_prefills_per_tick: int = 2,
                 clock: Callable[[], float] = time.monotonic):
        if max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1, got "
                             f"{max_queue_depth}")
        if max_prefills_per_tick < 1:
            raise ValueError(f"max_prefills_per_tick must be >= 1, got "
                             f"{max_prefills_per_tick}")
        self.max_queue_depth = max_queue_depth
        self.max_prefills_per_tick = max_prefills_per_tick
        self._clock = clock
        self._q: collections.deque = collections.deque()
        self._lock = threading.Lock()

    @property
    def depth(self) -> int:
        return len(self._q)

    def submit(self, req: Request) -> None:
        """Enqueue FCFS; raises :class:`QueueFullError` at capacity (the
        caller's future is untouched — the submit call itself fails)."""
        req.submitted_at = self._clock()
        with self._lock:
            if len(self._q) >= self.max_queue_depth:
                raise QueueFullError(
                    f"request queue at capacity ({self.max_queue_depth})")
            self._q.append(req)

    def take(self, free_slots: int,
             on_reject: Optional[Callable[[Request, ServingError], None]]
             = None) -> List[Request]:
        """Up to ``min(max_prefills_per_tick, free_slots)`` admissible
        requests, FCFS.  Requests whose deadline lapsed while queued are
        rejected in place: their future gets a
        :class:`DeadlineExceededError` and ``on_reject`` is notified —
        they do not consume a slot or a prefill budget entry."""
        budget = min(self.max_prefills_per_tick, free_slots)
        out: List[Request] = []
        while budget > 0:
            with self._lock:
                if not self._q:
                    break
                req = self._q.popleft()
            if req.deadline is not None and self._clock() > req.deadline:
                err = DeadlineExceededError(
                    f"request {req.id} deadline passed while queued "
                    f"({self._clock() - req.submitted_at:.3f}s in queue)")
                req.future.set_exception(err)
                if on_reject is not None:
                    on_reject(req, err)
                continue
            out.append(req)
            budget -= 1
        return out
