"""Continuous-batching inference serving (docs/serving.md).

The paper's fusion-of-pending-work architecture applied to decoding:
one compiled ``decode_step_slots`` executable hot over a fixed pool of
cache slots, a bounded FCFS scheduler admitting requests (one batched
batch-K prefill per tick) into freed slots with zero recompilation,
and a threaded stdlib-HTTP front — wrapped in a fault-tolerance layer
(supervised tick restarts, a watchdog against hung ticks, typed
failure propagation, cancellation, graceful drain) whose invariant is
that every submitted request resolves in bounded time with tokens or
a typed error.  In-flight requests are DURABLE (docs/serving.md
"Operations"): their decode state is journaled
(:mod:`horovod_tpu.serving.journal`), restarts RESUME them
token-identically instead of failing them, and the front tier
continues a dead replica's partially decoded requests on a survivor
from the journal's resume descriptor.  The decode hot loop is a device/host pipeline
(``EngineConfig.overlap``, default on): device-resident tokens feed
tick N's output straight into tick N+1's dispatch while host
bookkeeping runs one tick behind — token-identical to the synchronous
path (docs/serving.md "Performance").

    from horovod_tpu import serving
    engine = serving.InferenceEngine(params, cfg,
                                     serving.EngineConfig(n_slots=8))
    with serving.ServingServer(engine, port=8000):
        ...
"""

from horovod_tpu.serving.cache import (
    PagedSlotCache,
    SlotCache,
    init_page_pool,
    init_slot_cache,
    insert_prefill,
    insert_prefill_batch,
)
from horovod_tpu.serving.engine import (
    DEGRADED,
    DRAINING,
    FAILED,
    HEALTHY,
    EngineConfig,
    GenerationFuture,
    InferenceEngine,
)
from horovod_tpu.serving.faults import (
    FaultInjector,
    FaultSpec,
    InjectedFaultError,
)
from horovod_tpu.serving.journal import (
    JournalEntry,
    RequestJournal,
)
from horovod_tpu.serving.metrics import (
    Counter,
    Gauge,
    Histogram,
    ServingMetrics,
)
from horovod_tpu.serving.sampling import (
    SamplingParams,
    SlotSampling,
)
from horovod_tpu.serving.sharding import (
    ServingSharding,
    ShardingConfigError,
)
from horovod_tpu.serving.sse import (
    SSEParser,
    event_bytes,
)
from horovod_tpu.serving.scheduler import (
    PRIORITY_CLASSES,
    CacheOutOfPagesError,
    DeadlineExceededError,
    DrainingError,
    EngineFailedError,
    EngineStalledError,
    QueueFullError,
    Request,
    RequestTooLongError,
    Scheduler,
    ServingError,
    priority_rank,
)
from horovod_tpu.serving.server import ServingServer
# The replicated front tier (router subpackage) — imported last: it
# builds ON the engine/server modules above, never the reverse.
from horovod_tpu.serving import router  # noqa: E402  (docs/serving.md "Front tier")

__all__ = [
    "router",
    "SlotCache", "PagedSlotCache", "init_slot_cache", "init_page_pool",
    "insert_prefill", "insert_prefill_batch",
    "EngineConfig", "GenerationFuture", "InferenceEngine",
    "HEALTHY", "DEGRADED", "DRAINING", "FAILED",
    "FaultInjector", "FaultSpec", "InjectedFaultError",
    "JournalEntry", "RequestJournal",
    "Counter", "Gauge", "Histogram", "ServingMetrics",
    "SamplingParams", "SlotSampling", "SSEParser", "event_bytes",
    "ServingSharding", "ShardingConfigError",
    "CacheOutOfPagesError", "DeadlineExceededError", "DrainingError",
    "EngineFailedError", "EngineStalledError", "QueueFullError",
    "Request", "RequestTooLongError", "Scheduler", "ServingError",
    "ServingServer",
]
