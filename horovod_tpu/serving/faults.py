"""Deterministic fault injection for the serving stack.

The chaos suite (``tests/test_chaos.py``) needs to prove one invariant:
*no submitted request ever hangs* — under device exceptions, hung
ticks, non-finite logits, and mid-stream cancellations, every
:class:`~horovod_tpu.serving.engine.GenerationFuture` resolves with
tokens or a typed error within a bounded wall-clock, and the engine
recovers to oracle-identical greedy output.  Proving that requires
faults that fire at EXACT, reproducible points, which is what this
module provides: a seedable :class:`FaultInjector` with site-addressed
probes that the engine calls at its failure-prone boundaries.

Sites (``FaultInjector.SITES``):

* ``"prefill"`` — probed in ``InferenceEngine._admit_batch``
  immediately before the batched prefill (a device fault during
  admission).
* ``"decode_tick"`` — probed in the engine's decode path immediately
  before the compiled tick is DISPATCHED (a device fault mid-decode);
  the ``"nonfinite"`` kind corrupts the tick's per-slot max-logit
  vector at its fetch instead, modeling NaN/Inf logits from bad
  params or flaky hardware.
* ``"decode_fetch"`` — probed immediately before the engine fetches a
  dispatched tick's results (``np.asarray`` of the device tokens).
  With the overlapped pipeline this is the DEFERRED-fetch boundary —
  the one host sync per steady-state tick, where an async device
  failure from the PREVIOUS tick actually surfaces — so the chaos
  suite can model a device that accepted the dispatch and then died
  (raise) or wedged (hang) before delivering the value.
* ``"watchdog"`` — probed at the top of ``InferenceEngine.step``; a
  ``"hang"`` here stalls the whole tick outside any device call,
  which is exactly what the watchdog thread exists to catch.
* ``"prefill_chunk"`` — probed in ``InferenceEngine._ingest_step``
  immediately before each CHUNK of a chunked prompt ingestion is
  dispatched (docs/serving.md "Scheduling"), so the chaos invariant
  covers a crash at every chunk boundary: the partially-ingested
  request suspends through the resume path (no tokens were emitted
  yet — the journal frontier is the original prompt) and re-ingests
  oracle-exact after the supervised restart.
* ``"restart_resume"`` — probed in ``InferenceEngine._recover`` at
  the point where a non-terminal restart would SUSPEND in-flight
  requests for resume (the ISSUE 9 durability path).  A ``"raise"``
  models the resume machinery itself failing (unreadable journal,
  corrupted state): the engine degrades to the legacy fail-typed
  restart — in-flight futures resolve with ``EngineFailedError``
  instead of resuming, and nothing is ever replayed from state it
  cannot trust.
* ``"rollout_drain"`` / ``"rollout_rebuild"`` / ``"rollout_canary"``
  / ``"rollout_promote"`` — probed by the fleet
  :class:`~horovod_tpu.serving.router.rollout.RolloutController` (NOT
  the engine) at each step of a rolling reconfiguration: before a
  replica is drained for rebuild, before the rebuilt replica is
  awaited, before the canary is admitted for scoring, and before each
  post-canary promotion step (docs/serving.md "Fleet rollouts").  A
  ``"raise"`` at any of them models the controller machinery failing
  mid-step and must trip the automatic rollback; a ``"hang"`` models
  a stalled step (the rollback path still converges the fleet).

Kinds:

* ``"raise"`` — raise :class:`InjectedFaultError` at the site.
* ``"hang"`` — sleep ``delay`` seconds at the site (the tick
  heartbeat keeps aging, so a delay past the engine's
  ``tick_timeout`` budget trips the watchdog).
* ``"nonfinite"`` — only meaningful at ``decode_tick``: the engine
  replaces the active slots' max-logits with NaN, which its
  finiteness check then converts into a typed engine failure.

Determinism: each site keeps a visit counter; a spec fires on visits
``skip, skip+1, ...`` until ``max_fires`` is exhausted, gated by a
``random.Random(seed)`` draw when ``p < 1`` — same seed + same call
sequence = same faults.  The injector records every firing in
:attr:`FaultInjector.fired` so tests can assert exactly what happened.
The injector is probed only from the engine thread; it is not
thread-safe and does not need to be.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["FaultInjector", "FaultSpec", "InjectedFaultError"]


class InjectedFaultError(RuntimeError):
    """Raised at a fault site by a ``kind="raise"`` spec.  Deliberately
    NOT a ServingError: the engine must survive arbitrary exceptions,
    not just its own typed ones."""


@dataclasses.dataclass
class FaultSpec:
    """One scripted fault.

    ``site`` must be in :attr:`FaultInjector.SITES`; ``kind`` in
    ``("raise", "hang", "nonfinite")``.  The spec becomes eligible on
    the site's ``skip``-th visit (0-based) and fires at most
    ``max_fires`` times (``None`` = unlimited), each eligible visit
    passing an independent probability-``p`` draw."""

    site: str
    kind: str = "raise"
    p: float = 1.0
    delay: float = 0.0
    max_fires: Optional[int] = 1
    skip: int = 0
    _fires: int = dataclasses.field(default=0, init=False, repr=False)


class FaultInjector:
    """Seedable, site-addressed fault probes for the inference engine.

    >>> inj = FaultInjector([
    ...     FaultSpec(site="decode_tick", kind="raise", skip=3),
    ...     FaultSpec(site="decode_tick", kind="hang", delay=0.5,
    ...               skip=10),
    ... ], seed=7)
    >>> cfg = EngineConfig(faults=inj)

    The engine calls :meth:`probe` at each site; the third decode tick
    raises, the tenth hangs 0.5 s, everything else runs clean.
    """

    SITES = ("prefill", "prefill_chunk", "decode_tick", "decode_fetch",
             "watchdog", "restart_resume",
             # Fleet-rollout sites, probed by the RolloutController in
             # the SUPERVISOR process (never by an engine):
             "rollout_drain", "rollout_rebuild", "rollout_canary",
             "rollout_promote")
    KINDS = ("raise", "hang", "nonfinite")

    def __init__(self, specs: Sequence[FaultSpec] = (), seed: int = 0):
        self.specs: List[FaultSpec] = []
        self._rng = random.Random(seed)
        self._visits: Dict[str, int] = {s: 0 for s in self.SITES}
        #: every firing, in order: (site, kind, site-visit index)
        self.fired: List[Tuple[str, str, int]] = []
        self.add(*specs)

    def add(self, *specs: FaultSpec) -> "FaultInjector":
        """Validate and append specs — also usable MID-RUN, so a test
        can warm an engine fault-free and then schedule a fault
        relative to :meth:`visits` (``skip=inj.visits(site) + n``:
        fire on the n-th visit from now)."""
        for spec in specs:
            if spec.site not in self.SITES:
                raise ValueError(
                    f"unknown fault site {spec.site!r}; expected one of "
                    f"{self.SITES}")
            if spec.kind not in self.KINDS:
                raise ValueError(
                    f"unknown fault kind {spec.kind!r}; expected one of "
                    f"{self.KINDS}")
            self.specs.append(spec)
        return self

    def visits(self, site: str) -> int:
        """How many times ``site`` has been probed so far."""
        return self._visits[site]

    @property
    def exhausted(self) -> bool:
        """True when every bounded spec has fired its fill (an
        unlimited spec never exhausts)."""
        return all(s.max_fires is not None and s._fires >= s.max_fires
                   for s in self.specs)

    def probe(self, site: str) -> Optional[str]:
        """Visit ``site``; fire the first matching eligible spec.

        ``"raise"`` raises :class:`InjectedFaultError` here;
        ``"hang"`` sleeps ``delay`` here and returns ``"hang"``;
        ``"nonfinite"`` returns ``"nonfinite"`` for the caller to apply
        (only the engine knows where its logits are).  Returns None
        when nothing fires."""
        visit = self._visits[site]
        self._visits[site] = visit + 1
        for spec in self.specs:
            if spec.site != site or visit < spec.skip:
                continue
            if spec.max_fires is not None and spec._fires >= spec.max_fires:
                continue
            if spec.p < 1.0 and self._rng.random() >= spec.p:
                continue
            spec._fires += 1
            self.fired.append((site, spec.kind, visit))
            if spec.kind == "raise":
                raise InjectedFaultError(
                    f"injected fault at {site} (visit {visit})")
            if spec.kind == "hang":
                time.sleep(spec.delay)
            return spec.kind
        return None
