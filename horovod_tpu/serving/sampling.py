"""Per-request sampling, vectorized as per-slot DATA inside the one
compiled decode tick.

The paper's move — fuse per-caller work into one batched device program
instead of per-caller programs — applied to sampling: every request
carries its own ``temperature`` / ``top_k`` / ``top_p`` / ``seed``, and
the engine rides them through the tick as per-slot parameter COLUMNS
plus per-slot PRNG key ROWS (``models/transformer.py:
sample_token_rows``).  One compiled sampled-decode executable serves
every parameter mix; greedy is just a ``temperature=0`` row, so mixed
greedy/sampled batches share the program and request churn never
recompiles (the same compile-count-guarded property as the paged and
speculative modes).

Reproducibility contract: slot output is token-identical to
``sample_decode`` (the per-request oracle) at the same seed/params.
The key for the token at logical position ``p`` is
``fold_in(fold_in(PRNGKey(seed), p), 0)`` — a pure function of (seed,
position), never of how generation was sliced across prefills — so a
restart-resume or router-failover re-prefill of ``prompt + emitted``
lands on the identical key stream with no extra state to carry.

This module owns the HOST half: parameter validation
(:func:`validate`), the host-side seed→key derivation
(:func:`seed_key` — no device op per submit), and the per-slot column
mirror (:class:`SlotSampling`) whose device copy is re-uploaded only
when a slot's parameters change (an async upload, never a host sync —
the engine's ≤ 1-sync-per-tick guarantee is untouched).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

import numpy as np

from horovod_tpu.serving.scheduler import ServingError

__all__ = ["MAX_SEED", "SamplingParams", "SlotSampling", "seed_key",
           "validate"]

#: Seeds are capped to non-negative int32 range: ``jax.random.PRNGKey``
#: packs the seed into the low key word (the high word is 0 below
#: 2**32, and 32-bit jax builds truncate above it) — keeping seeds in
#: [0, 2**31) makes :func:`seed_key` exact on every jax config.
MAX_SEED = 2 ** 31


def validate(temperature=0.0, top_k=0, top_p=0.0,
             seed=None) -> Tuple[float, int, float, int]:
    """Normalize and validate one request's sampling parameters.

    Returns ``(temperature, top_k, top_p, seed)`` as plain
    ``(float, int, float, int)``; raises :class:`ServingError` (HTTP
    400) on anything the kernel cannot honor.  ``temperature=0`` is
    greedy; ``top_k=0`` and ``top_p`` of 0 or 1 disable their
    filters."""
    try:
        temperature = float(temperature if temperature is not None else 0.0)
        top_k = int(top_k if top_k is not None else 0)
        top_p = float(top_p if top_p is not None else 0.0)
        seed = int(seed if seed is not None else 0)
    except (TypeError, ValueError) as e:
        raise ServingError(f"bad sampling parameter: {e}")
    if not math.isfinite(temperature) or temperature < 0.0:
        raise ServingError(
            f"temperature must be finite and >= 0, got {temperature}")
    if top_k < 0:
        raise ServingError(f"top_k must be >= 0, got {top_k}")
    if not math.isfinite(top_p) or not 0.0 <= top_p <= 1.0:
        raise ServingError(f"top_p must be in [0, 1], got {top_p}")
    if not 0 <= seed < MAX_SEED:
        raise ServingError(
            f"seed must be in [0, {MAX_SEED}), got {seed}")
    return temperature, top_k, top_p, seed


def seed_key(seed: int) -> np.ndarray:
    """``np.asarray(jax.random.PRNGKey(seed))`` without the device op:
    the threefry key for a seed in [0, 2**31) is ``[seed >> 32, seed &
    0xffffffff] = [0, seed]`` uint32 (guarded by a unit test against
    the real ``PRNGKey`` so a jax-side layout change cannot drift
    silently)."""
    if not 0 <= seed < MAX_SEED:
        raise ValueError(f"seed out of range [0, {MAX_SEED}): {seed}")
    return np.array([0, seed], np.uint32)


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """One request's sampling knobs, post-validation (a convenience
    bundle for callers that pass them around together; the scheduler's
    ``Request`` carries them as plain fields)."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0

    @classmethod
    def make(cls, temperature=0.0, top_k=0, top_p=0.0,
             seed=None) -> "SamplingParams":
        return cls(*validate(temperature, top_k, top_p, seed))

    @property
    def sampled(self) -> bool:
        return self.temperature > 0.0


class SlotSampling:
    """The per-slot sampling columns: host mirror + cached device copy.

    The engine sets a slot's row at admission and zeroes it at release
    (a zero row is greedy — exactly what inactive and greedy slots
    need); :meth:`device` re-uploads only when something changed, so
    steady-state decode adds zero transfers.  ``jnp`` is imported
    lazily to keep this module importable without a device runtime."""

    def __init__(self, n_slots: int):
        self.temperature = np.zeros(n_slots, np.float32)
        self.top_k = np.zeros(n_slots, np.int32)
        self.top_p = np.zeros(n_slots, np.float32)
        self.key = np.zeros((n_slots, 2), np.uint32)
        self._dev: Optional[tuple] = None
        self._dirty = True

    def set(self, slot: int, *, temperature: float, top_k: int,
            top_p: float, seed: int) -> None:
        self.temperature[slot] = temperature
        self.top_k[slot] = top_k
        self.top_p[slot] = top_p
        self.key[slot] = seed_key(seed)
        self._dirty = True

    def clear(self, slot: int) -> None:
        self.temperature[slot] = 0.0
        self.top_k[slot] = 0
        self.top_p[slot] = 0.0
        self.key[slot] = 0
        self._dirty = True

    def reset(self) -> None:
        """Restart path: zero every column and drop the device copy
        (it belonged to the dead cache lineage); re-admissions repopulate."""
        self.temperature[:] = 0.0
        self.top_k[:] = 0
        self.top_p[:] = 0.0
        self.key[:] = 0
        self._dev = None
        self._dirty = True

    @property
    def any_sampled(self) -> bool:
        return bool((self.temperature > 0.0).any())

    def device(self) -> tuple:
        """The ``(temperature, top_k, top_p, keys)`` device columns the
        tick consumes — re-uploaded (async) only when dirty."""
        if self._dev is None or self._dirty:
            import jax.numpy as jnp

            self._dev = (jnp.asarray(self.temperature),
                         jnp.asarray(self.top_k),
                         jnp.asarray(self.top_p),
                         jnp.asarray(self.key))
            self._dirty = False
        return self._dev
