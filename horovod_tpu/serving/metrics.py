"""Serving observability: counters, gauges, and fixed-bucket histograms.

Deliberately dependency-free (stdlib only) and thread-safe — instruments
are updated from the engine thread and read from HTTP handler threads.
Snapshots are plain dicts so ``/stats`` can ``json.dumps`` them
directly.  Percentiles come from the cumulative bucket counts (the
Prometheus-style estimate: the reported pN is the upper edge of the
bucket containing the N-th percentile observation), which keeps memory
constant no matter how long the server runs.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence


class Counter:
    def __init__(self) -> None:
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        return self._v


class Gauge:
    def __init__(self) -> None:
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    @property
    def value(self) -> float:
        return self._v


# Latency buckets in seconds: 1ms .. 60s, roughly x2.5 per step — wide
# enough for CPU-smoke ticks and TPU production alike.
DEFAULT_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# Tick-phase buckets extend down to 10us: an async dispatch (and a
# fully-hidden device wait) is sub-millisecond, which the request-level
# buckets above cannot resolve.
TICK_PHASE_BUCKETS = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
) + DEFAULT_LATENCY_BUCKETS


class Histogram:
    """Fixed-bucket histogram with an implicit +Inf overflow bucket."""

    def __init__(self, buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        self.buckets: List[float] = sorted(float(b) for b in buckets)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        i = 0
        while i < len(self.buckets) and v > self.buckets[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def mean(self) -> Optional[float]:
        return self._sum / self._count if self._count else None

    def _percentile(self, counts: List[int], total: int,
                    q: float) -> Optional[float]:
        if not total:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank:
                return self.buckets[i] if i < len(self.buckets) \
                    else self.buckets[-1]
        return self.buckets[-1]

    def percentile(self, q: float) -> Optional[float]:
        """Upper edge of the bucket holding the q-quantile observation
        (q in [0, 1]); None when empty, +Inf bucket reports the largest
        finite edge."""
        with self._lock:
            counts, total = list(self._counts), self._count
        return self._percentile(counts, total, q)

    def snapshot(self) -> Dict:
        # One locked copy; count/sum/buckets AND percentiles all
        # describe the same population (an observe() racing /stats must
        # not split them).
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        return {
            "count": total,
            "sum": round(s, 6),
            "mean": round(s / total, 6) if total else None,
            "p50": self._percentile(counts, total, 0.50),
            "p99": self._percentile(counts, total, 0.99),
            "buckets": {
                ("%g" % b): c for b, c in zip(self.buckets, counts)
            } | {"+Inf": counts[-1]},
        }


class ServingMetrics:
    """The engine's instrument panel, surfaced verbatim through /stats.

    * ``ttft`` — submit-to-first-token latency (prefill + queueing).
    * ``token_latency`` — per-token decode-tick latency.
    * ``queue_depth`` / ``slot_occupancy`` — gauges sampled every tick.
    * ``admitted`` / ``rejected`` / ``completed`` / ``cancelled`` —
      request counters (rejected covers queue-full, deadline, and
      too-long — BOTH the submit-time and the take-time paths;
      cancelled covers caller-side :meth:`GenerationFuture.cancel`,
      including the server's 504 slot reclamation).
    * ``engine_failures`` / ``engine_restarts`` — fault-tolerance
      counters: every tick failure or watchdog stall, and every
      successful supervised restart (fresh slot cache).
    * ``tick_dispatch`` / ``tick_device_wait`` / ``tick_host`` — the
      pipeline phase timers: time to BUILD AND DISPATCH a decode tick
      (async — returns before the device finishes), time BLOCKED
      fetching a tick's results (the host-visible device wait; with the
      overlapped loop this is the residual the pipeline could not
      hide), and time in host bookkeeping (emit / retire / admission
      accounting).  ``device_wait / (dispatch + device_wait + host)``
      is the overlap-efficiency number ``benchmarks/serving.py``
      reports — 1.0 means every host cycle was hidden behind device
      compute.
    * ``decode_ticks`` / ``host_syncs`` — dispatched decode ticks and
      host sync points (value fetches that block on device work) on
      the decode hot path.  Steady-state overlapped decode performs
      exactly ONE sync per tick (the deferred fetch of the previous
      tick); ``host_syncs_per_tick`` in the snapshot is the regression
      guard against an accidental ``np.asarray`` /
      ``block_until_ready`` creeping back onto the hot path.
    """

    def __init__(self) -> None:
        self.ttft = Histogram()
        self.token_latency = Histogram()
        self.queue_depth = Gauge()
        self.slot_occupancy = Gauge()
        self.admitted = Counter()
        self.rejected = Counter()
        self.completed = Counter()
        self.cancelled = Counter()
        self.tokens_generated = Counter()
        self.engine_failures = Counter()
        self.engine_restarts = Counter()
        self.tick_dispatch = Histogram(buckets=TICK_PHASE_BUCKETS)
        self.tick_device_wait = Histogram(buckets=TICK_PHASE_BUCKETS)
        self.tick_host = Histogram(buckets=TICK_PHASE_BUCKETS)
        self.decode_ticks = Counter()
        self.host_syncs = Counter()

    def snapshot(self) -> Dict:
        ticks = self.decode_ticks.value
        return {
            "ttft_seconds": self.ttft.snapshot(),
            "token_latency_seconds": self.token_latency.snapshot(),
            "queue_depth": self.queue_depth.value,
            "slot_occupancy": self.slot_occupancy.value,
            "requests_admitted": self.admitted.value,
            "requests_rejected": self.rejected.value,
            "requests_completed": self.completed.value,
            "requests_cancelled": self.cancelled.value,
            "tokens_generated": self.tokens_generated.value,
            "engine_failures": self.engine_failures.value,
            "engine_restarts": self.engine_restarts.value,
            "tick_dispatch_seconds": self.tick_dispatch.snapshot(),
            "tick_device_wait_seconds": self.tick_device_wait.snapshot(),
            "tick_host_seconds": self.tick_host.snapshot(),
            "decode_ticks": ticks,
            "host_syncs": self.host_syncs.value,
            "host_syncs_per_tick":
                round(self.host_syncs.value / ticks, 4) if ticks else None,
        }
