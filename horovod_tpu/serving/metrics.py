"""Serving observability: the engine's instrument panel, now a thin
view over :mod:`horovod_tpu.obs.registry`.

Historically this module owned its own Counter/Gauge/Histogram classes;
those now live in the process-wide registry layer (same semantics,
thread-safe, constant-memory histograms) and are re-exported here for
backward compatibility.  :class:`ServingMetrics` registers every
instrument under a ``serving_*`` Prometheus family name in a PRIVATE
:class:`~horovod_tpu.obs.registry.MetricsRegistry` (one per engine
lifetime — tests and benchmarks create many engines per process, and
their series must not collide), keeps the original attribute API the
engine updates (``metrics.admitted.inc()`` …), and keeps the original
``snapshot()`` dict the ``/stats`` endpoint serves.  The server's
``GET /metrics`` renders this registry PLUS the default registry
(training/elastic/timeline families) as Prometheus text exposition.
"""

from __future__ import annotations

from typing import Dict, Optional

from horovod_tpu.obs.registry import (  # noqa: F401  (back-compat re-export)
    DEFAULT_LATENCY_BUCKETS,
    TICK_PHASE_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "ServingMetrics",
    "DEFAULT_LATENCY_BUCKETS", "TICK_PHASE_BUCKETS",
]


class ServingMetrics:
    """The engine's instrument panel, surfaced verbatim through /stats
    and as Prometheus families through /metrics.

    * ``ttft`` — submit-to-first-token latency (prefill + queueing),
      a ``{class=}``-labeled family (one child histogram per SLO
      priority class) so the per-class tail the scheduler orders on
      is observable per class; ``/stats`` serves both the merged
      population (``ttft_seconds``, the historical key) and the
      per-class split (``ttft_seconds_by_class``).
    * ``queue_wait`` — submit-to-admission latency, same ``{class=}``
      labeling: the share of TTFT the SLO scheduler can actually move
      (prefill cost is the model's).
    * ``preemptions`` — admitted requests suspended under slot/page
      pressure (journal frontier kept, re-admitted later, output
      byte-identical); the victim count the preemption policy pays
      for bounded winner wait.
    * ``token_latency`` — per-token decode-tick latency.
    * ``queue_depth`` / ``slot_occupancy`` — gauges sampled every tick.
    * ``admitted`` / ``rejected`` / ``completed`` / ``cancelled`` —
      request counters (rejected covers queue-full, deadline, and
      too-long — BOTH the submit-time and the take-time paths;
      cancelled covers caller-side :meth:`GenerationFuture.cancel`,
      including the server's 504 slot reclamation).
    * ``engine_failures`` / ``engine_restarts`` — fault-tolerance
      counters: every tick failure or watchdog stall, and every
      successful supervised restart (fresh slot cache).
    * ``resumed`` / ``resume_wasted_tokens`` — durability counters
      (docs/serving.md "Operations"): in-flight requests re-admitted
      across a supervised restart with their futures still live, and
      the tokens those re-admissions re-prefilled (original prompt +
      previously emitted) — the bounded price of not re-executing
      from scratch.  ``resume_wasted_tokens / tokens_generated`` is
      the wasted-token ratio ``benchmarks/serving.py --chaos``
      reports.
    * ``tick_dispatch`` / ``tick_device_wait`` / ``tick_host`` — the
      pipeline phase timers: time to BUILD AND DISPATCH a decode tick
      (async — returns before the device finishes), time BLOCKED
      fetching a tick's results (the host-visible device wait; with the
      overlapped loop this is the residual the pipeline could not
      hide), and time in host bookkeeping (emit / retire / admission
      accounting).  ``device_wait / (dispatch + device_wait + host)``
      is the overlap-efficiency number ``benchmarks/serving.py``
      reports — 1.0 means every host cycle was hidden behind device
      compute.
    * ``kv_pages_total`` / ``kv_pages_free`` / ``kv_pages_shared`` /
      ``kv_bytes_per_token`` — page-pool pressure gauges for the paged
      KV cache (docs/serving.md "Paged KV cache"): pool size, free
      heap depth (admission headroom), pages referenced by >1 owner
      (prefix sharing in effect), and the per-token cache cost the
      ``kv_dtype`` lever moves.  All 0 on a slot-contiguous engine.
    * ``decode_ticks`` / ``host_syncs`` — dispatched decode ticks and
      host sync points (value fetches that block on device work) on
      the decode hot path.  Steady-state overlapped decode performs
      exactly ONE sync per tick (the deferred fetch of the previous
      tick); ``host_syncs_per_tick`` in the snapshot is the regression
      guard against an accidental ``np.asarray`` /
      ``block_until_ready`` creeping back onto the hot path.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        r = registry if registry is not None else MetricsRegistry()
        self.registry = r
        self.ttft = r.histogram(
            "serving_ttft_seconds",
            "Submit-to-first-token latency (queueing + prefill), "
            "labeled by SLO priority class",
            labels=("class",))
        self.queue_wait = r.histogram(
            "serving_queue_wait_seconds",
            "Submit-to-admission latency, labeled by SLO priority "
            "class — the share of TTFT scheduling policy can move",
            labels=("class",))
        self.preemptions = r.counter(
            "serving_preemptions_total",
            "Admitted requests suspended under slot/page pressure "
            "(requeued with their journal frontier; output stays "
            "byte-identical)")
        self.token_latency = r.histogram(
            "serving_token_latency_seconds",
            "Per-token decode-tick latency (dispatch to host fetch)")
        self.queue_depth = r.gauge(
            "serving_queue_depth", "Requests queued awaiting admission")
        self.slot_occupancy = r.gauge(
            "serving_slot_occupancy", "Active slots / total slots")
        self.admitted = r.counter(
            "serving_requests_admitted_total", "Requests admitted to slots")
        self.rejected = r.counter(
            "serving_requests_rejected_total",
            "Typed rejections (queue-full, deadline, too-long)")
        self.completed = r.counter(
            "serving_requests_completed_total",
            "Requests retired with tokens (eos/length/capacity/deadline)")
        self.cancelled = r.counter(
            "serving_requests_cancelled_total",
            "Requests cancelled caller-side (incl. 504 slot reclamation)")
        self.tokens_generated = r.counter(
            "serving_tokens_generated_total", "Tokens emitted to futures")
        self.resumed = r.counter(
            "serving_requests_resumed_total",
            "In-flight requests re-admitted after an engine restart "
            "(journaled decode state; the original future stays live)")
        self.resume_wasted_tokens = r.counter(
            "serving_resume_wasted_tokens",
            "Tokens re-prefilled by resume admissions (prompt + "
            "previously emitted) — the bounded re-work durability costs")
        self.engine_failures = r.counter(
            "serving_engine_failures_total",
            "Tick failures and watchdog stalls")
        self.engine_restarts = r.counter(
            "serving_engine_restarts_total",
            "Successful supervised restarts (fresh slot cache)")
        self.tick_dispatch = r.histogram(
            "serving_tick_dispatch_seconds",
            "Time to build and dispatch one decode tick (async)",
            buckets=TICK_PHASE_BUCKETS)
        self.tick_device_wait = r.histogram(
            "serving_tick_device_wait_seconds",
            "Host-visible wait fetching a tick's results",
            buckets=TICK_PHASE_BUCKETS)
        self.tick_host = r.histogram(
            "serving_tick_host_seconds",
            "Host bookkeeping per tick (emit/retire/admission)",
            buckets=TICK_PHASE_BUCKETS)
        self.decode_ticks = r.counter(
            "serving_decode_ticks_total", "Decode ticks dispatched")
        self.host_syncs = r.counter(
            "serving_host_syncs_total",
            "Host sync points (blocking value fetches) on the decode path")
        self.kv_pages_total = r.gauge(
            "serving_kv_pages_total",
            "KV page pool size (paged cache; 0 = slot-contiguous)")
        self.kv_pages_free = r.gauge(
            "serving_kv_pages_free",
            "KV pages on the free heap (admission headroom)")
        self.kv_pages_shared = r.gauge(
            "serving_kv_pages_shared",
            "KV pages referenced by more than one owner "
            "(prefix sharing in effect)")
        self.kv_bytes_per_token = r.gauge(
            "serving_kv_bytes_per_token",
            "KV cache bytes per stored token (k+v across layers, "
            "incl. int8 scales) — the kv_dtype lever made legible")
        # Speculative decoding (docs/serving.md "Speculative decoding"):
        # tokens_per_tick is the multiplier made visible — every active
        # slot observes how many tokens one tick emitted for it (always
        # 1 on a non-speculative engine, 1..K+1 under speculation), so
        # the speculative A/B and the overlap pipeline report on the
        # same per-tick axis.  Acceptance is drafted-vs-accepted:
        # wasted = drafted - accepted is the draft compute speculation
        # burned on disagreement.
        self.tokens_per_tick = r.histogram(
            "serving_tokens_per_tick",
            "Tokens emitted per slot per decode tick (1 without "
            "speculation; 1..K+1 with it)",
            buckets=tuple(float(b) for b in range(1, 18)))
        self.spec_drafted = r.counter(
            "serving_spec_drafted_tokens_total",
            "Draft tokens proposed to the verify kernel")
        self.spec_accepted = r.counter(
            "serving_spec_accepted_tokens_total",
            "Draft tokens the target's greedy verify accepted")
        self.spec_wasted = r.counter(
            "serving_spec_wasted_tokens_total",
            "Draft tokens rejected by the verify (drafted - accepted)")
        self.spec_acceptance = r.histogram(
            "serving_spec_acceptance_ratio",
            "Accepted/drafted ratio per slot per speculative tick",
            buckets=(0.0, 0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875,
                     1.0))
        # Streaming transport (docs/serving.md "HTTP API"): per-token
        # SSE delivery, cancel-on-disconnect, and the user-facing
        # latency number streaming exists to improve — time to the
        # FIRST STREAMED TOKEN EVENT on the wire (vs ttft, which stops
        # at the engine emitting it).
        self.streamed_tokens = r.counter(
            "serving_streamed_tokens_total",
            "Tokens delivered as SSE token events (stream=true)")
        self.disconnects = r.counter(
            "serving_disconnects_total",
            "Streaming clients that vanished mid-stream (request "
            "cancelled, slot/pages reclaimed within one tick)")
        self.streamed_ttfb = r.histogram(
            "serving_streamed_ttfb_seconds",
            "Request arrival to first streamed token event on the "
            "wire (the honest user-facing TTFT for stream=true)")
        # Tensor-parallel serving (docs/serving.md "Tensor-parallel
        # replicas"): the replica's tp degree as a gauge so a fleet
        # dashboard can tell a tp=4 replica's tok/s from four tp=1
        # replicas' at a glance (cataloged in docs/observability.md).
        self.tp_degree = r.gauge(
            "serving_tp_degree",
            "Tensor-parallel degree of this engine's serving mesh "
            "(1 = unsharded single-device serving)")
        self.model_flops_per_token = r.gauge(
            "serving_model_flops_per_token",
            "Configured model FLOPs per generated token "
            "(EngineConfig.model_flops_per_token; 0 = not configured)")
        self.achieved_flops = r.gauge(
            "serving_achieved_flops_per_sec",
            "Achieved model FLOP/s over the recent token-rate window "
            "(tokens/sec x model_flops_per_token; 0 until configured "
            "and two samples apart)")
        # Online autotuning (docs/serving.md "Autotuning"): one sample
        # = one scored knob setting over one window of worked ticks.
        # Registered unconditionally (cheap) so the families are
        # documented and lint-checked whether or not a tuner runs.
        self.tuning_samples = r.counter(
            "tuning_samples_total",
            "Knob settings scored by the online autotuner "
            "(one per scoring window, warmup/settling discarded)")
        self.tuning_rollbacks = r.counter(
            "tuning_rollbacks_total",
            "Tuning samples rolled back for violating a per-class "
            "SLO constraint beyond the guard band")
        self.tuning_objective = r.gauge(
            "tuning_objective",
            "Weighted objective of the most recent scored window")
        self.tuning_best_objective = r.gauge(
            "tuning_best_objective",
            "Best constraint-satisfying objective seen this trajectory")

    # -- per-class observation hooks ---------------------------------------

    def observe_ttft(self, priority: str, v: float) -> None:
        self.ttft.labels(**{"class": priority}).observe(v)

    def observe_queue_wait(self, priority: str, v: float) -> None:
        self.queue_wait.labels(**{"class": priority}).observe(v)

    @staticmethod
    def _merged(family) -> Dict:
        """Class-merged histogram snapshot — the historical /stats
        shape (count/sum/mean/p50/p99/buckets over the WHOLE
        population), rebuilt bucket-wise from the labeled children
        (they all share the default bucket edges)."""
        h = Histogram()
        for _, child in family.children():
            st = child.state()
            h._counts = [a + b for a, b in zip(h._counts, st["counts"])]
            h._sum += st["sum"]
            h._count += st["count"]
        return h.snapshot()

    @staticmethod
    def _by_class(family) -> Dict:
        return {key[0]: child.snapshot()
                for key, child in family.children()}

    def snapshot(self) -> Dict:
        ticks = self.decode_ticks.value
        return {
            "ttft_seconds": self._merged(self.ttft),
            "ttft_seconds_by_class": self._by_class(self.ttft),
            "queue_wait_seconds_by_class": self._by_class(self.queue_wait),
            "preemptions": self.preemptions.value,
            "token_latency_seconds": self.token_latency.snapshot(),
            "queue_depth": self.queue_depth.value,
            "slot_occupancy": self.slot_occupancy.value,
            "requests_admitted": self.admitted.value,
            "requests_rejected": self.rejected.value,
            "requests_completed": self.completed.value,
            "requests_cancelled": self.cancelled.value,
            "requests_resumed": self.resumed.value,
            "resume_wasted_tokens": self.resume_wasted_tokens.value,
            "tokens_generated": self.tokens_generated.value,
            "engine_failures": self.engine_failures.value,
            "engine_restarts": self.engine_restarts.value,
            "tick_dispatch_seconds": self.tick_dispatch.snapshot(),
            "tick_device_wait_seconds": self.tick_device_wait.snapshot(),
            "tick_host_seconds": self.tick_host.snapshot(),
            "decode_ticks": ticks,
            "kv_pages_total": self.kv_pages_total.value,
            "kv_pages_free": self.kv_pages_free.value,
            "kv_pages_shared": self.kv_pages_shared.value,
            "kv_bytes_per_token": self.kv_bytes_per_token.value,
            "tokens_per_tick": self.tokens_per_tick.snapshot(),
            "spec_drafted_tokens": self.spec_drafted.value,
            "spec_accepted_tokens": self.spec_accepted.value,
            "spec_wasted_tokens": self.spec_wasted.value,
            "spec_acceptance_ratio":
                round(self.spec_accepted.value / self.spec_drafted.value,
                      4) if self.spec_drafted.value else None,
            "streamed_tokens": self.streamed_tokens.value,
            "disconnects": self.disconnects.value,
            "streamed_ttfb_seconds": self.streamed_ttfb.snapshot(),
            "host_syncs": self.host_syncs.value,
            "host_syncs_per_tick":
                round(self.host_syncs.value / ticks, 4) if ticks else None,
            "model_flops_per_token":
                self.model_flops_per_token.value or None,
            "achieved_flops_per_sec": self.achieved_flops.value or None,
        }
