"""Slot-based KV-cache manager for continuous batching.

The device side is the transformer's existing STATIC cache layout
(:func:`horovod_tpu.models.transformer.init_cache` with ``batch = S``)
with one change: ``pos`` is a PER-SLOT ``(S,)`` vector instead of a
shared scalar, because every slot holds a different request at a
different depth.  The host side (:class:`SlotCache`) is plain free-list
bookkeeping: slots are allocated FCFS-lowest-index, freed on
retirement, and the active set is exported as a ``(S,)`` bool mask that
the engine feeds to :func:`~horovod_tpu.models.transformer.
decode_step_slots` every tick — the live set is DATA, not structure, so
the decode executable never recompiles as requests come and go.

A freed slot is NOT scrubbed: decode writes position ``p`` in the same
step that first attends it, so whatever the previous tenant left behind
is overwritten before the next one can attend it (the argument is
spelled out on ``decode_step_slots``; the no-contamination test in
``tests/test_serving.py`` exercises it).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu.models import transformer as T


def init_slot_cache(cfg: "T.TransformerConfig", n_slots: int,
                    max_len: int = 0) -> Dict:
    """A per-layer KV cache with ``n_slots`` independent request slots:
    ``k``/``v`` are ``(L, S, H_kv, T, Dh)`` exactly as
    :func:`~horovod_tpu.models.transformer.init_cache` lays them out for
    ``batch = S``, and ``pos`` is ``(S,)`` int32 — one write position per
    slot."""
    base = T.init_cache(cfg, n_slots, max_len)
    return {"k": base["k"], "v": base["v"],
            "pos": jnp.zeros((n_slots,), jnp.int32)}


def insert_prefill(cache: Dict, slot, prefilled: Dict) -> Dict:
    """Land a batch-1 prefilled cache in slot ``slot`` of a slot cache.

    ``prefilled`` is the cache returned by a single-request
    :func:`~horovod_tpu.models.transformer.prefill` — ``k``/``v`` shaped
    ``(L, 1, H_kv, T_pre, Dh)`` with ``T_pre <= T`` and scalar ``pos``.
    One ``lax.dynamic_update_slice`` per tensor writes the block at
    ``(layer 0, slot, head 0, position 0, dim 0)``; ``slot`` may be
    traced, so a jitted wrapper compiles once per prefill bucket shape
    and serves every slot index."""
    slot = jnp.asarray(slot, jnp.int32)
    zero = jnp.int32(0)
    k = lax.dynamic_update_slice(
        cache["k"], prefilled["k"].astype(cache["k"].dtype),
        (zero, slot, zero, zero, zero))
    v = lax.dynamic_update_slice(
        cache["v"], prefilled["v"].astype(cache["v"].dtype),
        (zero, slot, zero, zero, zero))
    pos = cache["pos"].at[slot].set(prefilled["pos"].astype(jnp.int32))
    return {"k": k, "v": v, "pos": pos}


def insert_prefill_batch(cache: Dict, slots, prefilled: Dict) -> Dict:
    """Land a batch-K prefilled cache in K slots of a slot cache.

    ``prefilled`` is the cache returned by a batch-K
    :func:`~horovod_tpu.models.transformer.prefill` with a PER-ROW
    ``true_len`` — ``k``/``v`` shaped ``(L, K, H_kv, T_pre, Dh)`` with
    ``T_pre <= T`` and ``pos`` a ``(K,)`` vector of per-row counts.
    Row ``i`` lands in slot ``slots[i]`` via one scatter per tensor;
    ``slots`` may be traced, so a jitted wrapper compiles once per
    ``(K, T_pre)`` shape and serves every slot assignment."""
    slots = jnp.asarray(slots, jnp.int32)
    t_pre = prefilled["k"].shape[3]
    k = cache["k"].at[:, slots, :, :t_pre, :].set(
        prefilled["k"].astype(cache["k"].dtype))
    v = cache["v"].at[:, slots, :, :t_pre, :].set(
        prefilled["v"].astype(cache["v"].dtype))
    pos = cache["pos"].at[slots].set(prefilled["pos"].astype(jnp.int32))
    return {"k": k, "v": v, "pos": pos}


class SlotCache:
    """Host-side slot allocator wrapped around one device slot cache.

    The device cache dict lives at :attr:`cache` and is REPLACED (never
    mutated) by :meth:`insert` and by the engine's decode tick — JAX
    functional style with host bookkeeping alongside.
    """

    def __init__(self, cfg: "T.TransformerConfig", n_slots: int,
                 max_len: int = 0):
        if n_slots < 1:
            raise ValueError(f"need at least one slot, got {n_slots}")
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len or cfg.max_seq
        self.cache = init_slot_cache(cfg, n_slots, self.max_len)
        self._active = np.zeros(n_slots, bool)
        self._free: List[int] = list(range(n_slots))
        # One compiled insert per prefill bucket shape (slot is traced);
        # the slot cache is donated — insert replaces it in place instead
        # of holding two full copies live.  The batch variant compiles
        # per (K, bucket) shape — the engine's batched admission path.
        self._insert = jax.jit(insert_prefill, donate_argnums=(0,))
        self._insert_batch = jax.jit(insert_prefill_batch,
                                     donate_argnums=(0,))

    # -- allocation ---------------------------------------------------------

    def alloc(self) -> Optional[int]:
        """Lowest free slot index, or ``None`` when the pool is full."""
        if not self._free:
            return None
        slot = self._free.pop(0)
        self._active[slot] = True
        return slot

    def free(self, slot: int) -> None:
        if not self._active[slot]:
            raise ValueError(f"slot {slot} is not active")
        self._active[slot] = False
        self._free.append(slot)
        self._free.sort()  # keep FCFS assignment at the lowest index

    def release_all(self) -> None:
        """Host-side reset: every slot freed (device K/V left in place —
        write-before-attend makes scrubbing unnecessary).  The engine's
        failure paths use this so a dead engine never reports phantom
        in-flight work."""
        self._active[:] = False
        self._free = list(range(self.n_slots))

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def active_count(self) -> int:
        return int(self._active.sum())

    @property
    def occupancy(self) -> float:
        return self.active_count / self.n_slots

    def active_mask(self) -> np.ndarray:
        """(S,) bool — a COPY, safe to hand to jit."""
        return self._active.copy()

    def positions(self) -> np.ndarray:
        return np.asarray(self.cache["pos"])

    # -- device ops ---------------------------------------------------------

    def insert(self, slot: int, prefilled: Dict) -> None:
        """Write a batch-1 prefilled cache into ``slot`` (which must be
        allocated) and adopt its position."""
        if not self._active[slot]:
            raise ValueError(f"slot {slot} is not allocated")
        self.cache = self._insert(self.cache, slot, prefilled)

    def insert_batch(self, slots, prefilled: Dict) -> None:
        """Write a batch-K prefilled cache (per-row ``true_len``
        prefill) into K allocated slots — row ``i`` lands in
        ``slots[i]`` — and adopt the per-row positions.  ONE device
        scatter for the whole admission group instead of K serial
        inserts."""
        for s in slots:
            if not self._active[s]:
                raise ValueError(f"slot {s} is not allocated")
        self.cache = self._insert_batch(
            self.cache, np.asarray(slots, np.int32), prefilled)
