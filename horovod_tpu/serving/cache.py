"""Slot-based KV-cache manager for continuous batching.

The device side is the transformer's existing STATIC cache layout
(:func:`horovod_tpu.models.transformer.init_cache` with ``batch = S``)
with one change: ``pos`` is a PER-SLOT ``(S,)`` vector instead of a
shared scalar, because every slot holds a different request at a
different depth.  The host side (:class:`SlotCache`) is plain free-list
bookkeeping: slots are allocated FCFS-lowest-index, freed on
retirement, and the active set is exported as a ``(S,)`` bool mask that
the engine feeds to :func:`~horovod_tpu.models.transformer.
decode_step_slots` every tick — the live set is DATA, not structure, so
the decode executable never recompiles as requests come and go.

A freed slot is NOT scrubbed: decode writes position ``p`` in the same
step that first attends it, so whatever the previous tenant left behind
is overwritten before the next one can attend it (the argument is
spelled out on ``decode_step_slots``; the no-contamination test in
``tests/test_serving.py`` exercises it).
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu.models import transformer as T
from horovod_tpu.serving.scheduler import CacheOutOfPagesError


def init_slot_cache(cfg: "T.TransformerConfig", n_slots: int,
                    max_len: int = 0) -> Dict:
    """A per-layer KV cache with ``n_slots`` independent request slots:
    ``k``/``v`` are ``(L, S, H_kv, T, Dh)`` exactly as
    :func:`~horovod_tpu.models.transformer.init_cache` lays them out for
    ``batch = S``, and ``pos`` is ``(S,)`` int32 — one write position per
    slot."""
    base = T.init_cache(cfg, n_slots, max_len)
    return {"k": base["k"], "v": base["v"],
            "pos": jnp.zeros((n_slots,), jnp.int32)}


def insert_prefill(cache: Dict, slot, prefilled: Dict) -> Dict:
    """Land a batch-1 prefilled cache in slot ``slot`` of a slot cache.

    ``prefilled`` is the cache returned by a single-request
    :func:`~horovod_tpu.models.transformer.prefill` — ``k``/``v`` shaped
    ``(L, 1, H_kv, T_pre, Dh)`` with ``T_pre <= T`` and scalar ``pos``.
    One ``lax.dynamic_update_slice`` per tensor writes the block at
    ``(layer 0, slot, head 0, position 0, dim 0)``; ``slot`` may be
    traced, so a jitted wrapper compiles once per prefill bucket shape
    and serves every slot index."""
    slot = jnp.asarray(slot, jnp.int32)
    zero = jnp.int32(0)
    k = lax.dynamic_update_slice(
        cache["k"], prefilled["k"].astype(cache["k"].dtype),
        (zero, slot, zero, zero, zero))
    v = lax.dynamic_update_slice(
        cache["v"], prefilled["v"].astype(cache["v"].dtype),
        (zero, slot, zero, zero, zero))
    pos = cache["pos"].at[slot].set(prefilled["pos"].astype(jnp.int32))
    return {"k": k, "v": v, "pos": pos}


def insert_prefill_batch(cache: Dict, slots, prefilled: Dict) -> Dict:
    """Land a batch-K prefilled cache in K slots of a slot cache.

    ``prefilled`` is the cache returned by a batch-K
    :func:`~horovod_tpu.models.transformer.prefill` with a PER-ROW
    ``true_len`` — ``k``/``v`` shaped ``(L, K, H_kv, T_pre, Dh)`` with
    ``T_pre <= T`` and ``pos`` a ``(K,)`` vector of per-row counts.
    Row ``i`` lands in slot ``slots[i]`` via one scatter per tensor;
    ``slots`` may be traced, so a jitted wrapper compiles once per
    ``(K, T_pre)`` shape and serves every slot assignment."""
    slots = jnp.asarray(slots, jnp.int32)
    t_pre = prefilled["k"].shape[3]
    k = cache["k"].at[:, slots, :, :t_pre, :].set(
        prefilled["k"].astype(cache["k"].dtype))
    v = cache["v"].at[:, slots, :, :t_pre, :].set(
        prefilled["v"].astype(cache["v"].dtype))
    pos = cache["pos"].at[slots].set(prefilled["pos"].astype(jnp.int32))
    return {"k": k, "v": v, "pos": pos}


class SlotCache:
    """Host-side slot allocator wrapped around one device slot cache.

    The device cache dict lives at :attr:`cache` and is REPLACED (never
    mutated) by :meth:`insert` and by the engine's decode tick — JAX
    functional style with host bookkeeping alongside.
    """

    def __init__(self, cfg: "T.TransformerConfig", n_slots: int,
                 max_len: int = 0):
        if n_slots < 1:
            raise ValueError(f"need at least one slot, got {n_slots}")
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len or cfg.max_seq
        self.cache = init_slot_cache(cfg, n_slots, self.max_len)
        self._active = np.zeros(n_slots, bool)
        self._free: List[int] = list(range(n_slots))
        # One compiled insert per prefill bucket shape (slot is traced);
        # the slot cache is donated — insert replaces it in place instead
        # of holding two full copies live.  The batch variant compiles
        # per (K, bucket) shape — the engine's batched admission path.
        self._insert = jax.jit(insert_prefill, donate_argnums=(0,))
        self._insert_batch = jax.jit(insert_prefill_batch,
                                     donate_argnums=(0,))

    # -- allocation ---------------------------------------------------------

    def alloc(self) -> Optional[int]:
        """Lowest free slot index, or ``None`` when the pool is full."""
        if not self._free:
            return None
        # A min-heap keeps FCFS-lowest-index assignment at O(log S) per
        # op; the old list.pop(0) + sort() was O(S log S) per
        # retirement on the hot path.
        slot = heapq.heappop(self._free)
        self._active[slot] = True
        return slot

    def free(self, slot: int) -> None:
        if not self._active[slot]:
            raise ValueError(f"slot {slot} is not active")
        self._active[slot] = False
        heapq.heappush(self._free, slot)

    def release_all(self) -> None:
        """Host-side reset: every slot freed (device K/V left in place —
        write-before-attend makes scrubbing unnecessary).  The engine's
        failure paths use this so a dead engine never reports phantom
        in-flight work."""
        self._active[:] = False
        self._free = list(range(self.n_slots))

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def active_count(self) -> int:
        return int(self._active.sum())

    @property
    def occupancy(self) -> float:
        return self.active_count / self.n_slots

    def active_mask(self) -> np.ndarray:
        """(S,) bool — a COPY, safe to hand to jit."""
        return self._active.copy()

    def positions(self) -> np.ndarray:
        return np.asarray(self.cache["pos"])

    # -- device ops ---------------------------------------------------------

    def insert(self, slot: int, prefilled: Dict) -> None:
        """Write a batch-1 prefilled cache into ``slot`` (which must be
        allocated) and adopt its position."""
        if not self._active[slot]:
            raise ValueError(f"slot {slot} is not allocated")
        self.cache = self._insert(self.cache, slot, prefilled)

    def insert_batch(self, slots, prefilled: Dict) -> None:
        """Write a batch-K prefilled cache (per-row ``true_len``
        prefill) into K allocated slots — row ``i`` lands in
        ``slots[i]`` — and adopt the per-row positions.  ONE device
        scatter for the whole admission group instead of K serial
        inserts."""
        for s in slots:
            if not self._active[s]:
                raise ValueError(f"slot {s} is not allocated")
        self.cache = self._insert_batch(
            self.cache, np.asarray(slots, np.int32), prefilled)


# --- paged layout (block allocator + page tables) -----------------------------
#
# The slot-contiguous layout above reserves max_len x S positions up
# front, so occupancy is bounded by the WORST-CASE request and mixed
# lengths fragment HBM.  The paged layout (PagedAttention, Kwon et al.,
# SOSP 2023) stores K/V as a pool of fixed-size pages; each slot owns an
# int32 page-table row, resolved INSIDE the compiled decode tick
# (models/transformer.py:decode_step_paged) — page tables are DATA, not
# structure, so allocation patterns never recompile anything.  Page 0 is
# the reserved NULL/trash page: never granted, the routing target for
# inactive rows' writes and unpopulated table entries.

NULL_PAGE = 0

_KV_DTYPES = {"bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
              "f32": jnp.float32, "float32": jnp.float32,
              "int8": jnp.int8}


def resolve_kv_dtype(cfg: "T.TransformerConfig", kv_dtype):
    """``(storage dtype, quantized?)`` for a ``kv_dtype`` spec: None =
    the model's compute dtype, "bf16" halves f32 cache bytes, "int8"
    quarters them (per-vector scales ride alongside;
    dequantize-on-attend in the tick)."""
    if kv_dtype is None:
        return cfg.dtype, False
    if isinstance(kv_dtype, str):
        if kv_dtype not in _KV_DTYPES:
            raise ValueError(f"unknown kv_dtype {kv_dtype!r}; expected "
                             f"one of {sorted(_KV_DTYPES)} or None")
        kv_dtype = _KV_DTYPES[kv_dtype]
    return kv_dtype, jnp.dtype(kv_dtype) == jnp.int8


def init_page_pool(cfg: "T.TransformerConfig", n_slots: int, n_pages: int,
                   page_size: int, kv_dtype=None) -> Dict:
    """The paged device cache: ``k``/``v`` are ``(L, P, H_kv, page,
    Dh)`` page pools (``P`` counts the NULL page), ``pos`` is the
    per-slot ``(S,)`` logical write position, and int8 storage adds
    ``k_scale``/``v_scale`` ``(L, P, H_kv, page)`` per-vector f32
    scales.  The page table itself is HOST state
    (:attr:`PagedSlotCache.table`), uploaded as data each tick."""
    dt, quant = resolve_kv_dtype(cfg, kv_dtype)
    L, Hkv, Dh = cfg.n_layers, cfg.kv_heads, cfg.head_dim
    pool = {
        "k": jnp.zeros((L, n_pages, Hkv, page_size, Dh), dt),
        "v": jnp.zeros((L, n_pages, Hkv, page_size, Dh), dt),
        "pos": jnp.zeros((n_slots,), jnp.int32),
    }
    if quant:
        pool["k_scale"] = jnp.zeros((L, n_pages, Hkv, page_size),
                                    jnp.float32)
        pool["v_scale"] = jnp.zeros((L, n_pages, Hkv, page_size),
                                    jnp.float32)
    return pool


def paged_insert(pool: Dict, slots, new_pos, phys, off,
                 prefilled_k, prefilled_v) -> Dict:
    """Land a prefilled K/V block into pages: position ``t`` of row
    ``i`` scatters to ``(page phys[i, t], offset off[i, t])`` — the
    index arrays are host-built DATA, so one executable per
    ``(K, bucket)`` shape serves every page assignment, every bucket
    alignment (suffix landings start mid-page after a COW), and junk
    routing (padding positions point at the NULL page).  ``slots`` /
    ``new_pos`` adopt the per-row positions (empty for slotless
    landings — prefix registration).  int8 pools quantize per vector
    on the way in, writing payload and scale in the same scatter."""
    k, v = prefilled_k, prefilled_v  # (L, K, H_kv, Tb, Dh)
    quant = "k_scale" in pool
    out = dict(pool)
    if quant:
        qk, sk = T.kv_quantize(k)
        qv, sv = T.kv_quantize(v)
        out["k"] = pool["k"].at[:, phys, :, off, :].set(
            jnp.transpose(qk, (1, 3, 0, 2, 4)))
        out["v"] = pool["v"].at[:, phys, :, off, :].set(
            jnp.transpose(qv, (1, 3, 0, 2, 4)))
        out["k_scale"] = pool["k_scale"].at[:, phys, :, off].set(
            jnp.transpose(sk, (1, 3, 0, 2)))
        out["v_scale"] = pool["v_scale"].at[:, phys, :, off].set(
            jnp.transpose(sv, (1, 3, 0, 2)))
    else:
        dt = pool["k"].dtype
        out["k"] = pool["k"].at[:, phys, :, off, :].set(
            jnp.transpose(k.astype(dt), (1, 3, 0, 2, 4)))
        out["v"] = pool["v"].at[:, phys, :, off, :].set(
            jnp.transpose(v.astype(dt), (1, 3, 0, 2, 4)))
    out["pos"] = pool["pos"].at[slots].set(new_pos)
    return out


def copy_page(pool: Dict, src, dst) -> Dict:
    """Copy one physical page (all layers, payload + scales) — the
    copy-on-write primitive.  ``src``/``dst`` are traced scalars, so
    one compile covers every copy."""
    out = dict(pool)
    for name in ("k", "v", "k_scale", "v_scale"):
        if name in pool:
            out[name] = pool[name].at[:, dst].set(pool[name][:, src])
    return out


def gather_prefix_pages(pool: Dict, pages):
    """Materialize ``pages`` (a ``(n,)`` id vector) as contiguous
    ``(k, v)`` of shape ``(L, H_kv, n * page, Dh)`` — the shared-prefix
    K/V handed to :func:`~horovod_tpu.models.transformer.
    prefill_with_prefix`.  int8 pools dequantize here (f32), so the
    suffix prefill attends real values."""
    k = pool["k"][:, pages]                   # (L, n, H_kv, ps, Dh)
    v = pool["v"][:, pages]
    L, n, Hkv, ps, Dh = k.shape
    k = jnp.moveaxis(k, 1, 2).reshape(L, Hkv, n * ps, Dh)
    v = jnp.moveaxis(v, 1, 2).reshape(L, Hkv, n * ps, Dh)
    if "k_scale" in pool:
        ks = jnp.moveaxis(pool["k_scale"][:, pages], 1, 2
                          ).reshape(L, Hkv, n * ps)
        vs = jnp.moveaxis(pool["v_scale"][:, pages], 1, 2
                          ).reshape(L, Hkv, n * ps)
        k = T.kv_dequantize(k, ks, jnp.float32)
        v = T.kv_dequantize(v, vs, jnp.float32)
    return k, v


class PagedSlotCache:
    """Host-side page allocator + slot bookkeeping over one device page
    pool.  API-compatible with :class:`SlotCache` where the engine
    touches it (alloc/free/active_mask/occupancy/...), plus the paging
    surface: per-slot page tables (:attr:`table`, uploaded as tick
    data; :attr:`table_version` bumps on every change so the engine
    re-uploads only then), a heapq free list of pages, REFCOUNTED pages
    for prefix sharing (:meth:`attach` / :meth:`grant_raw`), and
    copy-on-write (:meth:`cow`) so a shared page is copied only when a
    slot must write into it.

    Freed pages are NOT scrubbed: a page's next owner writes every
    position before first attending it (prefill landing covers the
    prompt span; decode writes position ``p`` the same tick it first
    attends ``p``) — the slot-contiguous write-before-attend argument,
    re-proven per page by the no-contamination test in
    ``tests/test_paged.py``."""

    def __init__(self, cfg: "T.TransformerConfig", n_slots: int,
                 max_len: int = 0, *, page_size: int = 16,
                 n_pages: int = 0, kv_dtype=None, mesh=None):
        if n_slots < 1:
            raise ValueError(f"need at least one slot, got {n_slots}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len or cfg.max_seq
        self.page_size = page_size
        self.max_pages = -(-self.max_len // page_size)
        # 0 = capacity parity with the slot-contiguous layout (every
        # slot can grow to max_len); a smaller pool is the whole point
        # — mixed-length traffic rarely needs worst case, and the
        # admission back-pressure handles the tail.
        self.n_pages = n_pages or n_slots * self.max_pages
        self.kv_dtype = kv_dtype
        # Tensor-parallel serving (docs/serving.md "Tensor-parallel
        # replicas"): with a mesh, the pool is allocated with an
        # EXPLICIT device sharding — payload (and int8 scales) split by
        # kv head over tp, per-slot pos replicated.  Everything
        # host-side below (tables, grants, refcounts, COW) is
        # sharding-OBLIVIOUS: pages are split by head, never by page
        # id, so the allocator's view of a page is unchanged.
        self.mesh = mesh
        self._storage_dtype, self.quantized = resolve_kv_dtype(
            cfg, kv_dtype)
        self.cache = init_page_pool(cfg, n_slots, self.n_pages + 1,
                                    page_size, kv_dtype)
        if mesh is not None:
            self.cache = T.shard_kv_pool(self.cache, mesh)
        self.table = np.zeros((n_slots, self.max_pages), np.int32)
        self.table_version = 0
        self._ref = np.zeros(self.n_pages + 1, np.int64)
        self._ref[NULL_PAGE] = 1  # never granted
        self._free_pages: List[int] = list(range(1, self.n_pages + 1))
        self._min_free = self.n_pages
        self._active = np.zeros(n_slots, bool)
        self._free: List[int] = list(range(n_slots))  # heap (sorted)
        # jax.jit caches one executable per input shape, so single
        # callables cover every (K, bucket) landing, every copy, and
        # every prefix-gather length.
        self._insert = jax.jit(paged_insert, donate_argnums=(0,))
        self._copy = jax.jit(copy_page, donate_argnums=(0,))
        self._gather = jax.jit(gather_prefix_pages)
        self._set_pos = jax.jit(
            lambda pool, s, v: {**pool, "pos": pool["pos"].at[s].set(v)},
            donate_argnums=(0,))

    # -- slot allocation (SlotCache-compatible) -----------------------------

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        slot = heapq.heappop(self._free)
        self._active[slot] = True
        return slot

    def acquire(self, slot: int) -> None:
        """Mark a SPECIFIC slot active — the paired-pool primitive: a
        draft model's page pool mirrors the target pool slot-for-slot
        (same slot ids, same retirement), so its allocator follows the
        target's choices instead of making its own.  Refcount/COW rules
        are unchanged; :meth:`free` releases as usual."""
        if self._active[slot]:
            raise ValueError(f"slot {slot} is already active")
        self._free.remove(slot)
        heapq.heapify(self._free)
        self._active[slot] = True

    def free(self, slot: int) -> None:
        """Retire a slot: every page its table references is
        dereferenced (a page reaching refcount 0 returns to the free
        heap — shared prefix pages survive until their last reference,
        including the registry's own pin, drops)."""
        if not self._active[slot]:
            raise ValueError(f"slot {slot} is not active")
        self._active[slot] = False
        heapq.heappush(self._free, slot)
        for pg in self.table[slot]:
            self._decref(int(pg))
        self.table[slot, :] = NULL_PAGE
        self.table_version += 1

    def release_all(self) -> None:
        """Host-side reset of slots AND pages (terminal/restart paths).
        Any prefix-registry pins die with this — the engine invalidates
        its registry whenever it resets the cache."""
        self._active[:] = False
        self._free = list(range(self.n_slots))
        self.table[:, :] = NULL_PAGE
        self.table_version += 1
        self._ref[:] = 0
        self._ref[NULL_PAGE] = 1
        self._free_pages = list(range(1, self.n_pages + 1))

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def active_count(self) -> int:
        return int(self._active.sum())

    @property
    def occupancy(self) -> float:
        return self.active_count / self.n_slots

    def active_mask(self) -> np.ndarray:
        """(S,) bool — a COPY, safe to hand to jit."""
        return self._active.copy()

    def positions(self) -> np.ndarray:
        return np.asarray(self.cache["pos"])

    # -- page accounting ----------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free_pages)

    @property
    def pages_shared(self) -> int:
        """Pages referenced more than once (prefix sharing in effect)."""
        return int((self._ref[1:] > 1).sum())

    @property
    def pages_high_water(self) -> int:
        """Most pages ever simultaneously allocated."""
        return self.n_pages - self._min_free

    @property
    def bytes_per_token(self) -> int:
        """KV bytes one token costs in this pool (the quantization
        lever made legible): payload for k+v across layers, plus the
        per-vector scales for int8."""
        elem = jnp.dtype(self._storage_dtype).itemsize
        n = self.cfg.n_layers * self.cfg.kv_heads
        b = 2 * n * self.cfg.head_dim * elem
        if self.quantized:
            b += 2 * n * 4  # f32 scale per (layer, head, token) vector
        return b

    def pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size) if n_tokens > 0 else 0

    def _pop_page(self) -> int:
        if not self._free_pages:
            raise CacheOutOfPagesError(
                f"page pool exhausted ({self.n_pages} pages, "
                f"{self.pages_shared} shared)")
        pg = heapq.heappop(self._free_pages)
        self._min_free = min(self._min_free, len(self._free_pages))
        return pg

    def _decref(self, pg: int) -> None:
        if pg == NULL_PAGE:
            return
        self._ref[pg] -= 1
        if self._ref[pg] == 0:
            heapq.heappush(self._free_pages, pg)
        elif self._ref[pg] < 0:  # pragma: no cover - allocator invariant
            raise AssertionError(f"page {pg} refcount underflow")

    # -- grants / sharing / COW --------------------------------------------

    def grant(self, slot: int, idx: int) -> int:
        """Grant a fresh PRIVATE page at table index ``idx`` (on-demand
        growth at a tick boundary).  Raises
        :class:`CacheOutOfPagesError` on an empty pool — the engine
        turns that into preemption or back-pressure, never silent
        over-allocation."""
        if self.table[slot, idx] != NULL_PAGE:
            raise ValueError(
                f"slot {slot} already has page {self.table[slot, idx]} "
                f"at index {idx}")
        pg = self._pop_page()
        self._ref[pg] = 1
        self.table[slot, idx] = pg
        self.table_version += 1
        return pg

    def grant_raw(self, n: int) -> List[int]:
        """``n`` pages owned by the CALLER (the prefix registry's pin),
        refcount 1 each, bound to no slot.  All-or-nothing."""
        if len(self._free_pages) < n:
            raise CacheOutOfPagesError(
                f"need {n} pages for prefix registration, "
                f"{len(self._free_pages)} free of {self.n_pages}")
        pages = []
        for _ in range(n):
            pg = self._pop_page()
            self._ref[pg] = 1
            pages.append(pg)
        return pages

    def release_raw(self, pages: Sequence[int]) -> None:
        """Drop a :meth:`grant_raw` pin (prefix unregistration)."""
        for pg in pages:
            self._decref(int(pg))

    def attach(self, slot: int, pages: Sequence[int]) -> None:
        """Reference shared pages from table indices ``0..len-1`` —
        prefix sharing: refcount++ per page, no copy, no compute."""
        for i, pg in enumerate(pages):
            if self.table[slot, i] != NULL_PAGE:
                raise ValueError(f"slot {slot} index {i} already mapped")
            self.table[slot, i] = pg
            self._ref[pg] += 1
        self.table_version += 1

    def cow(self, slot: int, idx: int) -> int:
        """Copy-on-write: make the page at table index ``idx`` PRIVATE
        to ``slot``.  A no-op if it already is; otherwise a fresh page
        is granted, the shared page's payload is copied on device, the
        table repointed, and the shared page dereferenced.  Called
        before ANY write can target a shared page — suffix landing
        into a partially-filled prefix page, or decode growing into
        one."""
        src = int(self.table[slot, idx])
        if src == NULL_PAGE:
            raise ValueError(f"slot {slot} has no page at index {idx}")
        if self._ref[src] <= 1:
            return src
        dst = self._pop_page()
        self._ref[dst] = 1
        self.cache = self._copy(self.cache, jnp.int32(src), jnp.int32(dst))
        self.table[slot, idx] = dst
        self._decref(src)
        self.table_version += 1
        return dst

    # -- device ops ---------------------------------------------------------

    def _phys_off(self, rows: Sequence[Sequence[int]], start: int,
                  true_lens, bucket: int):
        """Host-built landing indices: row ``i``'s position ``start +
        t`` maps to its page table unless past ``true_lens[i]`` (bucket
        padding), which routes to the NULL page."""
        ps = self.page_size
        logical = start + np.arange(bucket)
        idxs = np.clip(logical // ps, 0, self.max_pages - 1)
        phys = np.zeros((len(rows), bucket), np.int32)
        for i, row in enumerate(rows):
            p = np.asarray(row, np.int32)[idxs]
            phys[i] = np.where(logical < start + int(true_lens[i]), p,
                               NULL_PAGE)
        return phys, np.asarray(logical % ps, np.int32)

    def land(self, slots: Sequence[int], prefilled: Dict,
             true_lens, start: int = 0) -> None:
        """Land a prefilled (or suffix-prefilled) K/V block into the
        slots' granted pages with ONE scatter, and adopt the per-row
        positions from ``prefilled["pos"]``.  ``start`` is the logical
        position of bucket column 0 (0 for full prompts, the shared
        prefix length for suffix landings)."""
        for s in slots:
            if not self._active[s]:
                raise ValueError(f"slot {s} is not allocated")
        bucket = prefilled["k"].shape[3]
        phys, off = self._phys_off([self.table[s] for s in slots], start,
                                   true_lens, bucket)
        self.cache = self._insert(
            self.cache, np.asarray(slots, np.int32),
            prefilled["pos"].astype(jnp.int32), phys,
            np.broadcast_to(off, phys.shape), prefilled["k"],
            prefilled["v"])

    def land_raw(self, pages: Sequence[int], prefilled: Dict,
                 true_len: int) -> None:
        """Slotless landing into raw pages (prefix registration): the
        prefix block fills ``pages`` in order; no slot position is
        touched."""
        bucket = prefilled["k"].shape[3]
        row = list(pages) + [NULL_PAGE] * max(
            0, self.max_pages - len(pages))
        phys, off = self._phys_off([row], 0, [true_len], bucket)
        empty = np.zeros((0,), np.int32)
        self.cache = self._insert(
            self.cache, empty, jnp.zeros((0,), jnp.int32), phys,
            np.broadcast_to(off, phys.shape), prefilled["k"],
            prefilled["v"])

    def set_pos(self, slots: Sequence[int], vals: Sequence[int]) -> None:
        """Adopt positions without landing (attach-only admission — the
        whole prompt already lives in shared pages)."""
        self.cache = self._set_pos(
            self.cache, np.asarray(slots, np.int32),
            np.asarray(vals, np.int32))

    def gather_prefix(self, pages: Sequence[int]):
        """Contiguous ``(k, v)`` for a shared prefix's pages (see
        :func:`gather_prefix_pages`)."""
        return self._gather(self.cache, np.asarray(pages, np.int32))
