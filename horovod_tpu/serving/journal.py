"""Decode-state journaling: the durable record that makes in-flight
requests RESUMABLE instead of merely restartable.

The paper's fault story (elastic re-rendezvous + ``Join``) keeps the
*job* alive but discards in-flight work; the serving stack inherited
that shape — a supervised engine restart used to fail every in-flight
request, and router failover re-executed a dead replica's requests
from scratch.  At production request lengths that throws away seconds
of paid-for prefill and decode per incident.  The journal closes the
gap: for every live request it records exactly what a resume needs —
the ORIGINAL prompt, the generation parameters, the trace id, the
deadline, and the tokens emitted so far — so a crash costs one tick of
work plus one re-prefill, never the whole request.

Semantics that make resume oracle-exact:

* Tokens are appended ONLY when the engine emits them to the request's
  future (``InferenceEngine._emit``, reached from ``_retire_pending``)
  — the overlapped pipeline's one-tick-lag identity check has already
  run, so the journal never records a token the greedy oracle would
  not have emitted (a dispatched-but-unfetched tick's tokens are the
  "one tick of wasted work" a crash may cost).
* Greedy decode is a pure function of the token sequence, so
  re-prefilling ``prompt + emitted`` and continuing decode yields a
  concatenated output byte-identical to an uninterrupted run.
* An entry ends (and is purged) the instant its future resolves — by
  retirement, typed rejection, cancellation, ``terminate()``, or drain
  force-resolve — so a later restart can never ghost-re-admit work
  nobody is waiting for.

Two tiers of durability:

* **In-memory** (always on with ``EngineConfig.resume``): survives a
  supervised engine restart inside one process — ``_restart``
  re-admits journaled requests with their original
  :class:`~horovod_tpu.serving.engine.GenerationFuture` still live.
* **File-backed** (``EngineConfig.journal_path``): an append-only
  JSONL event log, flushed per event (page cache — the record
  survives SIGKILL of the process, which is the router failover
  story).  :meth:`RequestJournal.read_live` parses a dead replica's
  journal post-mortem, tolerating a torn final line, and returns a
  resume descriptor per live trace id — what
  ``router/server.py`` re-dispatches to a surviving replica.

Journaling is pure host bookkeeping: no device op, no host sync — the
engine's ≤ 1-host-sync-per-tick guarantee is untouched (the perf guard
in ``tests/test_overlap.py`` runs with journaling on by default).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["JournalEntry", "RequestJournal"]


@dataclasses.dataclass
class JournalEntry:
    """Everything a resume needs, for ONE live request.

    ``prompt`` / ``max_new_tokens`` are the ORIGINAL submission (never
    rewritten by a resume — the resume prompt is derived as ``prompt +
    emitted`` each time, so repeated crashes cannot compound).
    ``deadline`` is the in-process absolute ``time.monotonic()``
    instant; ``expires_at`` is the same deadline as absolute wall
    clock, the only form a DIFFERENT process (the router reading a
    dead replica's journal) can interpret."""

    id: int
    prompt: tuple
    max_new_tokens: int
    eos_id: Optional[int] = None
    deadline: Optional[float] = None
    expires_at: Optional[float] = None
    trace_id: Optional[str] = None
    #: the originating request SPAN id (obs/tracing.py): a post-mortem
    #: journal lookup after a SIGKILL hands it to the router, which
    #: stamps it on the resume edge — the resumed attempt links into
    #: the same cross-process trace tree as the dead one.
    span_id: Optional[str] = None
    #: sampling parameters (serving/sampling.py) — a resume must decode
    #: with the ORIGINAL knobs and seed: the PRNG key schedule is
    #: position-based, so ``prompt + emitted`` at the same seed
    #: continues the exact token stream.
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0
    #: SLO priority class (docs/serving.md "Scheduling") — a resume
    #: (restart, preemption, or router failover) re-admits at the
    #: ORIGINAL class: surviving a crash must neither promote nor
    #: demote a request.
    priority: str = "interactive"
    #: ARRIVAL clocks (tuning/replay.py): ``arrival`` is the monotonic
    #: offset in seconds from journal open — the inter-arrival spacing
    #: a replay reproduces — and ``arrival_wall`` the absolute wall
    #: clock of the same instant (the only form another process can
    #: order against its own records).  Optional: journals written
    #: before the arrival field replay in file order at zero offset.
    arrival: Optional[float] = None
    arrival_wall: Optional[float] = None
    #: whether the original caller streamed (``on_token`` / SSE) — a
    #: replay drives streamed requests through the same callback path.
    stream: bool = False
    emitted: List[int] = dataclasses.field(default_factory=list)
    resumes: int = 0

    @property
    def remaining(self) -> int:
        """Decode budget left after the emitted tokens."""
        return self.max_new_tokens - len(self.emitted)

    def descriptor(self) -> Dict:
        """The RESUME DESCRIPTOR — the stable routing-contract shape
        (docs/serving.md "Front tier") a failover re-dispatch consumes:
        the tokens already emitted and the REMAINING wall-clock budget
        (a resumed request inherits what is left of its deadline,
        never a fresh one)."""
        remaining_ms: Optional[float] = None
        if self.expires_at is not None:
            remaining_ms = round((self.expires_at - time.time()) * 1e3, 3)
        return {
            "emitted_tokens": list(self.emitted),
            "deadline_remaining_ms": remaining_ms,
            "span_id": self.span_id,
        }


class RequestJournal:
    """Thread-safe journal of live requests, optionally file-backed.

    ``begin`` at submit, ``append`` per emitted token, ``note_resume``
    per re-admission, ``end`` on resolution (purges the entry).  With
    ``path``, every event is also an append-only JSONL line flushed to
    the kernel immediately — cheap (~µs), and exactly what survives a
    SIGKILL.  The file compacts itself once enough ended entries
    accumulate, so a long-lived replica's journal stays proportional
    to its LIVE request set, not its lifetime traffic."""

    #: ended entries tolerated in the file before a compaction rewrite
    COMPACT_AFTER = 512

    def __init__(self, path: Optional[str] = None):
        self._lock = threading.Lock()
        self._entries: Dict[int, JournalEntry] = {}
        self.path = path
        self._f = None
        self._dead_lines = 0
        # Arrival epoch: begin-lines carry each request's monotonic
        # offset from THIS instant (plus wall clock), so a replay
        # (tuning/replay.py) reconstructs true inter-arrival spacing
        # instead of inferring it from file order.
        self._opened_mono = time.monotonic()
        self._opened_wall = time.time()
        if path:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            self._f = open(path, "a", encoding="utf-8")

    # -- engine-side events -------------------------------------------------

    def begin(self, req) -> JournalEntry:
        """Open an entry for a freshly submitted request.  ``req`` is a
        :class:`~horovod_tpu.serving.scheduler.Request`; its monotonic
        deadline is translated to wall clock here, while both clocks
        still agree."""
        expires = None
        if req.deadline is not None:
            expires = time.time() + (req.deadline - time.monotonic())
        entry = JournalEntry(
            id=req.id, prompt=tuple(req.prompt),
            max_new_tokens=req.max_new_tokens, eos_id=req.eos_id,
            deadline=req.deadline, expires_at=expires,
            trace_id=req.trace.trace_id if req.trace is not None else None,
            span_id=req.trace.span_id if req.trace is not None else None,
            temperature=getattr(req, "temperature", 0.0),
            top_k=getattr(req, "top_k", 0),
            top_p=getattr(req, "top_p", 0.0),
            seed=getattr(req, "seed", 0),
            priority=getattr(req, "priority", "interactive"),
            arrival=round(time.monotonic() - self._opened_mono, 6),
            arrival_wall=time.time(),
            stream=getattr(getattr(req, "future", None),
                           "_on_token", None) is not None)
        with self._lock:
            self._entries[req.id] = entry
            self._write(self._begin_line(entry))
        return entry

    @staticmethod
    def _begin_line(entry: JournalEntry) -> Dict:
        """The ONE shape of a begin record (begin + compaction write
        it; :meth:`read_live` parses it).  Sampling keys are written
        only when non-default, keeping greedy journals byte-compatible
        with pre-sampling readers."""
        line = {"e": "b", "id": entry.id, "trace": entry.trace_id,
                "span": entry.span_id,
                "prompt": list(entry.prompt),
                "max_new": entry.max_new_tokens,
                "eos": entry.eos_id,
                "expires_at": entry.expires_at}
        if entry.temperature > 0.0:
            line["samp"] = [entry.temperature, entry.top_k,
                            entry.top_p, entry.seed]
        if entry.priority != "interactive":
            # Written only when non-default, like "samp": default-class
            # journals stay byte-compatible with pre-priority readers.
            line["pri"] = entry.priority
        if entry.arrival is not None:
            # [monotonic offset from journal open, wall clock] — a
            # NEW key old readers simply ignore (byte-compatible), and
            # the replay reader's arrival-spacing source of truth.
            line["arr"] = [entry.arrival, entry.arrival_wall]
        if entry.stream:
            line["stream"] = 1
        return line

    def append(self, rid: int, tok: int) -> None:
        """Record one EMITTED token (no-op for an already-ended entry —
        a concurrent resolution's purge always wins)."""
        with self._lock:
            entry = self._entries.get(rid)
            if entry is None:
                return
            entry.emitted.append(int(tok))
            self._write({"e": "t", "id": rid, "t": int(tok)})

    def note_resume(self, rid: int) -> None:
        with self._lock:
            entry = self._entries.get(rid)
            if entry is None:
                return
            entry.resumes += 1
            self._write({"e": "r", "id": rid})

    def end(self, rid: int) -> None:
        """Purge an entry — the request resolved (tokens, typed error,
        cancel, terminate, drain).  After this a restart can never
        re-admit it.  Idempotent."""
        with self._lock:
            if self._entries.pop(rid, None) is None:
                return
            self._write({"e": "e", "id": rid})
            self._dead_lines += 1
            if self._f is not None and self._dead_lines >= self.COMPACT_AFTER:
                self._compact_locked()

    # -- introspection ------------------------------------------------------

    def get(self, rid: int) -> Optional[JournalEntry]:
        with self._lock:
            return self._entries.get(rid)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def entries(self) -> List[JournalEntry]:
        with self._lock:
            return list(self._entries.values())

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                finally:
                    self._f = None

    # -- file backend -------------------------------------------------------

    def _write(self, obj: Dict) -> None:
        """Caller holds the lock.  ``flush`` pushes the line into the
        kernel page cache — that is the SIGKILL-durability boundary
        this journal defends (host death is the elastic layer's
        problem, not serving's)."""
        if self._f is None:
            return
        try:
            self._f.write(json.dumps(obj, separators=(",", ":")) + "\n")
            self._f.flush()
        except (OSError, ValueError):  # pragma: no cover - disk trouble
            pass  # journaling must never fail serving

    def _compact_locked(self) -> None:
        """Rewrite the file with only LIVE entries (atomic: tmp +
        rename, same recipe as CheckpointManager)."""
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                for entry in self._entries.values():
                    f.write(json.dumps(self._begin_line(entry),
                                       separators=(",", ":")) + "\n")
                    for tok in entry.emitted:
                        f.write(json.dumps({"e": "t", "id": entry.id,
                                            "t": tok},
                                           separators=(",", ":")) + "\n")
            self._f.close()
            os.replace(tmp, self.path)
            self._f = open(self.path, "a", encoding="utf-8")
            self._dead_lines = 0
        except OSError:  # pragma: no cover - disk trouble
            pass

    # -- post-mortem reader (the router failover path) ----------------------

    @staticmethod
    def read_live(path: str) -> Dict[str, Dict]:
        """Parse a journal file — typically a SIGKILL'd replica's —
        and return ``trace_id -> resume descriptor`` for every entry
        that never ended.  Tolerates a torn final line (the process
        died mid-write; every complete line before it is good).  The
        descriptor carries ``emitted_tokens`` and
        ``deadline_remaining_ms`` computed from the wall-clock
        ``expires_at`` AT READ TIME — time spent dead counts against
        the budget, exactly like time spent decoding."""
        live: Dict[int, JournalEntry] = {}
        try:
            with open(path, "r", encoding="utf-8") as f:
                raw = f.read()
        except OSError:
            return {}
        for line in raw.splitlines():
            if not line.strip():
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn write at the kill instant
            e, rid = ev.get("e"), ev.get("id")
            if e == "b":
                samp = ev.get("samp") or [0.0, 0, 0.0, 0]
                arr = ev.get("arr") or [None, None]
                live[rid] = JournalEntry(
                    id=rid, prompt=tuple(ev.get("prompt") or ()),
                    max_new_tokens=int(ev.get("max_new") or 0),
                    eos_id=ev.get("eos"),
                    expires_at=ev.get("expires_at"),
                    trace_id=ev.get("trace"),
                    span_id=ev.get("span"),
                    temperature=float(samp[0]), top_k=int(samp[1]),
                    top_p=float(samp[2]), seed=int(samp[3]),
                    priority=ev.get("pri") or "interactive",
                    arrival=arr[0], arrival_wall=arr[1],
                    stream=bool(ev.get("stream")))
            elif e == "t" and rid in live:
                live[rid].emitted.append(int(ev["t"]))
            elif e == "r" and rid in live:
                live[rid].resumes += 1
            elif e == "e":
                live.pop(rid, None)
        out: Dict[str, Dict] = {}
        for entry in live.values():
            if entry.trace_id is None:
                continue
            out[entry.trace_id] = {
                **entry.descriptor(),
                "prompt": list(entry.prompt),
                "max_new_tokens": entry.max_new_tokens,
                "eos_id": entry.eos_id,
                # Informational for the failover path: the router
                # re-dispatches the ORIGINAL request body (which
                # carries the sampling fields) — the position-based
                # key schedule makes the continuation automatic.
                "temperature": entry.temperature,
                "seed": entry.seed,
                # The router's scratch-rebuild failover path (no
                # original body survived) re-submits at the ORIGINAL
                # class; body-based failovers carry it in the body.
                "priority": entry.priority,
            }
        return out
