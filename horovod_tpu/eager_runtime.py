"""Eager-path runtime: Python side of the native control plane.

Wires the native library (:mod:`horovod_tpu.native` — negotiation, fusion
planning, response cache, stall inspection, timeline) to the JAX eager data
plane (:mod:`horovod_tpu.ops.collectives` ``_eager_*`` implementations).

Division of labor, mirroring the reference's architecture
(``common/operations.cc`` background loop -> ``ops/*`` execution):

* Python enqueues a named request per eager collective and blocks on a
  handle (the reference's framework-binding role,
  ``torch/mpi_ops_v2.cc:52-79``).
* The native background thread negotiates global readiness each cycle and
  calls back into :meth:`EagerRuntime._execute` with a (possibly fused)
  Response (the reference's ``PerformOperation``,
  ``common/operations.cc:295``).
* ``_execute`` runs the collective as an XLA program over the process mesh
  and parks results until the waiting caller collects them.

A rank that has Joined keeps executing responses with zero-filled inputs
(the reference's zero-tensor substitution, ``global_state.h:104-107``).
"""

from __future__ import annotations

import itertools
import os
import threading
from typing import Any, Dict, Optional

import numpy as np

from horovod_tpu import basics

try:
    from horovod_tpu import native
except Exception:  # pragma: no cover - native package always importable
    native = None  # type: ignore

_OP_TO_NATIVE = {}
_NATIVE_TO_OP = {}


def _op_maps():
    from horovod_tpu.ops import collectives as C

    global _OP_TO_NATIVE, _NATIVE_TO_OP
    if not _OP_TO_NATIVE:
        _OP_TO_NATIVE = {
            C.Average: native.OP_AVERAGE,
            C.Sum: native.OP_SUM,
            C.Adasum: native.OP_ADASUM,
            C.Min: native.OP_MIN,
            C.Max: native.OP_MAX,
            C.Product: native.OP_PRODUCT,
        }
        _NATIVE_TO_OP = {v: k for k, v in _OP_TO_NATIVE.items()}
    return _OP_TO_NATIVE, _NATIVE_TO_OP


class CollectiveError(RuntimeError):
    """A collective failed — coordinator-detected mismatch, stall shutdown,
    or abort (reference: Response::ERROR delivered to the status callback)."""


class EagerRuntime:
    def __init__(self, rt: "native.NativeRuntime") -> None:
        self._rt = rt
        self._lock = threading.Lock()
        self._inputs: Dict[str, np.ndarray] = {}
        self._results: Dict[str, Any] = {}
        self._counters = {k: itertools.count() for k in
                          ("allreduce", "allgather", "broadcast", "alltoall",
                           "reducescatter", "barrier")}
        # Fusion observability (reference timeline's per-response grouping,
        # as cheap counters): responses executed vs tensors they carried —
        # tensors/responses is the achieved fusion ratio.  Mirrored into
        # the process registry so /metrics scrapes see the eager path.
        self.responses_executed = 0
        self.tensors_executed = 0
        try:
            from horovod_tpu.obs.registry import default_registry

            r = default_registry()
            self._m_responses = r.counter(
                "eager_responses_executed_total",
                "Eager collective responses executed (post-fusion groups)",
                exist_ok=True)
            self._m_tensors = r.counter(
                "eager_tensors_executed_total",
                "Tensors carried by executed eager responses "
                "(tensors/responses = achieved fusion ratio)",
                exist_ok=True)
        except Exception:  # pragma: no cover - metrics never gate eager ops
            self._m_responses = self._m_tensors = None
        rt.set_executor(self._execute)

    # ---- naming (reference: "allreduce.noname.N" convention in the torch
    # binding when no name is given; deterministic because every rank issues
    # eager ops in the same program order) --------------------------------

    def auto_name(self, kind: str, name: Optional[str]) -> str:
        if name:
            return name
        return f"{kind}.noname.{next(self._counters[kind])}"

    # ---- submission ------------------------------------------------------

    def submit(self, name: str, op_type: int, x: np.ndarray, *,
               reduce_op: int = 0, root_rank: int = 0,
               prescale: float = 1.0, postscale: float = 1.0) -> int:
        with self._lock:
            if name in self._inputs:
                raise CollectiveError(
                    f"tensor name {name!r} already pending (duplicate "
                    "submission race — reference DUPLICATE_NAME_ERROR)")
            self._inputs[name] = x
        try:
            return self._rt.enqueue(
                name, op_type, tuple(x.shape), x.dtype,
                reduce_op=reduce_op, root_rank=root_rank,
                prescale=prescale, postscale=postscale)
        except Exception:
            with self._lock:
                self._inputs.pop(name, None)
            raise

    def submit_barrier(self) -> int:
        name = self.auto_name("barrier", None)
        return self._rt.enqueue(name, native.BARRIER, (), np.dtype("uint8"))

    def barrier(self) -> None:
        h = self.submit_barrier()
        try:
            self._rt.wait(h)
        except native.NativeError as e:
            raise CollectiveError(str(e)) from e

    def join(self) -> int:
        """Block until all ranks joined (native JOIN accounting; this rank's
        executor keeps contributing zeros meanwhile).  Returns the rank
        that joined LAST, as observed by the coordinator (reference DoJoin
        contract — the rank holding the most-advanced state)."""
        h = self._rt.enqueue_join()
        self._rt.wait(h)
        return self._rt.last_joined_rank()

    def poll(self, handle: int) -> bool:
        return self._rt.poll(handle)

    def wait(self, handle: int, name: str):
        try:
            self._rt.wait(handle)
        except native.NativeError as e:
            with self._lock:
                self._inputs.pop(name, None)
                self._results.pop(name, None)
            raise CollectiveError(str(e)) from e
        with self._lock:
            self._inputs.pop(name, None)
            if name not in self._results:
                raise CollectiveError(f"no result produced for {name!r}")
            return self._results.pop(name)

    # ---- execution callback (native background thread) -------------------

    def _execute(self, resp: "native.Response") -> int:
        from horovod_tpu.ops import collectives as C

        _, to_op = _op_maps()
        self.responses_executed += 1
        self.tensors_executed += len(resp.tensor_names)
        if self._m_responses is not None:
            self._m_responses.inc()
            self._m_tensors.inc(len(resp.tensor_names))
        try:
            with self._lock:
                inputs = []
                mine = []  # whether this rank actually submitted each tensor
                for tname, shape in zip(resp.tensor_names, resp.shapes):
                    if tname in self._inputs:
                        inputs.append(np.asarray(self._inputs[tname]))
                        mine.append(True)
                    else:
                        # Joined rank: contribute zeros.
                        inputs.append(np.zeros(
                            shape, dtype=native.dtype_name(resp.dtype)))
                        mine.append(False)

            if resp.type == native.ALLREDUCE:
                op = to_op[resp.op]
                pre = resp.prescale if resp.prescale != 1.0 else None
                post = resp.postscale if resp.postscale != 1.0 else None
                if op == C.Adasum and len(inputs) > 1:
                    # Fused Adasum keeps PER-TENSOR coefficients
                    # (reference adasum.h FusedAllreduce): concatenating
                    # would collapse the group to one global dot product.
                    from horovod_tpu.ops import adasum as _ad

                    ins = [a if pre is None else
                           a * np.asarray(pre, a.dtype) for a in inputs]
                    outs = _ad.eager_adasum_group(ins)
                    if post is not None:
                        outs = [o * np.asarray(post, o.dtype) for o in outs]
                else:
                    flat = (np.concatenate([a.ravel() for a in inputs])
                            if len(inputs) > 1 else inputs[0].ravel())
                    red = C._eager_allreduce(flat, op, pre, post)
                    off = 0
                    outs = []
                    for a in inputs:
                        outs.append(red[off:off + a.size].reshape(a.shape))
                        off += a.size
            elif resp.type == native.ALLGATHER:
                outs = [C._eager_allgather(inputs[0])]
            elif resp.type == native.BROADCAST:
                outs = [C._eager_broadcast(inputs[0], resp.root_rank)]
            elif resp.type == native.ALLTOALL:
                outs = [C._eager_alltoall(inputs[0], None)]
            elif resp.type == native.RESP_REDUCESCATTER:
                outs = [C._eager_reducescatter(inputs[0], to_op[resp.op])]
            else:
                return native.STATUS_INVALID

            with self._lock:
                for tname, out, is_mine in zip(resp.tensor_names, outs, mine):
                    if is_mine:
                        self._results[tname] = out
            return native.STATUS_OK
        except Exception:
            import traceback

            traceback.print_exc()
            return native.STATUS_INVALID

    # ---- introspection ---------------------------------------------------

    def cycles(self) -> int:
        return self._rt.cycles()

    def cache_hits(self) -> int:
        return self._rt.cache_hits()

    def cache_entries(self) -> int:
        return self._rt.cache_entries()

    def joined_count(self) -> int:
        """Coordinator-observed count of currently-joined ranks (0 on
        non-coordinator ranks)."""
        return self._rt.joined_count()

    def set_fusion_bytes(self, nbytes: int) -> None:
        """Adjust the native fusion planner's threshold (autotuner knob —
        reference ParameterManager -> TensorFusionThresholdBytes)."""
        self._rt.set_fusion_bytes(int(nbytes))

    def set_cycle_ms(self, ms: float) -> None:
        """Adjust the background negotiation cycle time (autotuner knob —
        reference HOROVOD_CYCLE_TIME / ParameterManager joint BO)."""
        self._rt.set_cycle_us(int(ms * 1000))

    def set_cache_capacity(self, n: int) -> None:
        """Resize (and clear) the response cache; applied by the
        background thread between cycles.  The bit-vector protocol pads
        length mismatches during propagation, so ranks may apply this at
        slightly different cycles without error."""
        self._rt.set_cache_capacity(int(n))

    def shutdown(self) -> None:
        self._rt.shutdown()


# ---- lifecycle ---------------------------------------------------------------

_runtime: Optional[EagerRuntime] = None
_start_lock = threading.Lock()


def enabled_by_env() -> bool:
    return os.environ.get("HOROVOD_NATIVE", "1") not in ("0", "false", "")


def start(timeline_path: Optional[str] = None) -> Optional[EagerRuntime]:
    """Start the native eager runtime for this process (idempotent).
    Returns None when the native library is unavailable or disabled, in
    which case eager ops use the direct (un-negotiated) path."""
    global _runtime
    with _start_lock:
        if _runtime is not None:
            return _runtime
        if native is None or not enabled_by_env() or not native.native_built():
            return None
        rank = basics.process_rank()
        size = basics.num_processes()
        addr = os.environ.get("HOROVOD_COORDINATOR_ADDR", "127.0.0.1")
        if ":" in addr:
            addr = addr.split(":")[0]
        # Distinct from the rendezvous KV port and the JAX coordination
        # port (KV+2): the native control plane listens on KV+3.  Elastic
        # restarts offset by the rendezvous epoch so a relaunch never
        # races the dead epoch's lingering listener (the ElasticDriver
        # also exports a fresh HOROVOD_NATIVE_PORT per epoch; this covers
        # manually relaunched elastic jobs).
        port = os.environ.get("HOROVOD_NATIVE_PORT")
        if port is None:
            base = os.environ.get("HOROVOD_COORDINATOR_PORT")
            port = str(int(base) + 3) if base else "9374"
            epoch = os.environ.get("HOROVOD_ELASTIC_EPOCH")
            if epoch:
                port = str(int(port) + 2 * int(epoch))
        port = int(port)
        rt = native.NativeRuntime()
        rt.init(rank, size, addr, port, timeline_path=timeline_path)
        _runtime = EagerRuntime(rt)
        return _runtime


def get() -> Optional[EagerRuntime]:
    return _runtime


def stop() -> None:
    global _runtime
    with _start_lock:
        if _runtime is not None:
            _runtime.shutdown()
            _runtime = None
