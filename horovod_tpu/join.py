"""Join: graceful early exit for ranks that run out of data.

Reference: the JOIN request type and coordinator accounting
(``EnqueueJoin`` ``common/operations.cc:919-943``; ready-when
``count == size - joined_size`` ``controller.cc:780-803``; zero-tensor
substitution ``global_state.h:104-107``).

TPU re-design (SURVEY.md §7 "hard parts" #1): XLA collectives are compiled
for a fixed mesh, so membership cannot change dynamically inside a step.
Join therefore becomes a **data-level** construct: every worker always
participates in the collective, but a worker that has exhausted its data
contributes zeros and is excluded from the averaging denominator — exactly
the reference's zero-tensor trick, moved into the graph.  Use
:func:`masked_average` inside the train step, driven by an ``active`` flag
from the data loader.  The eager :func:`join` is a barrier that returns the
last rank to arrive, for epoch-boundary synchronization.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu import basics
from horovod_tpu.ops import collectives as C


def masked_average(grads, active, *, axis_name=None):
    """Average ``grads`` over workers where ``active`` is truthy.

    ``active`` is a per-worker 0/1 scalar (traced).  Contributions from
    inactive workers are zeroed (the reference's ``AllocateZeros``
    substitution, ``common.h:219``) and the divisor is the live count,
    clamped to 1 so a fully-joined step is a no-op rather than a NaN."""
    axes = axis_name
    if axes is None:
        axes = basics.axis_name() if basics.is_initialized() else basics.AXIS
    if isinstance(axes, str):
        axes = (axes,)
    a = jnp.asarray(active, jnp.float32)
    live = lax.psum(a, axes)
    live = jnp.maximum(live, 1.0)

    def _avg(g):
        g = g * a.astype(g.dtype)
        return lax.psum(g, axes) / live.astype(g.dtype)

    return jax.tree_util.tree_map(_avg, grads)


def join() -> int:
    """Block until every process has called ``join``; returns the last
    joining worker rank (the reference returns the last joined rank so
    callers can broadcast final state from it).

    With the native runtime this is the reference's true JOIN protocol
    (``EnqueueJoin`` ``operations.cc:919-943``): while blocked here, other
    ranks' allreduces proceed with this rank contributing zeros; the
    coordinator tracks join ARRIVAL ORDER and releases everyone once all
    ranks joined, distributing the last-joined rank in the JOIN response.

    Without the native control plane there is no arrival-order observer:
    the fallback is a plain barrier-style allreduce whose Max-of-rank
    return is only meaningful single-process (where it is correctly 0)."""
    basics._ctx()
    from horovod_tpu import eager_runtime

    rt = eager_runtime.get()
    if rt is not None:
        return int(rt.join())
    my = np.asarray(float(basics.rank()), np.float32)
    return int(C._eager_allreduce(my, C.Max, None, None))
