"""State-consistency primitives: broadcast of parameters, optimizer state,
and arbitrary Python objects.

Reference: ``horovod/torch/__init__.py`` ``broadcast_parameters`` /
``broadcast_optimizer_state`` / ``broadcast_object`` (~410-640),
``tensorflow/__init__.py:139-175`` ``broadcast_variables``.  These are the
checkpoint/resume consistency layer (SURVEY.md §5.4): rank 0 restores, then
broadcasts, so every worker starts identical.
"""

from __future__ import annotations

import io
import pickle
from typing import Any

import jax
import numpy as np

from horovod_tpu import basics
from horovod_tpu.ops import collectives as C


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a parameter pytree from ``root_rank``
    (``torch/__init__.py`` ``broadcast_parameters``).  Works eagerly (host
    arrays) and in-graph (under shard_map)."""
    return C.broadcast(params, root_rank)


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Broadcast optimizer state.  Array leaves are broadcast as tensors;
    non-array leaves (step counters, hyperparams, schedules state) are
    pickled and broadcast as bytes — the same split the reference makes
    (``torch/__init__.py`` ``broadcast_optimizer_state``: tensor state via
    broadcast, scalar state via cloudpickled ``broadcast_object``)."""
    leaves, treedef = jax.tree_util.tree_flatten(opt_state)
    out = []
    for leaf in leaves:
        if isinstance(leaf, (jax.Array, np.ndarray)) or np.isscalar(leaf):
            arr = np.asarray(leaf)
            if arr.dtype == object:
                out.append(broadcast_object(leaf, root_rank))
            else:
                b = C.broadcast(arr, root_rank)
                out.append(np.asarray(b, dtype=arr.dtype).reshape(arr.shape))
        else:
            out.append(broadcast_object(leaf, root_rank))
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_object(obj: Any, root_rank: int = 0, name: str = None) -> Any:
    """Pickle-broadcast an arbitrary object from ``root_rank``
    (``torch/__init__.py`` ``broadcast_object``; reference uses cloudpickle
    over a byte tensor).  Two phases: broadcast the length, then the
    payload."""
    basics._ctx()
    if basics.cross_size() == 1:
        return obj
    me_is_root = basics.rank() <= root_rank < basics.rank() + basics.local_size()
    if me_is_root:
        buf = io.BytesIO()
        pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
        payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)
    else:
        payload = np.zeros((0,), np.uint8)
    length = C.broadcast(np.asarray(payload.size, np.int64), root_rank,
                         name=f"{name}.len" if name else None)
    n = int(length)
    send = np.zeros((n,), np.uint8)
    if me_is_root:
        send[:] = payload
    data = np.asarray(C.broadcast(send, root_rank,
                                  name=f"{name}.data" if name else None),
                      np.uint8)
    return pickle.loads(data.tobytes())


def allgather_object(obj: Any, name: str = None) -> list:
    """Gather one object per process into a list on every process
    (Horovod's ``allgather_object``)."""
    basics._ctx()
    if basics.cross_size() == 1:
        return [obj]
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)
    lengths = np.asarray(
        C.allgather(np.asarray([payload.size], np.int64),
                    name=f"{name}.len" if name else None), np.int64
    )
    data = np.asarray(C.allgather(payload,
                                  name=f"{name}.data" if name else None),
                      np.uint8)
    out = []
    off = 0
    for n in lengths:
        out.append(pickle.loads(data[off : off + int(n)].tobytes()))
        off += int(n)
    return out
