"""horovod_tpu: a TPU-native distributed training framework with the
capability set of Horovod v0.19 (reference: nzmora/horovod), re-designed for
JAX/XLA/pjit/Pallas over ICI/DCN device meshes.

Typical use (the Horovod "minimal code change" contract, README.rst:37):

    import horovod_tpu as hvd
    hvd.init()
    opt = hvd.DistributedOptimizer(optax.adam(1e-3 * hvd.size()))
    step = hvd.spmd.make_train_step(loss_fn, opt)   # compiled SPMD step
    params = hvd.broadcast_parameters(params, root_rank=0)
"""

from horovod_tpu import _compat  # noqa: F401  (installs JAX version shims)
from horovod_tpu.basics import (
    AXIS,
    CROSS_AXIS,
    LOCAL_AXIS,
    NotInitializedError,
    axis_name,
    ccl_built,
    cross_rank,
    cross_size,
    ddl_built,
    gloo_built,
    gloo_enabled,
    hierarchical_mesh,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mesh,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    num_processes,
    process_rank,
    rank,
    sharding_for,
    shutdown,
    size,
    worker_index,
    xla_built,
)
from horovod_tpu.ops.collectives import (
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    allreduce_async_,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_async,
    broadcast_async_,
    grouped_allreduce,
    poll,
    process_sum,
    reducescatter,
    reducescatter_async,
    synchronize,
)
from horovod_tpu.ops.compression import Compression
from horovod_tpu.optim import (
    DistributedAdasumOptimizer,
    DistributedGradientTape,
    DistributedOptimizer,
    distributed_gradients,
)
from horovod_tpu.state import (
    allgather_object,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
)
from horovod_tpu.join import join, masked_average
from horovod_tpu import callbacks, data, elastic, obs, spmd, parallel, timeline
from horovod_tpu.data import DataLoader
from horovod_tpu.timeline import start_timeline, stop_timeline

__version__ = "0.1.0"

__all__ = [k for k in dir() if not k.startswith("_")]
