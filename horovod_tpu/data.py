"""Input pipeline: process-sharded, shuffled, DEVICE-PREFETCHED batches.

The reference delegates data loading to the frameworks (tf.data / torch
DataLoader / petastorm readers — e.g. ``spark/keras/remote.py``'s
``make_batch_reader``); what it standardizes is the *distributed
contract*: shard by rank, equal step counts per rank, reshuffle per
epoch.  This module provides that contract TPU-first:

* **sharding by process** with the lockstep guarantee — every rank runs
  exactly the same number of batches per epoch (the min over shards), so
  no rank ever submits a collective its peers won't match;
* **device prefetch** — ``jax.device_put`` is async, so enqueueing the
  next batch's transfer while the current step computes hides the
  host→HBM copy (the usual TPU input-pipeline win); a small deque keeps
  ``prefetch`` transfers in flight;
* optional **sharding placement** so multi-chip runs commit each batch
  directly to its mesh sharding instead of chip 0.
"""

from __future__ import annotations

import collections
from typing import Dict, Iterator, Optional

import jax
import numpy as np

from horovod_tpu import basics


class DataLoader:
    """Iterate dict-of-arrays as device-resident minibatches.

    Args:
      arrays: name -> ``(N, ...)`` host arrays, identical N.
      batch_size: per-process batch size.
      shuffle: reshuffle indices every epoch (seeded, same on every
        epoch replay of the same loader).
      seed: base seed; the per-process shard offset is folded in so
        ranks draw different data but reruns are reproducible.
      shard: shard rows by process rank (default True; pass False when
        the caller already sharded).
      drop_remainder: always True semantics — only full batches are
        yielded, and the count is the min over all ranks' shards.
      prefetch: how many batches to keep in flight on device.
      sharding: optional ``jax.sharding.Sharding`` the batches are
        committed to (e.g. ``NamedSharding(mesh, P(hvd.AXIS))``).
    """

    def __init__(self, arrays: Dict[str, np.ndarray], batch_size: int, *,
                 shuffle: bool = True, seed: int = 0, shard: bool = True,
                 prefetch: int = 2,
                 sharding: Optional[jax.sharding.Sharding] = None) -> None:
        lens = {k: len(v) for k, v in arrays.items()}
        if len(set(lens.values())) != 1:
            raise ValueError(f"arrays disagree on length: {lens}")
        self.n_total = next(iter(lens.values()))
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.prefetch = max(int(prefetch), 0)
        self.sharding = sharding
        self._epoch = 0

        if shard and basics.is_initialized() and basics.num_processes() > 1:
            r, p = basics.process_rank(), basics.num_processes()
            self.arrays = {k: v[r::p] for k, v in arrays.items()}
            # lockstep: every rank yields the same number of batches —
            # the smallest shard (size n//p) decides.
            self._len = (self.n_total // p) // self.batch_size
        else:
            self.arrays = dict(arrays)
            self._len = self.n_total // self.batch_size
        if self._len == 0:
            raise ValueError(
                f"batch_size={batch_size} exceeds the local shard "
                f"({min(len(v) for v in self.arrays.values())} rows)")

    def __len__(self) -> int:
        return self._len

    def _epoch_indices(self) -> np.ndarray:
        n = len(next(iter(self.arrays.values())))
        if not self.shuffle:
            return np.arange(n)
        rank = basics.process_rank() if basics.is_initialized() else 0
        rng = np.random.RandomState(
            ((self.seed * 1000003 + self._epoch) ^ rank) % (2 ** 32))
        return rng.permutation(n)

    def __iter__(self) -> Iterator[Dict[str, jax.Array]]:
        idx = self._epoch_indices()
        self._epoch += 1

        def put(b):
            start = b * self.batch_size
            if not self.shuffle:
                # Indices are arange by construction: slice VIEW instead
                # of a fancy-index copy — device_put stages straight from
                # the original buffer (measurably faster for large
                # batches).
                batch = {k: v[start:start + self.batch_size]
                         for k, v in self.arrays.items()}
            else:
                rows = idx[start:start + self.batch_size]
                batch = {k: v[rows] for k, v in self.arrays.items()}
            if self.sharding is not None:
                return {k: jax.device_put(v, self.sharding)
                        for k, v in batch.items()}
            return {k: jax.device_put(v) for k, v in batch.items()}

        buf: "collections.deque" = collections.deque()
        for b in range(min(self.prefetch, self._len)):
            buf.append(put(b))  # async: transfers start immediately
        for b in range(self._len):
            nxt = b + self.prefetch
            if nxt < self._len:
                buf.append(put(nxt))
            yield buf.popleft()
