"""Input pipeline: process-sharded, shuffled, DEVICE-PREFETCHED batches.

The reference delegates data loading to the frameworks (tf.data / torch
DataLoader / petastorm readers — e.g. ``spark/keras/remote.py``'s
``make_batch_reader``); what it standardizes is the *distributed
contract*: shard by rank, equal step counts per rank, reshuffle per
epoch.  This module provides that contract TPU-first:

* **sharding by process** with the lockstep guarantee — every rank runs
  exactly the same number of batches per epoch (the min over shards), so
  no rank ever submits a collective its peers won't match;
* **device prefetch** — ``jax.device_put`` is async, so enqueueing the
  next batch's transfer while the current step computes hides the
  host→HBM copy (the usual TPU input-pipeline win); a small deque keeps
  ``prefetch`` transfers in flight;
* optional **sharding placement** so multi-chip runs commit each batch
  directly to its mesh sharding instead of chip 0;
* **global-array feeding** — when the target sharding spans multiple
  processes (a pod run: one process per host over a global mesh), each
  process loads only ITS batch rows and the loader assembles them into
  one global ``jax.Array`` via ``jax.make_array_from_process_local_data``
  — the multi-host input contract of a compiled GSPMD step (the pod
  analogue of the reference's per-rank ``shard`` + framework loader,
  e.g. ``spark/keras/remote.py`` make_batch_reader sharding).
"""

from __future__ import annotations

import collections
from typing import Dict, Iterator, Optional

import jax
import numpy as np

from horovod_tpu import basics


class DataLoader:
    """Iterate dict-of-arrays as device-resident minibatches.

    Args:
      arrays: name -> ``(N, ...)`` host arrays, identical N.
      batch_size: per-process batch size.
      shuffle: reshuffle indices every epoch (seeded, same on every
        epoch replay of the same loader).
      seed: base seed; the per-process shard offset is folded in so
        ranks draw different data but reruns are reproducible.
      shard: shard rows by process rank (default True; pass False when
        the caller already sharded).
      drop_remainder: always True semantics — only full batches are
        yielded, and the count is the min over all ranks' shards.
      prefetch: how many batches to keep in flight on device.
      sharding: optional ``jax.sharding.Sharding`` the batches are
        committed to (e.g. ``NamedSharding(mesh, P(hvd.AXIS))``).  When
        the sharding spans multiple PROCESSES, ``batch_size`` is the
        GLOBAL batch size: every process draws the same shuffled index
        stream (same seed — no per-rank fold), materializes only the
        rows its devices own, and yields global arrays assembled with
        ``jax.make_array_from_process_local_data``.  Only the leading
        (batch) dimension may be partitioned across processes; inner
        dims may still be sharded across the devices WITHIN a process
        (e.g. sp over local chips).
    """

    def __init__(self, arrays: Dict[str, np.ndarray], batch_size: int, *,
                 shuffle: bool = True, seed: int = 0, shard: bool = True,
                 prefetch: int = 2,
                 sharding: Optional[jax.sharding.Sharding] = None) -> None:
        lens = {k: len(v) for k, v in arrays.items()}
        if len(set(lens.values())) != 1:
            raise ValueError(f"arrays disagree on length: {lens}")
        self.n_total = next(iter(lens.values()))
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.prefetch = max(int(prefetch), 0)
        self.sharding = sharding
        self._epoch = 0

        self._global = (
            sharding is not None
            and len(sharding.device_set)
            > len(list(sharding.addressable_devices))
        )
        if self._global:
            # Pod mode: the permutation is process-independent (all ranks
            # see the same global index stream) and sharding is decided by
            # the SHARDING's row ownership, not rank round-robin.
            self.arrays = dict(arrays)
            self._len = self.n_total // self.batch_size
            self._local_rows = self._addressable_rows()
        elif shard and basics.is_initialized() and basics.num_processes() > 1:
            r, p = basics.process_rank(), basics.num_processes()
            self.arrays = {k: v[r::p] for k, v in arrays.items()}
            # lockstep: every rank yields the same number of batches —
            # the smallest shard (size n//p) decides.
            self._len = (self.n_total // p) // self.batch_size
        else:
            self.arrays = dict(arrays)
            self._len = self.n_total // self.batch_size
        if self._len == 0:
            raise ValueError(
                f"batch_size={batch_size} exceeds the local shard "
                f"({min(len(v) for v in self.arrays.values())} rows)")

    def __len__(self) -> int:
        return self._len

    def _addressable_rows(self) -> np.ndarray:
        """Positions WITHIN a global batch (dim 0) owned by this process's
        devices under ``self.sharding``.  Validates the pod-mode contract:
        only the leading batch dim may be partitioned across processes
        (inner dims may still shard over the devices inside a process)."""
        rows = None
        for k, v in self.arrays.items():
            shape = (self.batch_size,) + v.shape[1:]
            imap = self.sharding.addressable_devices_indices_map(shape)
            dim_sets = [set() for _ in shape]
            for idx in imap.values():
                for d, sl in enumerate(idx):
                    start, stop, _ = sl.indices(shape[d])
                    dim_sets[d].update(range(start, stop))
            for d in range(1, len(shape)):
                if len(dim_sets[d]) != shape[d]:
                    raise ValueError(
                        "global DataLoader: only the leading batch dim may "
                        f"be sharded across processes (dim {d} of {k!r} is "
                        "process-partitioned)")
            r = np.array(sorted(dim_sets[0]), dtype=np.int64)
            if rows is None:
                rows = r
            elif not np.array_equal(rows, r):
                raise ValueError(
                    "arrays disagree on per-process row ownership")
        return rows

    def _epoch_indices(self) -> np.ndarray:
        n = len(next(iter(self.arrays.values())))
        if not self.shuffle:
            return np.arange(n)
        # Pod mode: every process must draw the SAME permutation — each
        # materializes a different slice of the same global batch.
        rank = (basics.process_rank()
                if basics.is_initialized() and not self._global else 0)
        rng = np.random.RandomState(
            ((self.seed * 1000003 + self._epoch) ^ rank) % (2 ** 32))
        return rng.permutation(n)

    def __iter__(self) -> Iterator[Dict[str, jax.Array]]:
        idx = self._epoch_indices()
        self._epoch += 1

        def put_global(b):
            start = b * self.batch_size
            rows_g = idx[start:start + self.batch_size]
            sel = rows_g[self._local_rows]
            out = {}
            for k, v in self.arrays.items():
                out[k] = jax.make_array_from_process_local_data(
                    self.sharding, np.ascontiguousarray(v[sel]),
                    (self.batch_size,) + v.shape[1:])
            return out

        def put(b):
            if self._global:
                return put_global(b)
            start = b * self.batch_size
            if not self.shuffle:
                # Indices are arange by construction: slice VIEW instead
                # of a fancy-index copy — device_put stages straight from
                # the original buffer (measurably faster for large
                # batches).
                batch = {k: v[start:start + self.batch_size]
                         for k, v in self.arrays.items()}
            else:
                rows = idx[start:start + self.batch_size]
                batch = {k: v[rows] for k, v in self.arrays.items()}
            if self.sharding is not None:
                return {k: jax.device_put(v, self.sharding)
                        for k, v in batch.items()}
            return {k: jax.device_put(v) for k, v in batch.items()}

        buf: "collections.deque" = collections.deque()
        for b in range(min(self.prefetch, self._len)):
            buf.append(put(b))  # async: transfers start immediately
        for b in range(self._len):
            nxt = b + self.prefetch
            if nxt < self._len:
                buf.append(put(nxt))
            yield buf.popleft()
