"""MXNet gluon distributed MNIST (reference ``examples/mxnet_mnist.py``):
DistributedTrainer + broadcast_parameters over the shared eager data
plane. Requires mxnet (not in this image — the frontend is verified
against a mocked module in ``tests/test_mxnet_frontend.py``).

    horovodrun -np 2 python examples/mxnet_mnist.py
"""

import numpy as np

import mxnet as mx
from mxnet import autograd, gluon

import horovod_tpu.mxnet as hvd


def synthetic_mnist(n=4096, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 1, 28, 28).astype(np.float32)
    w = rng.randn(28 * 28, 10).astype(np.float32)
    y = (x.reshape(n, -1) @ w).argmax(axis=1).astype(np.float32)
    return x, y


def main():
    hvd.init()

    x, y = synthetic_mnist()
    x = x[hvd.cross_rank()::hvd.cross_size()]
    y = y[hvd.cross_rank()::hvd.cross_size()]

    net = gluon.nn.Sequential()
    net.add(gluon.nn.Conv2D(16, 3, activation="relu"),
            gluon.nn.MaxPool2D(),
            gluon.nn.Flatten(),
            gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())

    # params broadcast from rank 0 (deferred-init safe)
    hvd.broadcast_parameters(net.collect_params(), root_rank=0)

    opt_params = {"learning_rate": 0.01 * hvd.cross_size(), "momentum": 0.9}
    trainer = hvd.DistributedTrainer(net.collect_params(), "sgd", opt_params)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    batch = 64
    for epoch in range(3):
        losses = []
        for i in range(0, len(x) - batch, batch):
            data = mx.nd.array(x[i:i + batch])
            label = mx.nd.array(y[i:i + batch])
            with autograd.record():
                loss = loss_fn(net(data), label)
            loss.backward()
            trainer.step(batch)
            losses.append(float(loss.mean().asscalar()))
        avg = float(hvd.allreduce(mx.nd.array([np.mean(losses)]),
                                  average=True).asscalar())
        if hvd.cross_rank() == 0:
            print(f"epoch {epoch}: loss {avg:.4f}")


if __name__ == "__main__":
    main()
