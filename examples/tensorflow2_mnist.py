"""TF2 eager distributed MNIST (reference ``examples/tensorflow2_mnist.py``):
init -> shard data by rank -> DistributedGradientTape -> broadcast initial
variables -> rank-0 checkpointing.

    horovodrun -np 2 python examples/tensorflow2_mnist.py

Uses a synthetic MNIST-shaped dataset so the example runs hermetically.
"""

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def synthetic_mnist(n=4096, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    w = rng.randn(28 * 28, 10).astype(np.float32)
    y = (x.reshape(n, -1) @ w).argmax(axis=1).astype(np.int64)
    return x, y


def main():
    hvd.init()

    x, y = synthetic_mnist()
    # shard by process rank (reference: dataset.shard(hvd.size(), hvd.rank()))
    n = hvd.num_processes()
    x, y = x[hvd.process_rank()::n], y[hvd.process_rank()::n]
    dataset = (tf.data.Dataset.from_tensor_slices((x, y))
               .shuffle(len(x), seed=1).batch(64).repeat())

    model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(16, 3, activation="relu",
                               input_shape=(28, 28, 1)),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(10),
    ])
    loss_obj = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)
    # scale LR by the number of workers (reference recipe)
    opt = tf.keras.optimizers.SGD(0.01 * hvd.num_processes())

    checkpoint = tf.train.Checkpoint(model=model, optimizer=opt)

    for step, (images, labels) in enumerate(dataset.take(200)):
        with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = loss_obj(labels, model(images, training=True))
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))

        if step == 0:
            # after the first step so optimizer slots exist (reference
            # BroadcastGlobalVariablesHook timing)
            hvd.broadcast_variables(model.variables, root_rank=0)
            hvd.broadcast_variables(opt.variables, root_rank=0)

        if step % 50 == 0 and hvd.process_rank() == 0:
            print(f"step {step}: loss {float(loss):.4f}")

    if hvd.process_rank() == 0:
        checkpoint.save("/tmp/tf2_mnist_ckpt")


if __name__ == "__main__":
    main()
