"""Join: graceful early exit when ranks have unequal data (reference:
``hvd.join()``, test_torch.py:1540's pattern as an example).

Rank r gets 10*(r+1) batches; ranks that finish early call join() and
keep contributing zeros to the stragglers' allreduces until everyone is
done — no hang, no wasted barrier.

    horovodrun -np 2 python examples/join_elastic.py
"""

import numpy as np
import torch

import horovod_tpu.torch as hvd


def main():
    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    model = torch.nn.Linear(4, 1)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05),
        named_parameters=model.named_parameters())

    torch.manual_seed(rank)
    num_batches = 10 * (rank + 1)  # deliberately unequal
    for step in range(num_batches):
        x = torch.randn(16, 4)
        y = x.sum(dim=1, keepdim=True)
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
    print(f"rank {rank} finished {num_batches} batches; joining")
    last = hvd.join()
    if rank == 0:
        print(f"all ranks joined (last worker rank: {last}); "
              f"final loss {float(loss.detach()):.4f}")


if __name__ == "__main__":
    main()
