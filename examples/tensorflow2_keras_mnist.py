"""Keras distributed MNIST with the full callback set (reference
``examples/tensorflow2_keras_mnist.py`` + ``keras_mnist_advanced.py``):
DistributedOptimizer, initial-state broadcast, metric averaging, LR
warmup, rank-0 checkpointing, hvd.load_model round-trip.

    horovodrun -np 2 python examples/tensorflow2_keras_mnist.py
"""

import numpy as np
import tensorflow as tf

import horovod_tpu.keras as hvd


def synthetic_mnist(n=4096, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    w = rng.randn(28 * 28, 10).astype(np.float32)
    y = (x.reshape(n, -1) @ w).argmax(axis=1).astype(np.int64)
    return x, y


def main():
    hvd.init()

    x, y = synthetic_mnist()
    n = hvd.size()  # chips; == processes with one chip per process
    from horovod_tpu import basics
    x = x[basics.process_rank()::basics.num_processes()]
    y = y[basics.process_rank()::basics.num_processes()]

    model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(16, 3, activation="relu",
                               input_shape=(28, 28, 1)),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(10, activation="softmax"),
    ])
    # base LR scaled by worker count; warmup ramps into it
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(0.01 * n, momentum=0.9))
    model.compile(optimizer=opt,
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"],
                  run_eagerly=True)  # host-path collectives: see docs/frontends.md

    steps = len(x) // 64
    callbacks = [
        hvd.BroadcastGlobalVariablesCallback(0),
        hvd.MetricAverageCallback(),
        hvd.LearningRateWarmupCallback(warmup_epochs=2,
                                       steps_per_epoch=steps, verbose=1),
    ]
    if basics.process_rank() == 0:
        callbacks.append(tf.keras.callbacks.ModelCheckpoint(
            "/tmp/keras_mnist.keras"))

    model.fit(x, y, batch_size=64, steps_per_epoch=steps, epochs=4,
              callbacks=callbacks,
              verbose=1 if basics.process_rank() == 0 else 0)

    if basics.process_rank() == 0:
        # round-trip: load_model rewraps the optimizer (docs/inference.md)
        restored = hvd.load_model("/tmp/keras_mnist.keras")
        print("restored:", restored.optimizer.__class__.__name__)


if __name__ == "__main__":
    main()
