"""Text generation with the KV-cache decode path (docs/inference.md).

Trains a tiny LM on a synthetic ramp sequence for a few steps, then
generates greedily and by sampling — exercising prefill + decode_step +
greedy_decode/sample_decode end to end on whatever backend is active.

Run:  python examples/generate.py [--steps 30] [--gen 16]
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30, help="train steps")
    ap.add_argument("--gen", type=int, default=16, help="tokens to generate")
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--top-k", type=int, default=8)
    args = ap.parse_args()

    import horovod_tpu as hvd
    from horovod_tpu.models import transformer as T

    hvd.init()
    cfg = T.TransformerConfig(
        vocab_size=32, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq=64, dtype=jnp.float32, n_kv_heads=2)  # GQA halves the cache
    params = T.init_params(jax.random.PRNGKey(0), cfg)

    # Teach it to count mod 32: tokens[i+1] = tokens[i] + 1.
    base = np.arange(64 * 8).reshape(8, 64) % 32
    batch = {"tokens": jnp.asarray(base, jnp.int32),
             "targets": jnp.asarray((base + 1) % 32, jnp.int32)}

    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(T.loss_fn)(params, batch, cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    loss = T.loss_fn(params, batch, cfg)
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state)
    print(f"trained {args.steps} steps, loss {float(loss):.3f}")

    prompt = jnp.asarray([[3, 4, 5, 6]], jnp.int32)
    greedy = T.greedy_decode(params, prompt, args.gen, cfg)
    print("greedy :", np.asarray(greedy)[0].tolist())
    sampled = T.sample_decode(params, prompt, args.gen, cfg,
                              rng=jax.random.PRNGKey(1),
                              temperature=args.temperature,
                              top_k=args.top_k)
    print("sampled:", np.asarray(sampled)[0].tolist())
    hvd.shutdown()


if __name__ == "__main__":
    main()
