"""Text generation with the KV-cache decode path (docs/inference.md).

Trains a tiny LM on a synthetic ramp sequence for a few steps, then
generates greedily and by sampling — exercising prefill + decode_step +
greedy_decode/sample_decode end to end on whatever backend is active.

Run:  python examples/generate.py [--steps 30] [--gen 16]
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30, help="train steps")
    ap.add_argument("--gen", type=int, default=16, help="tokens to generate")
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--top-k", type=int, default=8)
    ap.add_argument("--mesh", default="", metavar="tpN",
                    help="serve tp-sharded over an N-device tensor-parallel"
                         " mesh (e.g. tp2) and verify token-identity with"
                         " single-chip decode")
    args = ap.parse_args()

    import horovod_tpu as hvd
    from horovod_tpu.models import transformer as T

    hvd.init()
    cfg = T.TransformerConfig(
        vocab_size=32, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq=64, dtype=jnp.float32, n_kv_heads=2)  # GQA halves the cache
    params = T.init_params(jax.random.PRNGKey(0), cfg)

    # Teach it to count mod 32: tokens[i+1] = tokens[i] + 1.
    base = np.arange(64 * 8).reshape(8, 64) % 32
    batch = {"tokens": jnp.asarray(base, jnp.int32),
             "targets": jnp.asarray((base + 1) % 32, jnp.int32)}

    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(T.loss_fn)(params, batch, cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    loss = T.loss_fn(params, batch, cfg)
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state)
    print(f"trained {args.steps} steps, loss {float(loss):.3f}")

    prompt = jnp.asarray([[3, 4, 5, 6]], jnp.int32)
    greedy = T.greedy_decode(params, prompt, args.gen, cfg)
    print("greedy :", np.asarray(greedy)[0].tolist())
    sampled = T.sample_decode(params, prompt, args.gen, cfg,
                              rng=jax.random.PRNGKey(1),
                              temperature=args.temperature,
                              top_k=args.top_k)
    print("sampled:", np.asarray(sampled)[0].tolist())

    if args.mesh:
        # tp-sharded serving: params sharded per serving_param_specs
        # (heads/ffn/vocab over tp, training-only axes replicated), KV
        # cache head-sharded per cache_specs; must be token-identical to
        # the single-chip decode above.
        from jax.sharding import Mesh
        try:
            tp = int(args.mesh.removeprefix("tp"))
        except ValueError:
            tp = 0
        if not args.mesh.startswith("tp") or tp < 1:
            raise SystemExit(f"--mesh must look like tp2, got {args.mesh!r}")
        if len(jax.devices()) < tp:
            raise SystemExit(
                f"--mesh {args.mesh} needs {tp} devices, have "
                f"{len(jax.devices())} (hint: JAX_PLATFORMS=cpu "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={tp})")
        mesh = Mesh(np.array(jax.devices()[:tp]), axis_names=("tp",))
        param_sh, cache_sh = T.serving_shardings(mesh, cfg)
        params_tp = jax.device_put(params, param_sh)
        greedy_tp = jax.jit(
            lambda p, t: T.greedy_decode(p, t, args.gen, cfg,
                                         cache_shardings=cache_sh)
        )(params_tp, prompt)
        same = bool((np.asarray(greedy_tp) == np.asarray(greedy)).all())
        print(f"tp{tp}   :", np.asarray(greedy_tp)[0].tolist())
        print(f"tp{tp} decode token-identical to single-chip: {same}")
        if not same:
            raise SystemExit(1)
    hvd.shutdown()


if __name__ == "__main__":
    main()
