"""Distributed PyTorch training (reference: ``examples/pytorch_mnist.py``):
init -> shard data by rank -> DistributedOptimizer -> broadcast parameters
and optimizer state -> metric averaging -> rank-0 checkpoint.

    horovodrun -np 2 python examples/pytorch_mnist.py
"""

import argparse
import os

import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(1, 10, 5)
        self.conv2 = torch.nn.Conv2d(10, 20, 5)
        self.fc1 = torch.nn.Linear(320, 50)
        self.fc2 = torch.nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2(x), 2))
        x = x.flatten(1)
        return F.log_softmax(self.fc2(F.relu(self.fc1(x))), dim=1)


def synthetic_mnist(n=4096, seed=0):
    g = torch.Generator().manual_seed(seed)
    x = torch.rand(n, 1, 28, 28, generator=g)
    w = torch.randn(28 * 28, 10, generator=g)
    y = (x.flatten(1) @ w).argmax(dim=1)
    return torch.utils.data.TensorDataset(x, y)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.01)
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(42)

    dataset = synthetic_mnist()
    # Shard by rank (the reference uses DistributedSampler; same effect).
    sampler = torch.utils.data.distributed.DistributedSampler(
        dataset, num_replicas=hvd.size(), rank=hvd.rank())
    loader = torch.utils.data.DataLoader(
        dataset, batch_size=args.batch_size, sampler=sampler)

    model = Net()
    optimizer = torch.optim.SGD(model.parameters(),
                                lr=args.lr * hvd.size(), momentum=0.5)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())

    for epoch in range(args.epochs):
        sampler.set_epoch(epoch)
        model.train()
        for batch_idx, (data, target) in enumerate(loader):
            optimizer.zero_grad()
            loss = F.nll_loss(model(data), target)
            loss.backward()
            optimizer.step()
        # epoch metric averaged over workers (MetricAverageCallback role)
        avg = hvd.allreduce(loss.detach(), op=hvd.Average, name=f"loss.{epoch}")
        if hvd.rank() == 0:
            print(f"epoch {epoch}: avg loss {float(avg):.4f}")

    if hvd.rank() == 0:
        path = os.environ.get("CKPT", "/tmp/pytorch_mnist.pt")
        torch.save(model.state_dict(), path)
        print(f"checkpoint -> {path}")


if __name__ == "__main__":
    main()
