"""Full-featured distributed ResNet-50 training (reference
``examples/keras_imagenet_resnet50.py`` / ``pytorch_imagenet_resnet50.py``):
every production knob in one script — LR warmup + stepwise decay, bf16
wire compression, gradient fusion, checkpointing with restore-then-
broadcast resume, timeline tracing, metric averaging.

    horovodrun -np 8 python examples/jax_imagenet_resnet50.py --epochs 90

Runs hermetically on synthetic data; point ``--data-dir`` at an
imagefolder-style tree to train for real (loader stub below).
"""

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import callbacks, checkpoint, spmd, timeline
from horovod_tpu.models import resnet


def parse_args():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None,
                    help="imagefolder root; synthetic data when omitted")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--steps-per-epoch", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=128,
                    help="per-chip batch")
    ap.add_argument("--base-lr", type=float, default=0.0125,
                    help="per-chip LR (reference default), scaled by size")
    ap.add_argument("--warmup-epochs", type=int, default=5)
    ap.add_argument("--checkpoint-dir", default="/tmp/resnet50_ckpt")
    ap.add_argument("--timeline", default=None)
    ap.add_argument("--fp16-allreduce", action="store_true",
                    help="bf16 wire compression for gradients")
    return ap.parse_args()


def synthetic_dataset(n, image_size=224, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "images": rng.rand(n, image_size, image_size, 3).astype(np.float32),
        "labels": rng.randint(0, 1000, (n,)).astype(np.int32),
    }


def main():
    args = parse_args()
    hvd.init()
    n = hvd.size()
    rank0 = hvd.rank() == 0
    if args.timeline:
        timeline.start_timeline(args.timeline)

    model = resnet.create("ResNet50", num_classes=1000)
    variables = resnet.init_variables(model, jax.random.PRNGKey(0), 224)
    params, batch_stats = variables["params"], variables["batch_stats"]

    # Goyal et al. linear-scaling recipe: LR = base * size, warmed up.
    steps_per_epoch = args.steps_per_epoch
    schedule = callbacks.warmup_schedule(
        args.base_lr, warmup_steps=args.warmup_epochs * steps_per_epoch,
        size=n)
    decay = optax.piecewise_constant_schedule(
        1.0, {30 * steps_per_epoch: 0.1, 60 * steps_per_epoch: 0.1,
              80 * steps_per_epoch: 0.1})
    opt = hvd.DistributedOptimizer(
        optax.chain(
            optax.trace(decay=0.9, nesterov=False),
            optax.scale_by_schedule(lambda s: -schedule(s) * decay(s)),
        ),
        compression=hvd.Compression.bf16 if args.fp16_allreduce
        else hvd.Compression.none,
    )
    opt_state = opt.init(params)

    # resume: restore rank 0's checkpoint then broadcast (docs/elastic.md)
    start_epoch = 0
    latest = os.path.join(args.checkpoint_dir, "latest")
    if os.path.isdir(latest):
        restored = checkpoint.restore(
            latest, template={"params": params, "epoch": 0})
        params, start_epoch = restored["params"], int(restored["epoch"]) + 1
    params = hvd.broadcast_parameters(params, root_rank=0)

    def loss_fn(p, stats, images, labels):
        logits, new_state = model.apply(
            {"params": p, "batch_stats": stats}, images,
            train=True, mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy(
            logits, jax.nn.one_hot(labels, 1000)).mean()
        return loss, new_state["batch_stats"]

    mesh, axis = hvd.mesh(), hvd.AXIS

    def _step(params, opt_state, stats, images, labels):
        (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, stats, images, labels)
        updates, opt_state = opt.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state, stats,
                jax.lax.pmean(loss, axis))

    step = jax.jit(spmd.shard(
        _step, in_specs=(P(), P(), P(), P(axis), P(axis)),
        out_specs=(P(), P(), P(), P()), mesh=mesh), donate_argnums=(0, 1, 2))

    # Device-prefetched input pipeline: next batch's host->HBM transfer
    # overlaps the current step (horovod_tpu.data.DataLoader; swap
    # synthetic_dataset for a real reader keeping the same dict shape).
    from horovod_tpu.data import DataLoader

    data = synthetic_dataset(args.batch_size * n * steps_per_epoch)
    data["images"] = data["images"].astype(jnp.bfloat16)
    loader = DataLoader(data, args.batch_size * n, shard=False,
                        sharding=NamedSharding(mesh, P(axis)))
    for epoch in range(start_epoch, args.epochs):
        with timeline.trace(f"epoch.{epoch}"):
            losses = []
            for batch in loader:
                params, opt_state, batch_stats, loss = step(
                    params, opt_state, batch_stats,
                    batch["images"], batch["labels"])
                losses.append(loss)
            epoch_loss = float(np.mean([float(np.asarray(l))
                                        for l in losses]))
        if rank0:
            print(f"epoch {epoch}: loss {epoch_loss:.4f} "
                  f"lr {float(schedule(epoch * steps_per_epoch)):.4f}")
            checkpoint.save(latest, {"params": jax.device_get(params),
                                     "epoch": epoch})
    if args.timeline:
        timeline.stop_timeline()


if __name__ == "__main__":
    main()
