"""Distributed skip-gram word2vec in JAX with SPARSE embedding-gradient
reduction (the IndexedSlices-allgather analogue; reference
``examples/tensorflow_word2vec.py`` + ``tensorflow/__init__.py:74-89``).

Each step touches a few hundred rows of the embedding tables, so
``DistributedOptimizer(..., sparse_keys=("embed",))`` reduces those
leaves by allgathering (indices, values) instead of allreducing the
dense tables — wire traffic scales with the batch's vocabulary slice,
not the table.  The run prints measured wire bytes sparse-vs-dense.

    horovodrun -np 2 python examples/jax_word2vec.py

Synthetic Zipf corpus so the example runs hermetically.  The training
loop is EAGER (like the reference's tape) — that is where the sparse
route engages; under jit, gradients are static-shape dense.
"""

import numpy as np

import jax

# CPU demo (must run before any backend init): the sparse reduction is a
# host-side eager path, and N launcher ranks should not all grab the
# accelerator.  Delete this line to run on real chips.
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.ops import sparse as SP

VOCAB = 2000
DIM = 64
WINDOW = 2
BATCH = 256
NEG = 4


def synthetic_corpus(n=50_000, seed=0):
    rng = np.random.RandomState(seed)
    return rng.zipf(1.3, n).clip(max=VOCAB - 1).astype(np.int32)


def batches(corpus, seed):
    rng = np.random.RandomState(seed)
    while True:
        centers = rng.randint(WINDOW, len(corpus) - WINDOW, BATCH)
        offs = rng.randint(1, WINDOW + 1, BATCH) * rng.choice([-1, 1], BATCH)
        ctx = corpus[centers + offs]
        neg = rng.randint(0, VOCAB, (BATCH, NEG)).astype(np.int32)
        yield corpus[centers], ctx, neg


def loss_fn(params, center, ctx, neg):
    """Negative-sampling skip-gram loss."""
    v = params["in_embed"][center]           # (B, D)
    u_pos = params["out_embed"][ctx]         # (B, D)
    u_neg = params["out_embed"][neg]         # (B, NEG, D)
    pos = jax.nn.log_sigmoid(jnp.sum(v * u_pos, -1))
    negs = jax.nn.log_sigmoid(-jnp.einsum("bd,bnd->bn", v, u_neg))
    return -(pos.mean() + negs.sum(-1).mean())


def main():
    hvd.init()
    rank = hvd.process_rank()
    rng = np.random.RandomState(0)
    params = {
        "in_embed": jnp.asarray(
            rng.uniform(-0.5 / DIM, 0.5 / DIM, (VOCAB, DIM)), jnp.float32),
        "out_embed": jnp.zeros((VOCAB, DIM), jnp.float32),
    }
    opt = hvd.DistributedOptimizer(optax.adagrad(0.5),
                                   sparse_keys=("embed",))
    state = opt.init(params)
    params = hvd.broadcast_parameters(params, root_rank=0)

    grad = jax.jit(jax.value_and_grad(loss_fn))
    stream = batches(synthetic_corpus(), seed=rank)
    sparse_bytes = dense_bytes = 0
    for step in range(60):
        center, ctx, neg = next(stream)
        loss, g = grad(params, center, ctx, neg)
        g = {k: np.asarray(v) for k, v in g.items()}  # eager: sparse path
        for v in g.values():  # wire accounting (same math the path does)
            rows = np.flatnonzero(np.any(v != 0, axis=1))
            sparse_bytes += rows.nbytes + v[rows].nbytes
            dense_bytes += v.nbytes
        up, state = opt.update(g, state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, up)
        if rank == 0 and step % 20 == 0:
            print(f"step {step:3d}  loss {float(loss):.4f}")

    # Independent check (collective — every rank participates): one
    # sparse reduction equals the dense one.
    probe = np.asarray(g["in_embed"])
    np.testing.assert_allclose(
        SP.sparse_allreduce(probe, hvd.Average, name="w2v.check"),
        np.asarray(hvd.allreduce(probe, hvd.Average, name="w2v.ref")),
        rtol=1e-6)
    if rank == 0:
        print(f"wire bytes: sparse {sparse_bytes:,} vs dense "
              f"{dense_bytes:,} ({dense_bytes / sparse_bytes:.1f}x saved)")
        print("sparse == dense reduction: OK")
    hvd.shutdown()


if __name__ == "__main__":
    main()
