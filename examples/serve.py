"""Serve a toy LM over HTTP with the continuous-batching engine.

Trains the same count-mod-32 LM as ``examples/generate.py`` for a few
steps, then stands up the full serving stack (docs/serving.md):
slot-based KV cache + FCFS scheduler + engine loop + stdlib-HTTP front
— and fires a burst of concurrent clients at it to show continuous
batching at work.  Runs on any backend, including JAX_PLATFORMS=cpu.

Run:  python examples/serve.py [--steps 30] [--port 8000] [--keep]
      python examples/serve.py --trace /tmp/serve_trace.json --chaos
      python examples/serve.py --replicas 3
      python examples/serve.py --tp 2              # one GSPMD-sharded engine
      python examples/serve.py --replicas 2 --tp 2 # router over tp-2 replicas

``--tp N`` shards the engine (or, with ``--replicas``, every replica's
engine) over an N-device GSPMD ``tp`` mesh — attention heads and the
MLP hidden dim split, the paged KV pool head-sharded — serving output
token-identical to tp=1 (docs/serving.md "Tensor-parallel replicas").
CPU demos force N host devices automatically.

``--replicas N`` (N > 1) stands up the REPLICATED front tier instead
(docs/serving.md "Front tier"): the trained params are pickled once,
a ReplicaSupervisor spawns N replica processes serving them, and a
router proxies /generate over the pool with join-shortest-queue +
failover.  The demo SIGKILLs one replica in the middle of the burst
and shows every request still completing (the router retries on a
surviving replica; the supervisor respawns the dead one).  SIGTERM /
Ctrl-C still drain gracefully.

With ``--keep`` the server stays up (curl it yourself):
    curl -s localhost:8000/generate -d '{"tokens": [3,4,5], "max_new_tokens": 8}'
    curl -s localhost:8000/stats
    curl -s localhost:8000/metrics          # Prometheus text exposition

``--trace PATH`` records ONE Perfetto/Chrome trace (open in
https://ui.perfetto.dev) interleaving the training steps, every serving
request's queue/prefill/decode spans (with trace ids), the engine
tick-phase spans, and instant events for XLA compiles — plus a
``PATH.jsonl`` structured request log.  ``--chaos`` injects one decode
fault after the demo burst so the trace also shows a supervised engine
restart (docs/observability.md).

Shutdown is GRACEFUL: SIGTERM (what Kubernetes / systemd send) and
Ctrl-C both trigger a drain — /healthz flips to 503 ``draining``, new
/generate calls are rejected with 503, in-flight requests run to
completion, then the server tears down (docs/serving.md "Operations").
"""

from __future__ import annotations

import argparse
import json
import signal
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax


def train_toy_lm(steps: int):
    """The counting LM from examples/generate.py: tokens[i+1] =
    tokens[i] + 1 (mod 32)."""
    from horovod_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        vocab_size=32, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq=64, dtype=jnp.float32, n_kv_heads=2)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    base = np.arange(64 * 8).reshape(8, 64) % 32
    batch = {"tokens": jnp.asarray(base, jnp.int32),
             "targets": jnp.asarray((base + 1) % 32, jnp.int32)}
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(T.loss_fn)(params, batch, cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    from horovod_tpu import obs

    loss = None
    for _ in range(steps):
        # Span + step-time histogram: with --trace the training steps
        # land on the same Perfetto time axis as the serving requests.
        with obs.training_step():
            params, opt_state, loss = step(params, opt_state)
    print(f"trained {steps} steps, loss {float(loss):.3f}")
    return params, cfg


def replicated_demo(args, params, cfg) -> None:
    """The front tier end to end: N replicas serving the SAME trained
    params behind the router, one SIGKILLed mid-burst — and every
    request still completes (docs/serving.md "Front tier")."""
    import os
    import signal as _signal
    import tempfile

    from horovod_tpu import obs
    from horovod_tpu.serving.router import (
        ReplicaRegistry,
        ReplicaSpec,
        ReplicaSupervisor,
        RouterServer,
    )
    from horovod_tpu.serving.router.replica_main import dump_model

    fd, params_path = tempfile.mkstemp(prefix="serve_lm_",
                                       suffix=".pkl")
    os.close(fd)
    dump_model(params_path, params, cfg)

    stop_requested = threading.Event()
    signal.signal(signal.SIGTERM,
                  lambda signum, frame: stop_requested.set())

    registry = ReplicaRegistry(poll_interval=0.2, heartbeat_stale=15.0)
    journal_dir = tempfile.mkdtemp(prefix="serve_journal_")
    # Span streams: every replica + the router append to span_dir, so
    # GET /trace/<id> can autopsy the SIGKILL'd request afterwards
    # (docs/observability.md "Distributed tracing").
    span_dir = args.spans or tempfile.mkdtemp(prefix="serve_spans_")
    obs.tracing.start_spans(
        os.path.join(span_dir, "router.spans.jsonl"),
        proc="router", role="router")
    sup = ReplicaSupervisor(
        ReplicaSpec(params_path=params_path, slots=args.slots,
                    tp=args.tp,
                    warm=[8], tick_timeout=30.0, drain_timeout=10.0),
        args.replicas, registry=registry, unhealthy_grace=3.0,
        journal_dir=journal_dir, span_dir=span_dir)
    rt = RouterServer(registry, port=args.port,
                      resume_lookup=sup.resume_lookup,
                      span_dir=span_dir)
    try:
        sup.start()
        rt.start()
        host, port = rt.address
        base = f"http://{host}:{port}"
        print(f"spawning {args.replicas} replicas "
              f"(pids {[h.pid for h in sup.replicas()]}) ...")
        if not sup.wait_ready(timeout=180):
            raise RuntimeError("replicas never became ready")
        print(f"router on {base}  ({args.replicas} replicas in rotation)")
        if args.tp > 1:
            print("replica meshes: " + ", ".join(
                f"{s.endpoint.rid}[{s.mesh}]"
                for s in registry.in_rotation()))

        # Twice the single-engine burst, through the router; replica
        # r0 is SIGKILLed once half the requests are in flight.
        n = 2 * args.clients
        rng = np.random.default_rng(0)
        out, errs = {}, {}
        started = threading.Semaphore(0)

        def client(i):
            start = int(rng.integers(0, 24))
            prompt = [(start + j) % 32 for j in range(2 + i % 3)]
            req = urllib.request.Request(
                base + "/generate",
                data=json.dumps({"tokens": prompt,
                                 "max_new_tokens": 6 + i % 4}).encode(),
                headers={"Content-Type": "application/json"})
            started.release()
            try:
                with urllib.request.urlopen(req, timeout=120) as r:
                    out[i] = (prompt, json.loads(r.read()),
                              r.headers.get("X-Router-Replica"))
            except urllib.error.HTTPError as e:
                errs[i] = (e.code, json.loads(e.read()))
            except Exception as e:  # transport failure = a real DROP
                errs[i] = (None, {"type": repr(e)})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for _ in range(n // 2):
            started.acquire()
        victim = sup.handle(0)
        print(f"SIGKILL replica {victim.rid} (pid {victim.pid}) "
              f"mid-burst ...")
        os.kill(victim.pid, _signal.SIGKILL)
        for t in threads:
            t.join()

        by_rep = {}
        for i, (prompt, resp, rep) in sorted(out.items()):
            by_rep.setdefault(rep, []).append(i)
            print(f"client {i:2d}: {prompt} -> {resp['tokens']}  "
                  f"(via {rep}, {resp['finish_reason']})")
        for i, (code, resp) in sorted(errs.items()):
            print(f"client {i:2d}: HTTP {code} ({resp.get('type')})")
        stats = rt.stats()
        dropped = (n - len(out)
                   - sum(1 for c, _ in errs.values() if c is not None))
        print(f"{len(out) + len(errs)}/{n} requests resolved: "
              f"{len(out)} with tokens, "
              f"{len(errs) - dropped} typed errors, {dropped} dropped")
        print(f"per-replica: "
              f"{ {k: len(v) for k, v in by_rep.items()} }  "
              f"retries={stats['retries']:.0f} "
              f"failovers={stats['failovers']:.0f} "
              f"resumed={stats['resume_failovers']:.0f}")

        # The autopsy: pick a request that rode the failover (resumed
        # or multi-attempt) and print its cross-process span tree.
        from horovod_tpu.obs.trace_store import TraceStore

        autopsy_tid = None
        for i, (prompt, resp, rep) in sorted(out.items()):
            if resp.get("resumed"):
                autopsy_tid = resp.get("trace_id")
                break
        if autopsy_tid is None and out:
            autopsy_tid = next(iter(sorted(out.items())))[1][1] \
                .get("trace_id")
        if autopsy_tid:
            tree = TraceStore.from_dir(span_dir).ascii_tree(autopsy_tid)
            if tree:
                print(f"\nautopsy (GET {base}/trace/{autopsy_tid}):")
                print(tree)
            print(f"span streams: {span_dir}  (explore with "
                  f"python -m horovod_tpu.obs.trace --spans "
                  f"{span_dir} --list)")

        deadline = time.monotonic() + 60
        while (len(registry.in_rotation()) < args.replicas
               and time.monotonic() < deadline):
            time.sleep(0.2)
        print(f"supervisor respawned {victim.rid} -> "
              f"{sup.handle(0).rid}; "
              f"{len(registry.in_rotation())}/{args.replicas} back in "
              f"rotation (restarts: "
              f"{registry.metrics.replica_restarts.value:.0f})")

        if args.keep and not stop_requested.is_set():
            print("serving until SIGTERM / Ctrl-C ...")
            try:
                stop_requested.wait()
            except KeyboardInterrupt:
                pass
        print("draining front tier (replicas finish in-flight work) ...")
    finally:
        rt.stop()
        sup.stop(drain=True)
        obs.tracing.stop_spans()
        os.unlink(params_path)
    print("stopped")


def rollout_demo(args, params, cfg) -> None:
    """Zero-downtime fleet reconfiguration end to end (docs/serving.md
    "Fleet rollouts"): 3 replicas behind the router, a candidate
    config POSTed to the admin surface, the canary SIGKILLed mid-score
    — and the controller rolls the fleet back to the incumbent config
    on its own, with every in-flight request resolving."""
    import os
    import signal as _signal
    import tempfile

    from horovod_tpu.serving.router import (
        ReplicaRegistry,
        ReplicaSpec,
        ReplicaSupervisor,
        RolloutController,
        RouterServer,
    )
    from horovod_tpu.serving.router.replica_main import dump_model

    n = max(args.replicas, 3)
    fd, params_path = tempfile.mkstemp(prefix="serve_lm_",
                                       suffix=".pkl")
    os.close(fd)
    dump_model(params_path, params, cfg)
    registry = ReplicaRegistry(poll_interval=0.2, heartbeat_stale=15.0)
    journal_dir = tempfile.mkdtemp(prefix="serve_journal_")
    sup = ReplicaSupervisor(
        ReplicaSpec(params_path=params_path, slots=args.slots,
                    warm=[8], tick_timeout=30.0, drain_timeout=10.0),
        n, registry=registry, unhealthy_grace=3.0,
        journal_dir=journal_dir)
    # canary_windows is generous: the demo kills the canary before
    # scoring ever finishes, proving the crash-trip path.
    ctl = RolloutController(sup, canary_weight=0.3, canary_windows=60,
                            window_s=1.0, ready_timeout=240.0)
    rt = RouterServer(registry, port=args.port,
                      resume_lookup=sup.resume_lookup, rollout=ctl)
    stop_load = threading.Event()

    def load_loop(base):
        rng = np.random.default_rng(5)
        while not stop_load.is_set():
            prompt = [int(t) for t in rng.integers(0, 32, 3)]
            try:
                req = urllib.request.Request(
                    base + "/generate",
                    data=json.dumps({"tokens": prompt,
                                     "max_new_tokens": 8}).encode(),
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=60).read()
            except Exception:
                pass
            time.sleep(0.1)

    def post(base, payload):
        req = urllib.request.Request(
            base + "/rollout", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    def fleet_gens():
        gens = {}
        for st in registry.statuses():
            try:
                with urllib.request.urlopen(
                        st.endpoint.base_url + "/stats",
                        timeout=2.0) as r:
                    gens[st.endpoint.rid] = json.loads(r.read()).get(
                        "config_generation")
            except Exception:
                pass
        return gens

    loader = None
    try:
        sup.start()
        rt.start()
        host, port = rt.address
        base = f"http://{host}:{port}"
        print(f"spawning {n} replicas ...")
        if not sup.wait_ready(timeout=240):
            raise RuntimeError("replicas never became ready")
        print(f"router on {base}  ({n} replicas in rotation, "
              f"config generations {fleet_gens()})")
        loader = threading.Thread(target=load_loop, args=(base,),
                                  daemon=True)
        loader.start()

        candidate = {"max_prefills_per_tick": 4}
        print(f"POST /rollout candidate={candidate}")
        status = post(base, {"candidate": candidate})
        print(f"  -> rollout started: gen {status['config_generation']}")

        killed = False
        last_state = None
        deadline = time.monotonic() + 600.0
        while time.monotonic() < deadline:
            st = ctl.status()
            if st["state"] != last_state:
                last_state = st["state"]
                print(f"  state: {last_state}"
                      + (f"  (trip: {st['trip_reason']})"
                         if st["trip_reason"] else ""))
            if st["state"] == "canary" and not killed:
                h = sup.handle(0)
                time.sleep(1.0)   # let a scoring window open
                print(f"  SIGKILL canary {h.rid} (pid {h.pid}) "
                      f"mid-score ...")
                os.kill(h.pid, _signal.SIGKILL)
                killed = True
            if not st["active"]:
                break
            time.sleep(0.1)
        final = ctl.status()
        print(f"rollout terminal state: {final['state']} "
              f"(trip: {final['trip_reason']})")
        snap = registry.metrics.snapshot()
        print(f"rollbacks={snap['rollout_rollbacks']:.0f} "
              f"promotions={snap['rollout_promotions']:.0f} "
              f"steps={snap['rollout_steps']:.0f}")
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            gens = fleet_gens()
            if len(gens) >= n and set(gens.values()) == {0}:
                break
            time.sleep(0.5)
        print(f"fleet converged back to the incumbent: {fleet_gens()}")
        print(f"rollout journal: "
              f"{os.path.join(journal_dir, 'rollout.journal.jsonl')}")
    finally:
        stop_load.set()
        if loader is not None:
            loader.join(5.0)
        rt.stop()
        sup.stop(drain=True)
        os.unlink(params_path)
    print("stopped")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30, help="train steps")
    ap.add_argument("--port", type=int, default=0,
                    help="HTTP port (0 = ephemeral)")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--clients", type=int, default=6,
                    help="demo burst size")
    ap.add_argument("--keep", action="store_true",
                    help="keep serving after the demo burst")
    ap.add_argument("--trace", default="",
                    help="record a Perfetto/Chrome trace (training + "
                         "serving on one time axis) at this path, plus "
                         "a <path>.jsonl request log")
    ap.add_argument("--chaos", action="store_true",
                    help="inject one decode fault after the demo burst "
                         "so the trace shows a supervised engine restart")
    ap.add_argument("--replicas", type=int, default=1,
                    help="N > 1: serve through the replicated front "
                         "tier (router + supervisor) and SIGKILL one "
                         "replica mid-burst to demo zero-drop failover")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree: shard the engine "
                         "(each replica, with --replicas) over a "
                         "tp-device GSPMD mesh — heads + MLP hidden "
                         "split, paged KV pool head-sharded, output "
                         "token-identical to tp=1 (docs/serving.md "
                         "'Tensor-parallel replicas').  CPU demos get "
                         "forced host devices automatically")
    ap.add_argument("--autotune", action="store_true",
                    help="install the online autotuner and drive a "
                         "synthetic load until it converges, printing "
                         "each sampled knob setting and its objective "
                         "(docs/serving.md 'Autotuning')")
    ap.add_argument("--rollout", action="store_true",
                    help="fleet-rollout demo (docs/serving.md 'Fleet "
                         "rollouts'): 3+ replicas behind the router, a "
                         "candidate config POSTed to /rollout, the "
                         "canary SIGKILLed mid-score — the controller "
                         "rolls the whole fleet back to the incumbent "
                         "on its own (forces --replicas >= 3)")
    ap.add_argument("--spans", default="",
                    help="(with --replicas) span-stream directory for "
                         "distributed tracing — the killed request's "
                         "cross-process autopsy prints after the "
                         "burst and GET /trace/<id> serves it (a tmp "
                         "dir is used when omitted)")
    args = ap.parse_args()

    if args.tp > 1:
        # Devices must exist before the backend spins up (CPU hosts:
        # the forced-host-device flag; a real accelerator host already
        # exposes its topology).  jax has not run an op yet, so the
        # flag is still read at backend init.
        from horovod_tpu.serving.sharding import ensure_devices

        ensure_devices(args.tp)

    import horovod_tpu as hvd
    from horovod_tpu import obs, serving

    hvd.init()
    if args.trace:
        obs.tracing.start(args.trace, jsonl_path=args.trace + ".jsonl")
    params, cfg = train_toy_lm(args.steps)

    if args.rollout:
        rollout_demo(args, params, cfg)
        if args.trace:
            obs.tracing.stop()
            print(f"trace written: {args.trace} (open in "
                  f"https://ui.perfetto.dev); request log: "
                  f"{args.trace}.jsonl")
        hvd.shutdown()
        return

    if args.replicas > 1:
        replicated_demo(args, params, cfg)
        if args.trace:
            obs.tracing.stop()
            print(f"trace written: {args.trace} (open in "
                  f"https://ui.perfetto.dev); request log: "
                  f"{args.trace}.jsonl")
        hvd.shutdown()
        return

    inj = serving.FaultInjector() if args.chaos else None
    engine = serving.InferenceEngine(
        params, cfg,
        serving.EngineConfig(n_slots=args.slots, max_len=cfg.max_seq,
                             restart_backoff=0.05, faults=inj,
                             tp=args.tp,
                             # turns token counters into achieved
                             # FLOP/s in /stats (docs/observability.md)
                             model_flops_per_token=obs.xprof
                             .transformer_flops_per_token(params)),
        detokenize=lambda t: f" {t}")
    if args.tp > 1:
        print(f"engine sharded over {engine.stats()['mesh']}")
    if args.autotune:
        # Warm FIRST (the tuner derives its compile-safe knob bounds
        # from what warmup compiled), then install with demo-friendly
        # pacing — short scoring windows so convergence is watchable.
        from horovod_tpu.tuning import OnlineTuner

        engine.warmup([2, 4])
        tuner = OnlineTuner.install(engine, window_ticks=8,
                                    bo_samples=5)
        print(f"autotuner installed: knobs "
              f"{sorted(tuner.space.defaults())}")
    # SIGTERM (k8s/systemd stop) -> graceful drain, same as Ctrl-C —
    # installed for the WHOLE serving lifetime, demo burst included:
    # the load balancer sees 503 on /healthz, admitted requests
    # finish, then the listener closes.
    stop_requested = threading.Event()
    signal.signal(signal.SIGTERM,
                  lambda signum, frame: stop_requested.set())
    srv = serving.ServingServer(engine, port=args.port).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    print(f"serving on {base}  (slots={args.slots})")

    # Demo burst: concurrent clients, different prompts and lengths —
    # the engine fuses them into one masked decode batch.
    rng = np.random.default_rng(0)
    def client(i, out):
        start = int(rng.integers(0, 24))
        prompt = [(start + j) % 32 for j in range(2 + i % 3)]
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"tokens": prompt,
                             "max_new_tokens": 6 + i % 4}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            out[i] = (prompt, json.loads(r.read()))

    out = {}
    threads = [threading.Thread(target=client, args=(i, out))
               for i in range(args.clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in sorted(out):
        prompt, resp = out[i]
        print(f"client {i}: {prompt} ->{resp['text']}  "
              f"(ttft {resp['ttft_ms']}ms, {resp['finish_reason']})")

    with urllib.request.urlopen(base + "/stats", timeout=10) as r:
        stats = json.loads(r.read())
    print(f"stats: {stats['requests_completed']} completed, "
          f"{stats['tokens_generated']} tokens, "
          f"decode compiles {stats['decode_compilations']}, "
          f"TTFT p50 {stats['ttft_seconds']['p50']}s")

    if args.autotune:
        # Drive waves of mixed traffic until the tuner pins (or a wave
        # cap), printing each scored sample as it lands — live
        # convergence, knob by knob.
        tuner = engine._tuner
        printed = 0
        for wave in range(200):
            if tuner.phase == "pinned":
                break
            waves = []
            for i in range(args.slots * 2):
                start = int(rng.integers(0, 24))
                prompt = [(start + j) % 32 for j in range(2 + i % 3)]
                req = urllib.request.Request(
                    base + "/generate",
                    data=json.dumps({"tokens": prompt,
                                     "max_new_tokens": 6}).encode(),
                    headers={"Content-Type": "application/json"})
                t = threading.Thread(
                    target=lambda r=req: urllib.request.urlopen(
                        r, timeout=120).read())
                t.start()
                waves.append(t)
            for t in waves:
                t.join()
            snap = tuner.snapshot()
            for entry in snap["trajectory"][printed:]:
                print(f"  sample {entry['sample']:>2} "
                      f"[{entry['phase']}] {entry['settings']} -> "
                      f"objective {entry['objective']:.3f}"
                      + ("  (SLO violation, rolled back)"
                         if entry["violated"] else ""))
            printed = len(snap["trajectory"])
        snap = tuner.snapshot()
        print(f"autotune: phase={snap['phase']} after "
              f"{snap['samples']} samples; best objective "
              f"{snap['best']['objective']} with "
              f"{snap['best']['settings']}; GET {base}/tuning "
              f"serves this snapshot")

    if args.chaos:
        # One injected decode fault: the probe request fails typed
        # (503 engine_failed, trace id intact), the engine restarts
        # with a fresh cache, and the trace gains an engine_restart
        # instant next to the request spans.
        inj.add(serving.FaultSpec(
            site="decode_tick", kind="raise",
            skip=inj.visits("decode_tick") + 1))
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"tokens": [1, 2, 3],
                             "max_new_tokens": 8}).encode(),
            headers={"Content-Type": "application/json",
                     "X-Trace-Id": "chaos-demo"})
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                code, resp = r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            code, resp = e.code, json.loads(e.read())
        print(f"chaos: injected decode fault -> HTTP {code} "
              f"({resp.get('type')}, trace {resp.get('trace_id')})")
        deadline = time.monotonic() + 30
        while engine.health != "healthy" and time.monotonic() < deadline:
            time.sleep(0.05)
        with urllib.request.urlopen(req, timeout=60) as r:
            resp = json.loads(r.read())
        print(f"chaos: recovered ->{resp['text']}  "
              f"(engine restarts: "
              f"{engine.metrics.engine_restarts.value})")

    if args.keep and not stop_requested.is_set():
        print("serving until SIGTERM / Ctrl-C ...")
        try:
            stop_requested.wait()
        except KeyboardInterrupt:
            pass
    print("draining (in-flight requests run to completion) ...")
    srv.stop(drain_timeout=30.0)
    print(f"stopped; final engine state: {engine.health}")
    if args.trace:
        obs.tracing.stop()
        print(f"trace written: {args.trace} (open in "
              f"https://ui.perfetto.dev); request log: "
              f"{args.trace}.jsonl")
    hvd.shutdown()


if __name__ == "__main__":
    main()
