"""Train the flagship Transformer LM with full GSPMD parallelism
(dp/fsdp/tp/sp/pp/ep) — the capability demo the reference has no analogue
for (it is DP-only, SURVEY.md §2.6).

Single chip:             python examples/transformer_lm.py
8 virtual CPU devices:   JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                             python examples/transformer_lm.py --mesh dp2,tp2,sp2
Long context via ring attention (sequence parallelism):
                         ... --mesh sp8 --attention ring
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import transformer as T
from horovod_tpu.parallel import MeshSpec, make_mesh


def parse_mesh(arg: str):
    """"dp2,tp2,sp2" -> axis sizes dict; missing axes default to 1."""
    sizes = {"dp": 1, "fsdp": 1, "pp": 1, "ep": 1, "sp": 1, "tp": 1}
    if arg:
        for part in arg.split(","):
            name = part.rstrip("0123456789")
            count = part[len(name):]
            if name not in sizes or not count:
                raise SystemExit(
                    f"--mesh: bad token {part!r}; expected <axis><count> "
                    f"with axis in {sorted(sizes)} (e.g. dp2,tp2,sp2)")
            sizes[name] = int(count)
    return sizes


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mesh", default="", help="e.g. dp2,tp2,sp2")
    p.add_argument("--attention", default="reference",
                   choices=["reference", "flash", "ring"])
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=256)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    args = p.parse_args()

    hvd.init()
    sizes = parse_mesh(args.mesh)
    n_needed = int(np.prod(list(sizes.values())))
    devices = jax.devices()[:n_needed]
    mesh = make_mesh(MeshSpec(**sizes), devices)

    cfg = T.TransformerConfig(
        vocab_size=1024, d_model=args.d_model,
        n_heads=max(args.d_model // 64, 2), n_layers=args.layers,
        d_ff=args.d_model * 4, max_seq=args.seq,
        attention_impl=args.attention)

    params = T.init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(3e-4)
    opt_state = opt.init(params)

    pspecs = T.param_specs(cfg)
    bspecs = T.batch_specs()

    def put(tree, specs):
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            tree, specs, is_leaf=lambda x: isinstance(x, P))

    with mesh:
        params = put(params, pspecs)

        if args.attention == "ring":
            # Ring attention runs under shard_map: the sp axis must be
            # bound so K/V shards can ppermute around the ring.  Params
            # replicated; batch dim shards over dp(+fsdp), sequence over
            # sp; gradients average over all data axes so dp>1 does real
            # (not duplicated) work.
            data_axes = ("dp", "fsdp", "sp")

            def _step(params, opt_state, batch):
                loss, grads = jax.value_and_grad(T.loss_fn)(
                    params, batch, cfg)
                grads = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(g, data_axes), grads)
                loss = jax.lax.pmean(loss, data_axes)
                updates, opt_state = opt.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), opt_state, loss

            step = jax.jit(jax.shard_map(
                _step, mesh=mesh,
                in_specs=(P(), P(), P(("dp", "fsdp"), "sp")),
                out_specs=(P(), P(), P()),
                # Same default as spmd.shard: the Pallas flash kernels in
                # the ring path can't carry vma types through the CPU
                # interpreter (jax's own suggested workaround).
                check_vma=False,
            ))
        else:

            @jax.jit
            def step(params, opt_state, batch):
                loss, grads = jax.value_and_grad(T.loss_fn)(
                    params, batch, cfg)
                updates, opt_state = opt.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), opt_state, loss

        batch = T.synthetic_batch(jax.random.PRNGKey(1), cfg, args.batch,
                                  args.seq)
        batch = put(batch, bspecs)

        t0 = time.perf_counter()
        for s in range(args.steps):
            params, opt_state, loss = step(params, opt_state, batch)
            if s % 10 == 0 and hvd.process_rank() == 0:
                print(f"step {s}: loss {float(loss):.4f}")
        dt = time.perf_counter() - t0
        toks = args.batch * args.seq * args.steps
        if hvd.process_rank() == 0:
            print(f"{toks / dt:.0f} tokens/sec on mesh {sizes} "
                  f"({args.attention} attention); final loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
