"""Estimator API demo (reference: the Spark Estimator workflow,
``examples/keras_spark_mnist.py`` shape — data in a Store, fit() runs
distributed training, the returned Model predicts locally).

    python examples/estimator_example.py
"""

import tempfile

import numpy as np
import torch

from horovod_tpu.estimator import (EstimatorParams, LocalStore,
                                   TorchEstimator)


def model_factory():
    torch.manual_seed(0)
    return torch.nn.Sequential(
        torch.nn.Linear(8, 32), torch.nn.ReLU(), torch.nn.Linear(32, 1))


def optimizer_factory(params):
    return torch.optim.Adam(params, lr=1e-2)


def loss_fn(pred, target):
    return torch.nn.functional.mse_loss(pred, target)


def main():
    rng = np.random.RandomState(0)
    x = rng.randn(2048, 8).astype(np.float32)
    y = (x @ rng.randn(8, 1)).astype(np.float32)

    store = LocalStore(tempfile.mkdtemp(prefix="hvd_store_"))
    est = TorchEstimator(
        model_factory=model_factory,
        optimizer_factory=optimizer_factory,
        loss_fn=loss_fn,
        store=store,
        params=EstimatorParams(num_proc=2, epochs=5, batch_size=64),
    )
    model = est.fit(x, y)
    print("epoch losses:", [round(h, 4) for h in model.history])
    pred = model.predict(x[:4])
    print("predictions:", pred.ravel().round(3))
    print("targets:    ", y[:4].ravel().round(3))


if __name__ == "__main__":
    main()
