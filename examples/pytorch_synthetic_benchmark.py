"""PyTorch synthetic benchmark (reference:
``examples/pytorch_synthetic_benchmark.py``): same protocol — synthetic
data, N warmup batches, timed iterations, images/sec per worker with the
10-batch x 10-iter mean +/- 1.96 sigma report.

    horovodrun -np 2 python examples/pytorch_synthetic_benchmark.py
"""

import argparse
import time

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class SmallResNetish(torch.nn.Module):
    """Compact conv net standing in for torchvision's resnet50 (which
    isn't in this image); same benchmark mechanics."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.features = torch.nn.Sequential(
            torch.nn.Conv2d(3, 64, 7, stride=2, padding=3), torch.nn.ReLU(),
            torch.nn.MaxPool2d(3, 2, 1),
            torch.nn.Conv2d(64, 128, 3, stride=2, padding=1), torch.nn.ReLU(),
            torch.nn.Conv2d(128, 256, 3, stride=2, padding=1), torch.nn.ReLU(),
            torch.nn.AdaptiveAvgPool2d(1),
        )
        self.fc = torch.nn.Linear(256, num_classes)

    def forward(self, x):
        return self.fc(self.features(x).flatten(1))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-warmup-batches", type=int, default=10)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--fp16-allreduce", action="store_true")
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(42)

    model = SmallResNetish()
    lr_scaler = hvd.size()
    optimizer = torch.optim.SGD(model.parameters(), lr=0.01 * lr_scaler)
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    data = torch.randn(args.batch_size, 3, 224, 224)
    target = torch.randint(0, 1000, (args.batch_size,))

    def benchmark_step():
        optimizer.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        optimizer.step()

    for _ in range(args.num_warmup_batches):
        benchmark_step()

    img_secs = []
    for _ in range(args.num_iters):
        t0 = time.time()
        for _ in range(args.num_batches_per_iter):
            benchmark_step()
        dt = time.time() - t0
        img_secs.append(args.batch_size * args.num_batches_per_iter / dt)

    img_sec_mean = np.mean(img_secs)
    img_sec_conf = 1.96 * np.std(img_secs)
    if hvd.rank() == 0:
        print(f"Img/sec per worker: {img_sec_mean:.1f} +-{img_sec_conf:.1f}")
        print(f"Total img/sec on {hvd.size()} worker(s): "
              f"{hvd.size() * img_sec_mean:.1f} "
              f"+-{hvd.size() * img_sec_conf:.1f}")


if __name__ == "__main__":
    main()
