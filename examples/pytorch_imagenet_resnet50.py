"""Full-featured distributed ResNet-50 in PyTorch (reference
``examples/pytorch_imagenet_resnet50.py``): every production knob the
reference script carries — LR warmup + stepwise decay, fp16 wire
compression, gradient accumulation (``backward_passes_per_step``),
checkpoint resume with restore-then-broadcast, metric averaging.

    horovodrun -np 4 python examples/pytorch_imagenet_resnet50.py

Torch runs on CPU in this image; the script demonstrates the torch
FRONTEND's full API over the shared TPU data plane (for peak TPU compute
use the JAX flagship, ``examples/jax_imagenet_resnet50.py``). Synthetic
imagefolder-shaped data keeps it hermetic; see the loader stub.
"""

import argparse
import math
import os

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd


def parse_args():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--steps-per-epoch", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--base-lr", type=float, default=0.0125)
    ap.add_argument("--warmup-epochs", type=int, default=1)
    ap.add_argument("--batches-per-allreduce", type=int, default=1,
                    help="gradient accumulation window")
    ap.add_argument("--fp16-allreduce", action="store_true")
    ap.add_argument("--checkpoint", default="/tmp/torch_r50.pt")
    ap.add_argument("--image-size", type=int, default=64,
                    help="small default so the CPU demo stays quick")
    return ap.parse_args()


def small_resnet(num_classes=1000):
    """Torchvision-free stand-in with ResNet shape (conv stem + blocks);
    swap in torchvision.models.resnet50() when it is installed."""
    return torch.nn.Sequential(
        torch.nn.Conv2d(3, 32, 7, 2, 3), torch.nn.BatchNorm2d(32),
        torch.nn.ReLU(), torch.nn.MaxPool2d(3, 2, 1),
        torch.nn.Conv2d(32, 64, 3, 2, 1), torch.nn.BatchNorm2d(64),
        torch.nn.ReLU(), torch.nn.AdaptiveAvgPool2d(1),
        torch.nn.Flatten(), torch.nn.Linear(64, num_classes),
    )


def lr_at(args, epoch_frac):
    """Goyal et al.: warmup from base to base*size, then /10 at 30/60/80."""
    n = hvd.cross_size()
    if epoch_frac < args.warmup_epochs:
        return args.base_lr * (1 + epoch_frac / args.warmup_epochs * (n - 1))
    lr = args.base_lr * n
    for boundary in (30, 60, 80):
        if epoch_frac >= boundary:
            lr *= 0.1
    return lr


def main():
    args = parse_args()
    hvd.init()
    rank, n = hvd.cross_rank(), hvd.cross_size()
    torch.manual_seed(7)

    model = small_resnet()
    opt = torch.optim.SGD(model.parameters(), lr=args.base_lr,
                          momentum=0.9, weight_decay=5e-5)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters(),
        compression=(hvd.Compression.fp16 if args.fp16_allreduce
                     else hvd.Compression.none),
        backward_passes_per_step=args.batches_per_allreduce)

    start_epoch = 0
    if rank == 0 and os.path.exists(args.checkpoint):
        ckpt = torch.load(args.checkpoint, weights_only=False)
        model.load_state_dict(ckpt["model"])
        opt.load_state_dict(ckpt["optimizer"])  # momentum buffers too
        start_epoch = ckpt["epoch"] + 1
    start_epoch = hvd.broadcast_object(start_epoch, root_rank=0)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    rng = np.random.RandomState(100 + rank)  # per-rank data shard
    for epoch in range(start_epoch, args.epochs):
        model.train()
        losses = []
        for step in range(args.steps_per_epoch):
            lr = lr_at(args, epoch + step / args.steps_per_epoch)
            for g in opt.param_groups:
                g["lr"] = lr
            for _ in range(args.batches_per_allreduce):
                x = torch.from_numpy(rng.rand(
                    args.batch_size, 3, args.image_size,
                    args.image_size).astype(np.float32))
                y = torch.from_numpy(
                    rng.randint(0, 1000, args.batch_size))
                opt.zero_grad()
                loss = F.cross_entropy(model(x), y)
                loss.backward()
                losses.append(float(loss.detach()))
            opt.step()
        # epoch metric averaged over workers (MetricAverageCallback role)
        avg = float(hvd.allreduce(
            torch.tensor(float(np.mean(losses))), op=hvd.Average))
        if rank == 0:
            print(f"epoch {epoch}: loss {avg:.4f} lr {lr:.4f}")
            torch.save({"model": model.state_dict(),
                        "optimizer": opt.state_dict(),
                        "epoch": epoch}, args.checkpoint)

    hvd.shutdown()


if __name__ == "__main__":
    main()
