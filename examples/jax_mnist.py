"""Distributed MNIST-style training in JAX — the framework's "minimal
code change" demo (role of the reference's ``examples/tensorflow2_mnist.py``:
init -> scale LR by size -> DistributedOptimizer/GradientTape -> broadcast
initial state -> rank-0 checkpointing).

Run single-host multi-chip (SPMD over all local TPU chips):

    python examples/jax_mnist.py

Run multi-process via the launcher:

    horovodrun -np 4 python examples/jax_mnist.py

Uses a synthetic MNIST-shaped dataset (28x28 grayscale, 10 classes) so
the example runs hermetically; swap ``synthetic_mnist`` for a real
loader in practice.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import spmd


def synthetic_mnist(n=8192, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    # learnable structure: class = argmax of 10 fixed random projections
    w = rng.randn(28 * 28, 10).astype(np.float32)
    y = (x.reshape(n, -1) @ w).argmax(axis=1).astype(np.int32)
    return x, y


def init_params(rng):
    k1, k2 = jax.random.split(rng)
    return {
        "w1": jax.random.normal(k1, (28 * 28, 128)) * 0.05,
        "b1": jnp.zeros((128,)),
        "w2": jax.random.normal(k2, (128, 10)) * 0.1,
        "b2": jnp.zeros((10,)),
    }


def forward(params, x):
    h = jnp.tanh(x.reshape(x.shape[0], -1) @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def loss_fn(params, batch):
    logits = forward(params, batch["x"])
    labels = jax.nn.one_hot(batch["y"], 10)
    return optax.softmax_cross_entropy(logits, labels).mean()


def main():
    # Horovod-style bootstrap: init(), LR scaled by worker count
    # (reference tensorflow2_mnist.py: opt = tf.optimizers.Adam(0.001 * hvd.size())).
    hvd.init()
    opt = hvd.DistributedOptimizer(optax.adam(1e-3 * hvd.size()))

    params = init_params(jax.random.PRNGKey(0))
    # Consistent start: broadcast rank 0's init to everyone (reference
    # BroadcastGlobalVariablesHook / broadcast_parameters).
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt_state = opt.init(params)

    x, y = synthetic_mnist()
    axis = hvd.AXIS

    def _step(params, opt_state, xb, yb):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, {"x": xb, "y": yb})
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, jax.lax.pmean(loss, axis)

    step = jax.jit(
        spmd.shard(
            _step,
            in_specs=(P(), P(), P(axis), P(axis)),
            out_specs=(P(), P(), P()),
        ),
        donate_argnums=(0, 1),
    )

    batch = 64 * hvd.size()
    steps = 200
    rng = np.random.RandomState(hvd.rank())
    for s in range(steps):
        idx = rng.randint(0, len(x), batch)
        params, opt_state, loss = step(params, opt_state, x[idx], y[idx])
        if s % 50 == 0 and hvd.process_rank() == 0:
            print(f"step {s}: loss {float(loss):.4f}")

    # Rank-0-only checkpoint (the reference convention).
    if hvd.process_rank() == 0:
        import pickle

        path = os.environ.get("CKPT", "/tmp/jax_mnist_params.pkl")
        with open(path, "wb") as f:
            pickle.dump(jax.device_get(params), f)
        print(f"final loss {float(loss):.4f}; checkpoint -> {path}")


if __name__ == "__main__":
    main()
