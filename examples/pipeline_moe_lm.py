"""Train a tiny MoE transformer LM with 1F1B pipeline parallelism.

Combines two of the framework's beyond-reference parallelism pieces on a
virtual device mesh:

* the transformer's layer stack split into ``pp`` pipeline stages,
  scheduled with the memory-bounded **1F1B** schedule
  (``horovod_tpu.parallel.pipeline_value_and_grad(schedule="1f1b")``);
* **switch-MoE** FFNs inside every block (sparse capacity-factor
  dispatch — each token computes one expert).

Run on CPU with virtual devices:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python examples/pipeline_moe_lm.py [--steps 20]
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--pp", type=int, default=4, help="pipeline stages")
    ap.add_argument("--microbatches", type=int, default=8)
    args = ap.parse_args()

    import horovod_tpu as hvd
    from horovod_tpu.models import transformer as T
    from horovod_tpu.parallel import pipeline

    hvd.init()
    pp = args.pp
    if len(jax.devices()) < pp:
        raise SystemExit(
            f"need {pp} devices for pp={pp} "
            f"(hint: XLA_FLAGS=--xla_force_host_platform_device_count={pp})")

    cfg = T.TransformerConfig(
        vocab_size=32, d_model=32, n_heads=4, n_layers=2 * pp, d_ff=64,
        max_seq=16, dtype=jnp.float32, n_experts=4, capacity_factor=2.0)
    params = T.init_params(jax.random.PRNGKey(0), cfg)

    # Count-mod-32 task; M microbatches of (mb, S).
    M, mb = args.microbatches, 2
    base = np.arange(M * mb * cfg.max_seq).reshape(M * mb, cfg.max_seq) % 32
    tokens = jnp.asarray(base, jnp.int32)
    targets = jnp.asarray((base + 1) % 32, jnp.int32)

    mesh = Mesh(np.array(jax.devices()[:pp]), axis_names=("pp",))
    opt = optax.adam(1e-2)

    def stage_fn_maker(cfg):
        def stage_fn(stage_layers, x):
            def body(h, lp):
                return T._layer_body(h, lp, cfg), None

            out, _ = jax.lax.scan(body, x, stage_layers)
            return out

        return stage_fn

    def train_step(params, opt_state, tokens, targets):
        """shard_map body: embed, run the 1F1B pipeline over the layer
        stack, and apply the head inside the last stage's loss."""

        def inner(params, tokens, targets):
            x = params["embed"].astype(cfg.dtype)[tokens]
            xs = x.reshape(M, mb, cfg.max_seq, cfg.d_model)
            ts = targets.reshape(M, mb, cfg.max_seq)
            s = jax.lax.axis_index("pp")
            per_stage = cfg.n_layers // pp
            my_layers = jax.tree_util.tree_map(
                lambda l: jax.lax.dynamic_slice_in_dim(
                    l, s * per_stage, per_stage, 0),
                params["layers"])

            def loss_fn(y, tgt):
                h = T._rmsnorm(y, params["ln_f"])
                logits = jnp.einsum(
                    "bsd,dv->bsv", h,
                    params["head"].astype(cfg.dtype)).astype(jnp.float32)
                logz = jax.scipy.special.logsumexp(logits, axis=-1)
                gold = jnp.take_along_axis(
                    logits, tgt[..., None], axis=-1).squeeze(-1)
                return jnp.sum(logz - gold) / (M * mb * cfg.max_seq)

            loss, stage_grads = pipeline.pipeline_value_and_grad(
                stage_fn_maker(cfg), my_layers, xs, ts, loss_fn,
                axis_name="pp", schedule="1f1b")
            # Reassemble the full layer-stack gradient from the per-stage
            # pieces (each stage holds grads for ITS slice; psum of the
            # padded pieces concatenates them), so a plain optimizer step
            # applies everywhere identically.  Embedding/head grads flow
            # only through stage boundaries in this demo and are left to
            # the stage grads — fine for a pipeline showcase.
            def expand(g):
                full = jnp.zeros((pp,) + g.shape, g.dtype)
                full = full.at[s].set(g)
                full = jax.lax.psum(full, "pp")
                return full.reshape((cfg.n_layers,) + g.shape[1:])

            layer_grads = jax.tree_util.tree_map(expand, stage_grads)
            return loss, layer_grads

        loss, layer_grads = jax.shard_map(
            inner, mesh=mesh, in_specs=(P(), P(), P()),
            out_specs=(P(), P()))(params, tokens, targets)
        grads = jax.tree_util.tree_map(jnp.zeros_like, params)
        grads = {**grads, "layers": layer_grads}
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    opt_state = opt.init(params)
    step = jax.jit(train_step)
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:3d}  loss {float(loss):.4f}")
    print(f"1F1B pipeline (pp={pp}) + switch-MoE (E={cfg.n_experts}) "
          f"trained to loss {float(loss):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
