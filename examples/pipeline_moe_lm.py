"""Train a tiny MoE transformer LM with 1F1B pipeline parallelism.

Combines two of the framework's beyond-reference parallelism pieces on a
virtual device mesh:

* the transformer's layer stack split into ``pp`` pipeline stages,
  scheduled with the memory-bounded **1F1B** schedule
  (``transformer.pipelined_value_and_grad(..., schedule="1f1b")`` —
  EVERY parameter trains: embedding and head gradients flow through the
  schedule's input cotangents and loss-param accumulators);
* **switch-MoE** FFNs inside every block (sparse capacity-factor
  dispatch — each token computes one expert).

Run on CPU with virtual devices:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python examples/pipeline_moe_lm.py [--steps 30]
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--pp", type=int, default=4, help="pipeline stages")
    ap.add_argument("--microbatches", type=int, default=8)
    args = ap.parse_args()

    import horovod_tpu as hvd
    from horovod_tpu.models import transformer as T

    hvd.init()
    pp = args.pp
    if len(jax.devices()) < pp:
        raise SystemExit(
            f"need {pp} devices for pp={pp} "
            f"(hint: XLA_FLAGS=--xla_force_host_platform_device_count={pp})")

    cfg = T.TransformerConfig(
        vocab_size=32, d_model=32, n_heads=4, n_layers=2 * pp, d_ff=64,
        max_seq=16, dtype=jnp.float32, n_experts=4, capacity_factor=2.0,
        # Switch balance term: keeps the learned router from collapsing
        # onto few experts (flows through BOTH pipeline schedules).
        moe_aux_coeff=0.01)
    params = T.init_params(jax.random.PRNGKey(0), cfg)

    # Count-mod-32 task.
    M, mb = args.microbatches, 2
    base = np.arange(M * mb * cfg.max_seq).reshape(M * mb, cfg.max_seq) % 32
    batch = {"tokens": jnp.asarray(base, jnp.int32),
             "targets": jnp.asarray((base + 1) % 32, jnp.int32)}

    mesh = Mesh(np.array(jax.devices()[:pp]), axis_names=("pp",))
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.shard_map(
            lambda pr, b: T.pipelined_value_and_grad(
                pr, b, cfg, schedule="1f1b", n_microbatches=M),
            mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        )(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:3d}  loss {float(loss):.4f}")
    print(f"1F1B pipeline (pp={pp}) + switch-MoE (E={cfg.n_experts}) "
          f"trained to loss {float(loss):.4f}")
    load = np.asarray(T.expert_load(params, batch["tokens"], cfg))
    print("expert load per layer (aux keeps this near uniform = "
          f"{1 / cfg.n_experts:.2f}):")
    for li, row in enumerate(load):
        print(f"  layer {li:2d}: " + " ".join(f"{f:.2f}" for f in row))
    hvd.shutdown()


if __name__ == "__main__":
    main()
