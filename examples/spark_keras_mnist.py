"""Spark KerasEstimator example (reference ``examples/keras_spark_mnist.py``):
build a model, hand it to the estimator with a Store, fit on N workers,
predict with the returned transformer.

With pyspark + an active SparkContext the workers are Spark tasks; without
(this image) they are local launcher processes — same estimator contract.

    python examples/spark_keras_mnist.py
"""

import numpy as np
import tensorflow as tf

from horovod_tpu.spark import KerasEstimator
from horovod_tpu.estimator import EstimatorParams
from horovod_tpu.estimator.store import LocalStore


def synthetic_mnist(n=2048, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28 * 28).astype(np.float32)
    w = rng.randn(28 * 28, 10).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[(x @ w).argmax(axis=1)]
    return x, y


def main():
    x, y = synthetic_mnist()

    model = tf.keras.Sequential([
        tf.keras.layers.Input(shape=(28 * 28,)),
        tf.keras.layers.Dense(64, activation="relu"),
        tf.keras.layers.Dense(10, activation="softmax"),
    ])

    est = KerasEstimator(
        model=model,
        optimizer=tf.keras.optimizers.Adam(1e-3),
        loss="categorical_crossentropy",
        metrics=["accuracy"],
        store=LocalStore("/tmp/spark_keras_mnist"),
        params=EstimatorParams(num_proc=2, epochs=3, batch_size=32),
    )
    trained = est.fit(x, y)
    print("loss history:", [round(v, 4) for v in trained.history["loss"]])

    preds = trained.predict(x[:8])
    print("predictions:", preds.argmax(axis=1).tolist())


if __name__ == "__main__":
    main()
