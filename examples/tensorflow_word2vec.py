"""Distributed skip-gram word2vec (reference
``examples/tensorflow_word2vec.py``): embedding training whose gradients
are ``tf.IndexedSlices`` — they ride the SPARSE allreduce path
(allgather of touched rows, ``docs/frontends.md``), so wire traffic
scales with the batch's vocabulary slice, not the embedding table.

    horovodrun -np 2 python examples/tensorflow_word2vec.py

Synthetic corpus (Zipf-distributed token stream) so the example runs
hermetically.
"""

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd

VOCAB = 2000
DIM = 64
WINDOW = 2


def synthetic_corpus(n=100_000, seed=0):
    rng = np.random.RandomState(seed)
    return rng.zipf(1.3, n).clip(max=VOCAB - 1).astype(np.int64)


def skipgram_batches(corpus, batch, seed):
    rng = np.random.RandomState(seed)
    while True:
        centers = rng.randint(WINDOW, len(corpus) - WINDOW, batch)
        offsets = rng.randint(1, WINDOW + 1, batch) * rng.choice([-1, 1], batch)
        yield corpus[centers], corpus[centers + offsets]


def main():
    hvd.init()
    rank, n = hvd.process_rank(), hvd.num_processes()

    corpus = synthetic_corpus()
    # shard the corpus by rank
    corpus = corpus[rank::n]

    emb = tf.Variable(tf.random.uniform([VOCAB, DIM], -0.05, 0.05, seed=3))
    nce_w = tf.Variable(tf.zeros([VOCAB, DIM]))
    opt = tf.keras.optimizers.SGD(0.5 * n)
    hvd.broadcast_variables([emb, nce_w], root_rank=0)

    batches = skipgram_batches(corpus, 256, seed=rank)
    for step in range(200):
        centers, contexts = next(batches)
        negatives = np.random.RandomState(step).randint(0, VOCAB, (256, 5))
        with tf.GradientTape() as tape:
            h = tf.nn.embedding_lookup(emb, centers)          # sparse grad
            pos = tf.nn.embedding_lookup(nce_w, contexts)
            neg = tf.nn.embedding_lookup(nce_w, negatives)
            pos_logit = tf.reduce_sum(h * pos, axis=1)
            neg_logit = tf.einsum("bd,bkd->bk", h, neg)
            loss = tf.reduce_mean(
                tf.nn.sigmoid_cross_entropy_with_logits(
                    tf.ones_like(pos_logit), pos_logit)
                + tf.reduce_sum(tf.nn.sigmoid_cross_entropy_with_logits(
                    tf.zeros_like(neg_logit), neg_logit), axis=1))
        grads = tape.gradient(loss, [emb, nce_w])
        # IndexedSlices -> sparse allreduce (allgather of touched rows)
        grads = [hvd.allreduce(g, op=hvd.Average, name=f"w2v.g{i}")
                 for i, g in enumerate(grads)]
        opt.apply_gradients(zip(grads, [emb, nce_w]))
        if step % 50 == 0 and rank == 0:
            print(f"step {step}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
