"""Synthetic ResNet-50 benchmark — the reference's measurement protocol
(``examples/tensorflow2_synthetic_benchmark.py:36-131``): synthetic data,
default batch 32/worker, 10 warmup batches, 10 iterations x 10 batches,
reports images/sec per worker.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/sec/chip", "vs_baseline": N}

vs_baseline compares against the reference's published per-GPU throughput:
ResNet-101 at 1656.82 total img/s over 16 Pascal GPUs => 103.55
img/s/GPU (``docs/benchmarks.rst:29-43``); we use it as the per-accelerator
yardstick for ResNet-50 (the closest published number; ResNet-50 is
slightly cheaper so this flatters the baseline, not us).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

REFERENCE_IMG_PER_SEC_PER_ACCEL = 1656.82 / 16  # docs/benchmarks.rst:29-43


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="ResNet50")
    # Default 384/chip: the v5e MXU keeps gaining to here for ResNet-50
    # bf16 (32 -> 1.43k img/s, 128 -> 2.25k, 256 -> 2.33k, 384 -> 2.39k);
    # the reference's own published number used batch 64/GPU
    # (docs/benchmarks.rst:29-43) and its synthetic script default of 32 is
    # a CLI default, not part of the metric definition — batch size is
    # disclosed in the metric string.
    ap.add_argument("--batch-size", type=int, default=384)
    ap.add_argument("--num-warmup-batches", type=int, default=10)
    ap.add_argument("--num-batches-per-iter", type=int, default=10)
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--fp16-allreduce", action="store_true")
    ap.add_argument("--stem", default="conv7", choices=["conv7", "s2d"],
                    help="ResNet stem: canonical 7x7/2 conv, or 2x2 "
                         "space-to-depth + 4x4 conv (same function class, "
                         "4x the MXU input-channel occupancy)")
    ap.add_argument("--input-pipeline", action="store_true",
                    help="ALSO measure with batches fed from host memory "
                         "through horovod_tpu.data.DataLoader (prefetching "
                         "host->HBM) and report the overhead vs the "
                         "device-resident synthetic number, interleaved in "
                         "this same process (chip-to-chip variance ~15%)")
    args = ap.parse_args()

    import horovod_tpu as hvd

    hvd.init()

    # CPU fallback (configured TPU platform unavailable): a TPU-sized run
    # burns the whole harness budget before emitting its JSON line
    # (BENCH_r05: rc=124 at batch 384 on CPU) — clamp to a smoke
    # configuration so the line is ALWAYS emitted within the time budget.
    # The metric string and cpu_smoke flag disclose the clamp.  The
    # PR 2 clamp alone proved insufficient (BENCH_r05 regressed to
    # rc=124 again: ResNet-50@224 compile + batch-8 steps on 2 CPU
    # cores outlast the harness), so the smoke config is now smaller
    # still AND a SIGALRM wall-clock budget guarantees the JSON line
    # lands from a finally-path even when the measured loop cannot
    # finish.
    cpu_smoke = jax.devices()[0].platform == "cpu"
    if cpu_smoke:
        smoke = {"batch_size": 4, "num_warmup_batches": 1,
                 "num_batches_per_iter": 1, "num_iters": 2,
                 "image_size": 112}
        clamped = {k: v for k, v in smoke.items() if getattr(args, k) > v}
        for k, v in clamped.items():
            setattr(args, k, v)
        if clamped:
            print(f"TPU unavailable — running on CPU; clamped {clamped} "
                  "to a smoke configuration", file=sys.stderr)

    if args.model == "InceptionV3" and args.image_size == 224:
        args.image_size = 299  # Inception's native resolution

    from horovod_tpu.obs import xprof

    n = hvd.size()
    global_batch = args.batch_size * n
    kind = jax.devices()[0].device_kind
    # Peak table lives in obs.xprof now (shared with
    # benchmarks/transformer.py); unknown chip: MFU fields become JSON
    # null, not NaN.
    peak = xprof.chip_peak_flops()

    # The summary skeleton exists BEFORE any heavy work and the ONE
    # JSON line is printed from the finally-path below — so a
    # parseable line ALWAYS lands, even when compilation or the
    # measured loop outlives the CPU-smoke wall-clock budget
    # (value stays null and budget_exceeded says why).
    result = {
        "metric": f"{args.model} synthetic train throughput per chip "
        f"(batch {args.batch_size}/chip, {n} chip(s))",
        "value": None,
        "unit": "img/sec/chip",
        "vs_baseline": None,
        "stddev95": None,
        "mfu": None,
        "tflops_per_sec": None,
        "xla_flops_per_img": None,
        "hbm_peak_bytes": None,
        "training_mfu_live": None,
        "chip": kind,
        "peak_bf16_tflops": peak / 1e12 if peak else None,
        "cpu_smoke": cpu_smoke,
        "budget_exceeded": False,
    }
    state = {"img_secs": [], "fed_img_secs": [], "flops_per_img": 0.0}
    summarized = threading.Lock()  # whoever takes it prints THE line

    def _summarize() -> bool:
        if not summarized.acquire(blocking=False):
            return False  # the other side (watchdog vs main) printed
        if state["img_secs"]:
            med = float(np.median(state["img_secs"]))
            fpi = state["flops_per_img"]
            result["value"] = round(med, 2)
            result["vs_baseline"] = round(
                med / REFERENCE_IMG_PER_SEC_PER_ACCEL, 3)
            result["stddev95"] = round(
                float(1.96 * np.std(state["img_secs"])), 2)
            if fpi:
                result["tflops_per_sec"] = round(med * fpi / 1e12, 1)
                if peak:
                    result["mfu"] = round(med * fpi / peak, 4)
        print(json.dumps(result), flush=True)
        return True

    if cpu_smoke:
        # Wall-clock budget as a WATCHDOG THREAD, not SIGALRM: CPython
        # delivers signals only between bytecodes on the main thread,
        # so an alarm landing inside the minutes-long XLA compile call
        # would sit undelivered until compile returns — exactly the
        # compile-dominated case (BENCH_r05 rc=124) this guards.  A
        # timer thread runs regardless (compile releases the GIL),
        # prints the partial summary, and hard-exits 0 so the harness
        # always gets its parseable line inside the budget.
        budget = float(os.environ.get("BENCH_BUDGET_S", "420"))

        def _bail() -> None:
            result["budget_exceeded"] = True
            print("CPU-smoke wall-clock budget exceeded; emitting the "
                  "partial summary", file=sys.stderr, flush=True)
            if not _summarize():
                time.sleep(2.0)  # main thread is printing: let it land
            os._exit(0)

        watchdog = threading.Timer(budget, _bail)
        watchdog.daemon = True
        watchdog.start()

    try:
        _measure(args, hvd, result, state, n, global_batch)
    finally:
        if cpu_smoke:
            watchdog.cancel()
        _summarize()


def _measure(args, hvd, result, state, n, global_batch) -> None:
    from horovod_tpu import spmd
    from horovod_tpu.models import inception, resnet

    models_mod = inception if args.model == "InceptionV3" else resnet
    if args.model == "InceptionV3":
        model = models_mod.create(args.model, num_classes=1000)
    else:
        model = models_mod.create(args.model, num_classes=1000,
                                  stem=args.stem)
    rng = jax.random.PRNGKey(42)
    variables = models_mod.init_variables(model, rng, args.image_size, batch=2)
    params, batch_stats = variables["params"], variables["batch_stats"]

    compression = hvd.Compression.bf16 if args.fp16_allreduce else hvd.Compression.none
    opt = hvd.DistributedOptimizer(
        optax.sgd(0.01 * hvd.size(), momentum=0.9), compression=compression
    )
    opt_state = opt.init(params)

    def loss_fn(p, batch):
        images, labels, stats = batch["images"], batch["labels"], batch["stats"]
        logits, new_model_state = model.apply(
            {"params": p, "batch_stats": stats},
            images,
            train=True,
            mutable=["batch_stats"],
        )
        one_hot = jax.nn.one_hot(labels, 1000)
        loss = optax.softmax_cross_entropy(logits, one_hot).mean()
        return loss, new_model_state["batch_stats"]

    axis = hvd.AXIS
    mesh = hvd.mesh()

    from jax.sharding import PartitionSpec as P

    def _step(params, opt_state, stats, images, labels):
        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, {"images": images, "labels": labels, "stats": stats}
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, new_stats, jax.lax.pmean(loss, axis)

    step = jax.jit(
        spmd.shard(
            _step,
            in_specs=(P(), P(), P(), P(axis), P(axis)),
            out_specs=(P(), P(), P(), P()),
            mesh=mesh,
        ),
        donate_argnums=(0, 1, 2),
    )

    # Synthetic data lives ON DEVICE, sharded batch-wise over the worker
    # mesh (the reference benchmark's fixed random batch,
    # examples/tensorflow2_synthetic_benchmark.py:60-66): re-uploading
    # host arrays each step would measure host->device bandwidth, and an
    # unsharded device_put would commit the global batch to one chip.
    from jax.sharding import NamedSharding

    batch_sharding = NamedSharding(mesh, P(axis))
    images = jax.device_put(
        jnp.asarray(
            np.random.rand(global_batch, args.image_size, args.image_size, 3),
            jnp.bfloat16,
        ),
        batch_sharding,
    )
    labels = jax.device_put(
        jnp.asarray(np.random.randint(0, 1000, (global_batch,)), jnp.int32),
        batch_sharding,
    )

    def _sync(x):
        # Fetch the value rather than block_until_ready: on this repo's
        # tunneled TPU platform, timing loops closed with
        # block_until_ready measured above-physical-peak throughput
        # (i.e. it returned before the chain finished), while a value
        # fetch of the final loss is a watertight barrier.  The fetched
        # array is a scalar, so the transfer cost is nil.
        return float(np.asarray(jax.device_get(x)))

    # AOT-compile once and run the loop through the same executable (a
    # plain step(...) call after lower().compile() would compile a second
    # time — the AOT result doesn't enter jit's dispatch cache).
    # Executed FLOPs come from XLA's own cost analysis of the compiled
    # step via obs.xprof.introspect (forward + backward + optimizer,
    # everything the chip actually runs); the analytic model cost (3 x 2
    # x 4.09 GMACs ~ 12.3 GFLOPs/img for ResNet-50@224) is lower — XLA's
    # count includes BN/padding/optimizer work — so the XLA-based MFU is
    # the honest utilization of what was scheduled, disclosed alongside.
    from horovod_tpu import obs
    from horovod_tpu.obs import xprof

    step = step.lower(params, opt_state, batch_stats, images, labels).compile()
    report = xprof.introspect(step, fn="bench_train_step")
    step_flops = report.flops or 0.0
    result["hbm_peak_bytes"] = report.peak_hbm_bytes
    # cost_analysis() describes the per-device SPMD-partitioned module,
    # which processes the LOCAL batch shard — divide by batch/chip, not the
    # global batch, or multi-chip MFU would be understated n-fold.
    flops_per_img = step_flops / args.batch_size
    state["flops_per_img"] = flops_per_img
    result["xla_flops_per_img"] = round(flops_per_img / 1e9, 2)
    # Arm the live training_mfu gauge: one measured unit below is an
    # ITERATION (num_batches_per_iter steps closed by a sync), so the
    # armed cost is the iteration's FLOPs — the gauge then tracks the
    # same number the JSON line's `mfu` reports from the median.
    peak = result["peak_bf16_tflops"]
    peak = peak * 1e12 if peak else None
    xprof.set_training_cost(
        step_flops * args.num_batches_per_iter if step_flops else None,
        peak)

    # warmup (compile + stabilize)
    for _ in range(max(args.num_warmup_batches // args.num_batches_per_iter, 1)):
        for _ in range(args.num_batches_per_iter):
            params, opt_state, batch_stats, loss = step(
                params, opt_state, batch_stats, images, labels
            )
    _sync(loss)

    loader = None
    if args.input_pipeline:
        import ml_dtypes

        from horovod_tpu.data import DataLoader

        # One epoch per timed iteration: num_batches_per_iter global
        # batches of HOST-resident data, re-fed every iteration through
        # the prefetching loader (host->HBM transfers overlap compute).
        rows = global_batch * args.num_batches_per_iter
        # float32 generation (not np.random.rand's float64): the
        # transient is 2x the bf16 epoch, not 4x — at multi-chip row
        # counts the float64 intermediate would swamp host RAM.
        host_data = {
            "images": np.random.default_rng(0).random(
                (rows, args.image_size, args.image_size, 3),
                dtype=np.float32).astype(ml_dtypes.bfloat16),
            "labels": np.random.randint(0, 1000, (rows,)).astype(np.int32),
        }
        loader = DataLoader(host_data, args.batch_size * n, shuffle=False,
                            shard=False, prefetch=2,
                            sharding=batch_sharding)

    img_secs = state["img_secs"]  # appended per iter: the budget path
    fed_img_secs = state["fed_img_secs"]  # summarizes whatever landed
    for _ in range(args.num_iters):
        t0 = time.perf_counter()
        # obs.training_step spans the iteration: observes step time in
        # the default registry and refreshes the live `training_mfu`
        # gauge from the cost armed above (a scrape during the run sees
        # the same utilization the JSON line summarizes).
        with obs.training_step("bench_iter"):
            for _ in range(args.num_batches_per_iter):
                params, opt_state, batch_stats, loss = step(
                    params, opt_state, batch_stats, images, labels
                )
            _sync(loss)
        dt = time.perf_counter() - t0
        img_secs.append(global_batch * args.num_batches_per_iter / dt / n)
        mfu_live = obs.training_metrics().mfu.value
        if mfu_live:
            result["training_mfu_live"] = round(mfu_live, 4)
        if loader is None:
            continue
        # Interleaved A/B: same chip, same minute — loader-fed variant.
        t0 = time.perf_counter()
        for batch in loader:
            params, opt_state, batch_stats, loss = step(
                params, opt_state, batch_stats,
                batch["images"], batch["labels"]
            )
        _sync(loss)
        dt = time.perf_counter() - t0
        fed_img_secs.append(
            global_batch * args.num_batches_per_iter / dt / n)

    if fed_img_secs:
        med = float(np.median(img_secs))
        fed = float(np.median(fed_img_secs))
        # Raw host->device link ceiling: the same transfers, no compute.
        # With prefetch overlapping transfer and compute, the achievable
        # rate is min(compute_bound, transfer_bound); loader EFFICIENCY
        # is measured against that ceiling so a slow physical link (e.g.
        # a tunneled dev TPU) doesn't masquerade as loader overhead.
        t0 = time.perf_counter()
        for b in range(args.num_batches_per_iter):
            s0 = b * global_batch
            jax.block_until_ready(jax.device_put(
                host_data["images"][s0:s0 + global_batch], batch_sharding))
        link_dt = time.perf_counter() - t0
        transfer_bound = global_batch * args.num_batches_per_iter / link_dt / n
        ceiling = min(med, transfer_bound)
        result["dataloader_fed_img_per_sec"] = round(fed, 2)
        result["dataloader_overhead_pct"] = round(100 * (1 - fed / med), 2)
        result["host_to_device_bound_img_per_sec"] = round(transfer_bound, 2)
        result["dataloader_efficiency_vs_ceiling_pct"] = round(
            100 * fed / ceiling, 2)
    # No print here: main()'s finally-path emits the ONE JSON line
    # whether this function returned or the budget cut it short.


if __name__ == "__main__":
    main()
