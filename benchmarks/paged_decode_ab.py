"""A/B the fused Pallas paged-decode kernel against the unfused
gather/dequant/attend path, PAIRWISE in one process.

Two engines over the same params and the same paged pool geometry —
``paged_kernel=True`` vs ``paged_kernel=False`` — run the identical
workload with reps interleaved (chip-state variance dominates
cross-process comparisons; see moe_dispatch_ab.py), timed at the
full-pool per-tick p25 like benchmarks/serving.py ``_ab_paged``.  The
output sequences are compared token-for-token: the fused kernel is only
a win if it is also EXACT (the A/B oracle contract from
tests/test_paged.py).

Bytes-moved column (analytic, from the pool geometry — both paths walk
the full table-capacity row of ``MP = ceil(max_len / page_size)``
pages per slot per layer):

* fused: each referenced K/V page is streamed into VMEM once at its
  STORED dtype (int8 pages bring their f32 per-vector scales along);
  dequant happens in-register, nothing round-trips through HBM.
* unfused: the gather materializes an HBM copy of the full logical
  window at stored dtype (pool read + copy write + copy read), and a
  quantized pool additionally materializes the dequantized copy at the
  compute dtype (write + read by the attend einsum).

So per layer, per K-or-V tensor, with ``E = S*Hkv*MP*ps*Dh`` elements:
``fused = E*stored [+ scales]`` and ``unfused = 3*E*stored [+ scales]
[+ 2*E*compute if quantized]``.  The ratio is the bandwidth headroom
the fusion buys; the measured tick latency says how much of it the
backend realizes (on the CPU interpreter the fused path is SLOWER —
the interpreter exists for correctness, the ratio column is the TPU
story).

Run (CPU smoke — tiny shapes, emits one JSON line):

    JAX_PLATFORMS=cpu python benchmarks/paged_decode_ab.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--d-ff", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--max-seq", type=int, default=64)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--steps", type=int, default=16)
    ap.add_argument("--iters", type=int, default=2)
    ap.add_argument("--kv-dtype", default=None,
                    choices=[None, "bf16", "int8"],
                    help="pool storage dtype (None = compute dtype); "
                         "int8 exercises the in-load dequant")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from horovod_tpu import serving
    from horovod_tpu.models import transformer as T
    from horovod_tpu.serving.cache import resolve_kv_dtype

    cfg = T.TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model,
        n_heads=args.n_heads, n_layers=args.n_layers, d_ff=args.d_ff,
        max_seq=args.max_seq, n_kv_heads=args.kv_heads,
        dtype=jnp.float32 if jax.devices()[0].platform == "cpu"
        else jnp.bfloat16,
        attention_impl="reference",
    )
    params = T.init_params(jax.random.PRNGKey(0), cfg)

    S = args.slots
    prompt = np.random.default_rng(5).integers(
        0, cfg.vocab_size, args.prompt_len).tolist()
    steps = max(min(args.steps, cfg.max_seq - len(prompt)), 1)

    engines = {}
    for name, fused in (("fused", True), ("unfused", False)):
        eng = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(
                n_slots=S, max_len=cfg.max_seq,
                page_size=args.page_size, kv_dtype=args.kv_dtype,
                max_queue_depth=max(2 * S, 8),
                paged_kernel=fused))
        eng.warmup([len(prompt)])
        engines[name] = (eng, [])
    assert engines["fused"][0].stats()["paged_kernel_engaged"]

    toks = {}
    for _ in range(max(args.iters, 2)):
        for name, (eng, dts) in engines.items():
            futs = [eng.submit(prompt, max_new_tokens=steps)
                    for _ in range(S)]
            while not all(f.done() for f in futs):
                full = eng.slots.active_count == S
                t0 = time.perf_counter()
                eng.step()
                dt = time.perf_counter() - t0
                if full and eng.slots.active_count == S:
                    dts.append(dt)
            toks.setdefault(name, []).extend(
                f.tokens_so_far() for f in futs)
    q = {name: float(np.percentile(dts, 25))
         for name, (_, dts) in engines.items()}
    zero_recompiles = all(
        eng.stats()["decode_compilations"] == 1
        for eng, _ in engines.values())

    # -- analytic bytes moved per decode tick (attention stage) ----------
    ps = args.page_size
    mp = -(-cfg.max_seq // ps)                   # table row width
    hkv = cfg.n_kv_heads or cfg.n_heads
    dh = cfg.d_model // cfg.n_heads
    elems = S * hkv * mp * ps * dh               # one K or V tensor
    stored = jnp.dtype(resolve_kv_dtype(cfg, args.kv_dtype)[0]).itemsize
    compute = jnp.dtype(cfg.dtype).itemsize
    quantized = args.kv_dtype == "int8"
    scales = (S * hkv * mp * ps) * 4 if quantized else 0
    fused_b = cfg.n_layers * 2 * (elems * stored + scales)
    unfused_b = cfg.n_layers * 2 * (
        3 * elems * stored + scales
        + (2 * elems * compute if quantized else 0))

    print(json.dumps({
        "platform": jax.devices()[0].platform,
        "kv_dtype": args.kv_dtype or "compute",
        "tick_s_fused_p25": round(q["fused"], 6),
        "tick_s_unfused_p25": round(q["unfused"], 6),
        "fused_tick_speedup": round(q["unfused"] / q["fused"], 3),
        "attn_bytes_per_tick_fused": fused_b,
        "attn_bytes_per_tick_unfused": unfused_b,
        "attn_bytes_ratio": round(unfused_b / fused_b, 3),
        "equal_output_tokens": toks["fused"] == toks["unfused"],
        "zero_decode_recompiles": zero_recompiles,
    }))


if __name__ == "__main__":
    main()
