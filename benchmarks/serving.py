"""Serving-path benchmark: prefill latency + autoregressive decode
throughput on the current chip.

The decode loop is ONE compiled ``lax.scan`` (``sample_decode``), so the
tunneled chip's ~10 ms per-call floor amortizes over all steps; timing
closes with a value fetch of the final tokens (axon ``block_until_ready``
returns early).  GQA rows show the KV-cache bandwidth lever
(`n_kv_heads` shrinks the cache the decode step streams every token).

    python benchmarks/serving.py [--batches 1 8 32] [--steps 128]

``--engine`` instead drives the continuous-batching engine
(horovod_tpu/serving/) with a Poisson OPEN-LOOP arrival process —
requests arrive on their own clock, not when the server is ready, the
load shape a static-batch number can't see — and reports tok/s,
p50/p99 TTFT, and mean slot occupancy next to a static-batch decode
reference at B = n_slots, PLUS the EngineConfig.overlap A/B
(steady-state decode tok/s, pipelined vs synchronous, identical
workload), the EngineConfig.paged A/B (decode tok/s and max concurrent
mixed-length requests at a fixed HBM budget, page pool vs the
slot-contiguous baseline, with kv_bytes_per_token and the page-pool
high-water mark in the JSON line) and the pipeline phase metrics
(overlap_efficiency = device-wait share of the tick,
host_syncs_per_tick):

    python benchmarks/serving.py --engine [--slots 8] [--arrival-rate 4]

plus the sampled-vs-greedy throughput A/B (per-slot vectorized
sampling is data in the same executable; the ratio is the in-tick
sort/softmax/categorical cost) and, with ``--stream``, the SSE
streaming leg: client-observed TTFB p50/p99 (first token event on the
wire) against the non-streamed server-reported TTFT:

    python benchmarks/serving.py --engine --stream

``--router N`` drives the REPLICATED front tier (docs/serving.md
"Front tier"): a ReplicaSupervisor spawns N replica processes (each a
full engine + HTTP server, seeded identically), a router proxies the
same Poisson open-loop workload over them with join-shortest-queue,
and the JSON line reports aggregate tok/s, per-replica request counts
and mean occupancy, and the router's retry/failover counters:

    python benchmarks/serving.py --router 2 [--slots 8] [--arrival-rate 4]

``--chaos`` is the DURABILITY benchmark (docs/serving.md "Durable
in-flight requests"): the same open-loop workload with deterministic
engine crashes injected mid-decode and restart-resume on — the JSON
line reports resumed-vs-restarted counts, the wasted-token ratio
(tokens re-prefilled by resumes / tokens generated), and per-request
byte-identity against the no-fault greedy oracle:

    python benchmarks/serving.py --chaos [--slots 8]

``--tp N`` is the TENSOR-PARALLEL A/B (docs/serving.md
"Tensor-parallel replicas"): a tp=N GSPMD-sharded engine vs the tp=1
single-device engine on the identical mixed greedy/sampled workload —
steady-state decode tok/s both ways, ``tp_equal_output_tokens`` (the
full per-request sequences), and ``decode_recompiles: 0`` in the JSON
line, under the existing CPU smoke clamp (forced host devices stand in
for the ICI mesh):

    python benchmarks/serving.py --tp 2 [--slots 8]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _run_engine_once(args, cfg, params, prompts, arrival, overlap):
    """One open-loop run against a fresh engine; returns the stats the
    A/B needs.  Warm covers every (prefill bucket, admission batch k)
    shape plus the decode tick, then metrics reset so the reported
    numbers describe serving latency, not JIT compile time."""
    from horovod_tpu import serving

    from horovod_tpu.obs import xprof

    engine = serving.InferenceEngine(
        params, cfg, serving.EngineConfig(
            n_slots=args.slots, max_len=cfg.max_seq,
            max_prefills_per_tick=args.max_prefills_per_tick,
            max_queue_depth=max(args.n_requests, 8), overlap=overlap,
            # achieved FLOP/s ride the snapshot in the JSON line
            model_flops_per_token=xprof.transformer_flops_per_token(
                params)))

    engine.warmup(sorted({engine._bucket(len(p)) for p in prompts}))
    warm_compiles = engine.decode_compilations
    engine.metrics = serving.ServingMetrics()

    engine.start()
    engine.stats()  # first token-rate sample for achieved FLOP/s
    occ, futs = [], []
    t0 = time.monotonic()
    for i in range(args.n_requests):
        now = time.monotonic() - t0
        if now < arrival[i]:
            time.sleep(arrival[i] - now)
        futs.append(engine.submit(prompts[i], max_new_tokens=args.steps))
        occ.append(engine.slots.occupancy)
    while not all(f.done() for f in futs):
        occ.append(engine.slots.occupancy)
        time.sleep(0.005)
    wall = time.monotonic() - t0
    engine.stop()

    # tokens_so_far never raises: with the fault-tolerance layer a
    # request can resolve with a typed error (engine restart) instead
    # of tokens — the benchmark reports that instead of crashing.
    toks = sum(len(f.tokens_so_far()) for f in futs)
    snap = engine.stats()  # superset of metrics.snapshot(): adds
    # state/heartbeat plus the achieved-FLOP/s window closed here
    # Overlap efficiency: the share of a tick's host-visible time the
    # device wait accounts for — 1.0 means every host cycle (emit,
    # retire, admission bookkeeping, dispatch) was hidden behind
    # device compute; the sync path's number is the ceiling the
    # pipeline is chasing.
    phases = [snap["tick_dispatch_seconds"]["mean"] or 0.0,
              snap["tick_device_wait_seconds"]["mean"] or 0.0,
              snap["tick_host_seconds"]["mean"] or 0.0]
    tick_wall = sum(phases)
    return {
        "engine": engine, "snap": snap, "toks": toks, "wall": wall,
        "tok_s": toks / wall if wall else 0.0,
        "occ": float(np.mean(occ)) if occ else 0.0,
        "overlap_efficiency":
            round(phases[1] / tick_wall, 4) if tick_wall else None,
        "host_syncs_per_tick": snap["host_syncs_per_tick"],
        "recompiles": engine.decode_compilations - warm_compiles,
    }


def _ab_decode(args, cfg, params):
    """The EngineConfig.overlap A/B: steady-state decode tok/s with
    the pipelined loop vs the synchronous baseline on the IDENTICAL
    workload (equal output tokens by construction).  Per-tick wall
    times are sampled at FULL slot occupancy and compared at the 25th
    percentile — on shared/noisy hosts a best-of-walls comparison
    measures scheduler luck, while a low per-tick percentile estimates
    the clean tick for both modes — with the two engines' reps
    interleaved so drift hits both equally."""
    from horovod_tpu import serving

    S = args.slots
    prompt = np.random.default_rng(1).integers(
        0, cfg.vocab_size, max(args.prompt_len // 2, 1)).tolist()
    engines = {}
    for name, ov in (("overlap", True), ("sync", False)):
        eng = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(
                n_slots=S, max_len=cfg.max_seq,
                max_prefills_per_tick=args.max_prefills_per_tick,
                max_queue_depth=max(2 * S, 8), overlap=ov))
        eng.warmup([len(prompt)])
        engines[name] = (eng, [])

    toks = {}
    # Enough full-pool ticks per rep for a stable percentile — but
    # never more than a slot admits (prompt + steps - 1 <= max_seq),
    # or submit() rightly rejects the A/B workload as too long.
    steps = max(min(max(args.steps, 24), cfg.max_seq - len(prompt) + 1), 1)
    for _ in range(max(args.iters, 4)):
        for name, (eng, dts) in engines.items():
            futs = [eng.submit(prompt, max_new_tokens=steps)
                    for _ in range(S)]
            while not all(f.done() for f in futs):
                full = eng.slots.active_count == S
                t0 = time.perf_counter()
                eng.step()
                dt = time.perf_counter() - t0
                if full and eng.slots.active_count == S:
                    dts.append(dt)  # a pure steady-state decode step
            toks.setdefault(name, []).extend(
                f.tokens_so_far() for f in futs)

    # p25, not mean/median: host noise is one-sided (a preempted tick
    # is only ever SLOWER), so a low percentile estimates the clean
    # per-tick time for both modes and the ratio stays stable on
    # shared hosts.
    q = {name: float(np.percentile(dts, 25))
         for name, (_, dts) in engines.items()}
    return {
        "decode_tok_s_overlap": round(S / q["overlap"], 2),
        "decode_tok_s_sync": round(S / q["sync"], 2),
        "overlap_decode_speedup": round(q["sync"] / q["overlap"], 3),
        "equal_output_tokens": toks["overlap"] == toks["sync"],
        "ab_steps_sampled": {n: len(d) for n, (_, d) in engines.items()},
    }


def _ab_paged(args, cfg, params):
    """The EngineConfig.paged A/B (docs/serving.md "Paged KV cache"):

    1. Steady-state decode tok/s, paged pool vs the slot-contiguous
       baseline on the IDENTICAL workload, reps interleaved and
       compared at the per-tick p25 exactly like :func:`_ab_decode`.
       The page-table gather is indirection the contiguous layout does
       not pay, so a ratio near 1.0 is the goal — the paged win is the
       byte/concurrency column, not this one.
    2. Max concurrent requests at a FIXED HBM budget of cache tokens
       (2 worst-case slots' worth): the slot-contiguous layout admits
       ``budget // max_len`` requests no matter their actual length —
       that ceiling is the layout, not a measurement — while the paged
       engine admits short mixed-length requests page by page until
       the same bytes are genuinely full.
    """
    from horovod_tpu import serving

    S = args.slots
    prompt = np.random.default_rng(3).integers(
        0, cfg.vocab_size, max(args.prompt_len // 2, 1)).tolist()
    engines = {}
    for name, paged in (("paged", True), ("unpaged", False)):
        eng = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(
                n_slots=S, max_len=cfg.max_seq,
                max_prefills_per_tick=args.max_prefills_per_tick,
                max_queue_depth=max(2 * S, 8), paged=paged))
        eng.warmup([len(prompt)])
        engines[name] = (eng, [])

    toks = {}
    steps = max(min(max(args.steps, 24), cfg.max_seq - len(prompt) + 1), 1)
    for _ in range(max(args.iters, 4)):
        for name, (eng, dts) in engines.items():
            futs = [eng.submit(prompt, max_new_tokens=steps)
                    for _ in range(S)]
            while not all(f.done() for f in futs):
                full = eng.slots.active_count == S
                t0 = time.perf_counter()
                eng.step()
                dt = time.perf_counter() - t0
                if full and eng.slots.active_count == S:
                    dts.append(dt)
            # The SEQUENCES, not counts (counts are equal by
            # construction — every future runs to max_new_tokens):
            # this is the benchmark's live token-identity check.
            toks.setdefault(name, []).extend(
                f.tokens_so_far() for f in futs)
    q = {name: float(np.percentile(dts, 25))
         for name, (_, dts) in engines.items()}

    # -- fixed-HBM-budget concurrency ------------------------------------
    ps = 16
    max_len = cfg.max_seq
    budget_tokens = 2 * max_len  # two worst-case slots' worth of bytes
    unpaged_ceiling = budget_tokens // max_len
    rng = np.random.default_rng(4)
    n_req = 2 * S
    # Short mixed-length requests (~one page each): the traffic shape
    # the contiguous layout wastes a full max_len reservation on.
    frag_prompts = [rng.integers(0, cfg.vocab_size,
                                 int(n)).tolist()
                    for n in rng.integers(max(ps // 4, 1),
                                          ps // 2 + 1, n_req)]
    eng = serving.InferenceEngine(
        params, cfg, serving.EngineConfig(
            n_slots=S, max_len=max_len, page_size=ps,
            n_pages=budget_tokens // ps, max_prefills_per_tick=S,
            max_queue_depth=n_req))
    eng.warmup(sorted({eng._bucket(len(p)) for p in frag_prompts}))
    futs = [eng.submit(p, max_new_tokens=ps // 4) for p in frag_prompts]
    peak = 0
    while not all(f.done() for f in futs):
        eng.step()
        peak = max(peak, eng.slots.active_count)
    preempted = 0
    for f in futs:
        try:
            f.result(timeout=0)
        except serving.CacheOutOfPagesError:
            preempted += 1

    # -- per-tick attention time SPLIT: gather / dequant / attend vs the
    #    fused kernel, each leg its own jitted function on one layer's
    #    full int8 pool (int8 so the dequant leg is live), scaled to a
    #    per-tick figure by n_layers.  This is the attribution column
    #    for benchmarks/paged_decode_ab.py's end-to-end A/B: when the
    #    fused ratio moves, this says WHICH leg the kernel absorbed.
    from horovod_tpu.models import transformer as T
    from horovod_tpu.ops import paged_attention as PA

    hkv = cfg.n_kv_heads or cfg.n_heads
    dh = cfg.d_model // cfg.n_heads
    mp = -(-max_len // ps)
    npage = 1 + S * mp  # page 0 = NULL
    kq, ks = T.kv_quantize(jax.random.normal(
        jax.random.PRNGKey(11), (npage, hkv, ps, dh), jnp.float32))
    vq, vs = T.kv_quantize(jax.random.normal(
        jax.random.PRNGKey(12), (npage, hkv, ps, dh), jnp.float32))
    table = jnp.asarray(
        1 + np.arange(S * mp, dtype=np.int32).reshape(S, mp))
    pos = jnp.full((S,), max_len - 1, jnp.int32)
    mask = (jax.lax.broadcasted_iota(jnp.int32, (mp * ps,), 0)[None, :]
            <= pos[:, None])
    qh = jax.random.normal(jax.random.PRNGKey(13),
                           (S, cfg.n_heads, 1, dh), cfg.dtype)

    gather = jax.jit(lambda kp, sk, vp, sv, t: (
        T._gather_pages(kp, t), T._gather_scales(sk, t),
        T._gather_pages(vp, t), T._gather_scales(sv, t)))
    dequant = jax.jit(lambda kg, sk, vg, sv: (
        T.kv_dequantize(kg, sk, cfg.dtype),
        T.kv_dequantize(vg, sv, cfg.dtype)))
    attend = jax.jit(lambda q, kd, vd: T._cache_attend(
        q, kd, vd, mask[:, None, None, :]))
    fused = jax.jit(lambda q, kp, vp, sk, sv, t, lim: PA.paged_attend(
        q.reshape(S, hkv, cfg.n_heads // hkv, dh), kp, vp, sk, sv,
        t, lim, compute_dtype=cfg.dtype)[0])

    def _best(fn, *a):
        jax.block_until_ready(fn(*a))  # compile + warm
        best = float("inf")
        for _ in range(max(args.iters, 4)):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*a))
            best = min(best, time.perf_counter() - t0)
        return best

    t_gather = _best(gather, kq, ks, vq, vs, table)
    kg, skg, vg, svg = gather(kq, ks, vq, vs, table)
    t_dequant = _best(dequant, kg, skg, vg, svg)
    kd, vd = dequant(kg, skg, vg, svg)
    t_attend = _best(attend, qh, kd, vd)
    t_fused = _best(fused, qh, kq, vq, ks, vs, table, pos + 1)
    to_tick_ms = cfg.n_layers * 1e3
    attn_split = {
        "gather_ms": round(t_gather * to_tick_ms, 4),
        "dequant_ms": round(t_dequant * to_tick_ms, 4),
        "attend_ms": round(t_attend * to_tick_ms, 4),
        "unfused_total_ms": round(
            (t_gather + t_dequant + t_attend) * to_tick_ms, 4),
        "fused_ms": round(t_fused * to_tick_ms, 4),
    }

    return {
        "attn_split_per_tick": attn_split,
        "decode_tok_s_paged": round(S / q["paged"], 2),
        "decode_tok_s_unpaged": round(S / q["unpaged"], 2),
        "paged_decode_ratio": round(q["unpaged"] / q["paged"], 3),
        "paged_equal_output_tokens": toks["paged"] == toks["unpaged"],
        "fixed_budget_tokens": budget_tokens,
        "max_concurrent_paged": peak,
        "max_concurrent_unpaged": unpaged_ceiling,
        "fixed_budget_preempted": preempted,
        "fixed_budget_pages_high_water": eng.slots.pages_high_water,
    }


def _ab_spec(args, T, cfg):
    """The EngineConfig.speculative A/B (docs/serving.md "Speculative
    decoding"): EFFECTIVE steady-state decode tok/s — tokens emitted
    per second of tick wall-clock, since a speculative tick emits
    1..K+1 tokens per slot — speculative vs the plain overlap pipeline
    on two workload shapes:

    * **repetitive** — a toy LM trained (briefly, here) on Markov-1
      cyclic sequences (next token a function of the current one,
      period 8) decoding cyclic prompts: continuations genuinely
      repeat, so the n-gram prompt-lookup draft agrees and acceptance
      approaches 1.  This is the shape speculation exists for.
    * **adversarial** — a RANDOM-INIT target decoding random prompts
      at the same completion length: its greedy streams are acyclic,
      so bigrams never recur, drafts never agree, and every
      steady-state tick pays the W-position verify for one token.
      The ratio here is the bounded overhead of losing.

    The target model is TRAINED (not the random-init params the other
    A/Bs share) because speculative throughput is a property of output
    predictability — a random model's stream gives the draft nothing
    to agree with, and the A/B would measure only overhead.  Both
    engines decode the identical workload; equal output sequences are
    asserted, not assumed.  With ``--spec-draft model`` the draft is a
    half-depth TransformerConfig sharing the tokenizer, trained on the
    same corpus (two-model config; the CPU smoke clamp sizes both)."""
    import optax

    from horovod_tpu import serving

    S = args.slots
    K = args.spec_k
    V = cfg.vocab_size
    period = 8
    rng = np.random.default_rng(5)

    def train(model_cfg, seed, steps=45):
        p = T.init_params(jax.random.PRNGKey(seed), model_cfg)
        opt = optax.adam(1e-2)
        ost = opt.init(p)

        def batch(n=32, s=48):
            block = rng.integers(0, V // period, n)
            phase = rng.integers(0, period, n)
            toks = (block[:, None] * period
                    + (phase[:, None] + np.arange(s)[None, :]) % period)
            nxt = (block[:, None] * period
                   + (phase[:, None] + 1 + np.arange(s)[None, :]) % period)
            return {"tokens": jnp.asarray(toks, jnp.int32),
                    "targets": jnp.asarray(nxt, jnp.int32)}

        @jax.jit
        def step(p, o, b):
            l, g = jax.value_and_grad(T.loss_fn)(p, b, model_cfg)
            u, o = opt.update(g, o, p)
            return optax.apply_updates(p, u), o, l

        for _ in range(steps):
            p, ost, loss = step(p, ost, batch())
        return p, float(loss)

    params, loss = train(cfg, seed=11)
    draft = (None, None)
    if args.spec_draft == "model":
        dcfg = dataclasses.replace(cfg, n_layers=max(1, cfg.n_layers // 2))
        dparams, _ = train(dcfg, seed=12)
        draft = (dparams, dcfg)

    def make(model_params, spec):
        eng = serving.InferenceEngine(
            model_params, cfg, serving.EngineConfig(
                n_slots=S, max_len=cfg.max_seq,
                max_prefills_per_tick=args.max_prefills_per_tick,
                max_queue_depth=max(4 * S, 16), speculative=spec,
                spec_k=K, spec_draft=args.spec_draft if spec else "auto"),
            draft_params=draft[0] if spec else None,
            draft_cfg=draft[1] if spec else None)
        eng.warmup([12])
        return eng

    def measure(engines, prompts, steps, reps):
        # Effective tok/s over FULL-OCCUPANCY ticks only (the
        # _ab_decode discipline): admission/drain ticks measure
        # scheduling, not the speculative multiplier, and on shared
        # hosts they dominate the noise.  Tokens and wall are summed
        # per tick because a speculative tick emits a variable count.
        # Rep 0 is WARM (unmeasured, both engines): it absorbs the
        # adaptive controller's first evaluation window — a one-time
        # adaptation cost, not the steady state the ratio describes —
        # plus any residual compile/cache warmth, symmetrically.
        stats = {n: [0, 0.0, []] for n in engines}
        for rep in range(reps + 1):
            for name, eng in engines.items():  # interleaved reps
                futs = [eng.submit(p, max_new_tokens=steps)
                        for p in prompts]
                while not all(f.done() for f in futs):
                    full = eng.slots.active_count == S
                    before = eng.metrics.tokens_generated.value
                    t0 = time.perf_counter()
                    eng.step()
                    dt = time.perf_counter() - t0
                    if full and rep:
                        stats[name][0] += (
                            eng.metrics.tokens_generated.value - before)
                        stats[name][1] += dt
                stats[name][2].extend(f.tokens_so_far() for f in futs)
        return {n: (v[0] / v[1] if v[1] else 0.0, v[2])
                for n, v in stats.items()}

    steps = max(min(args.steps * 2, cfg.max_seq - 13), 16)
    reps = max(args.iters, 3)
    engines = {"spec": make(params, True), "plain": make(params, False)}
    rep_prompts = [((b % (V // period)) * period
                    + (np.arange(12) % period)).tolist() for b in range(S)]
    rep = measure(engines, rep_prompts, steps, reps)
    spec_eng = engines["spec"]
    drafted = spec_eng.metrics.spec_drafted.value
    acc_rate = (spec_eng.metrics.spec_accepted.value / drafted
                if drafted else None)
    tpt = spec_eng.metrics.tokens_per_tick
    # Adversarial: a random-init target's greedy streams are acyclic —
    # the drafts have nothing to agree with at FULL completion length,
    # so this measures steady-state decode paying the verify for
    # nothing (the draft model, if any, is equally useless here: it
    # was trained on the cyclic corpus the random target ignores).
    rnd_params = T.init_params(jax.random.PRNGKey(13), cfg)
    adv_engines = {"spec": make(rnd_params, True),
                   "plain": make(rnd_params, False)}
    adv_prompts = [rng.integers(0, V, 12).tolist() for _ in range(S)]
    adv = measure(adv_engines, adv_prompts, steps, reps)
    adv_drafted = adv_engines["spec"].metrics.spec_drafted.value
    adv_acc = (adv_engines["spec"].metrics.spec_accepted.value
               / adv_drafted if adv_drafted else None)
    equal = (rep["spec"][1] == rep["plain"][1]
             and adv["spec"][1] == adv["plain"][1])
    # ASSERTED, not just recorded: a speedup over diverging output is
    # not a speedup, and an identity regression must fail the
    # benchmark loudly rather than ride a JSON field nobody reads.
    assert equal, "speculative output diverged from plain greedy"
    return {
        "spec_k": K,
        "spec_draft": args.spec_draft,
        "spec_train_loss": round(loss, 5),
        "spec_decode_tok_s_repetitive": round(rep["spec"][0], 2),
        "plain_decode_tok_s_repetitive": round(rep["plain"][0], 2),
        "spec_repetitive_speedup":
            round(rep["spec"][0] / rep["plain"][0], 3)
            if rep["plain"][0] else None,
        "spec_decode_tok_s_adversarial": round(adv["spec"][0], 2),
        "plain_decode_tok_s_adversarial": round(adv["plain"][0], 2),
        "spec_adversarial_ratio":
            round(adv["spec"][0] / adv["plain"][0], 3)
            if adv["plain"][0] else None,
        "spec_acceptance_rate":
            round(acc_rate, 4) if acc_rate is not None else None,
        "spec_acceptance_rate_adversarial":
            round(adv_acc, 4) if adv_acc is not None else None,
        "spec_tokens_per_tick_mean": tpt.mean(),
        "spec_tokens_per_tick_p50": tpt.percentile(0.50),
        "spec_tokens_per_tick_p95": tpt.percentile(0.95),
        "spec_equal_output_tokens": equal,
        "spec_decode_compilations": spec_eng.decode_compilations,
    }


def _tp_mode(args, T) -> None:
    """The ``--tp N`` A/B leg (docs/serving.md "Tensor-parallel
    replicas"): steady-state decode tok/s of a tp=N GSPMD-sharded
    engine vs the tp=1 single-device engine on the IDENTICAL workload
    — reps interleaved, per-tick walls compared at the p25 exactly
    like the overlap A/B — with the benchmark's live token-identity
    check (``tp_equal_output_tokens``: the full per-request SEQUENCES,
    greedy and sampled rows both) and the zero-recompile guard in the
    JSON line.  On a single CPU host the tp engine pays real psum/
    all-gather collectives between forced host devices for no real
    memory win, so the ratio is the COORDINATION OVERHEAD floor, not a
    speedup — the tp win on hardware is serving a model whose params +
    KV do not fit one chip at all."""
    import dataclasses as _dc

    from horovod_tpu import serving

    if len(jax.devices()) < args.tp:
        print(json.dumps({
            "benchmark": "serving_tp", "skipped": True,
            "reason": f"{len(jax.devices())} devices < tp={args.tp} "
                      f"(set XLA_FLAGS="
                      f"--xla_force_host_platform_device_count="
                      f"{args.tp} before backend init)"}))
        return

    dtype = jnp.float32 if jax.devices()[0].platform == "cpu" \
        else jnp.bfloat16
    cfg = T.TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model,
        n_heads=args.n_heads, n_layers=args.n_layers, d_ff=args.d_ff,
        max_seq=args.prompt_len + args.steps,
        n_kv_heads=args.kv_heads[-1] if args.kv_heads else 0,
        attention_impl="reference", dtype=dtype)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    S = args.slots
    prompt = np.random.default_rng(7).integers(
        0, cfg.vocab_size, max(args.prompt_len // 2, 1)).tolist()
    engines = {}
    warm_compiles = {}
    for name, tp in (("tp", args.tp), ("tp1", 1)):
        eng = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(
                n_slots=S, max_len=cfg.max_seq, tp=tp,
                max_prefills_per_tick=args.max_prefills_per_tick,
                max_queue_depth=max(2 * S, 8)))
        eng.warmup([len(prompt)])
        warm_compiles[name] = eng.decode_compilations
        engines[name] = (eng, [])

    toks = {}
    steps = max(min(max(args.steps, 24),
                    cfg.max_seq - len(prompt) + 1), 1)
    for rep in range(max(args.iters, 4)):
        for name, (eng, dts) in engines.items():
            # Half the slots sampled: the A/B's identity check covers
            # the sampled rows' key schedule under the sharded tick.
            futs = [eng.submit(prompt, max_new_tokens=steps,
                               temperature=0.9 if i % 2 else 0.0,
                               seed=i)
                    for i in range(S)]
            while not all(f.done() for f in futs):
                full = eng.slots.active_count == S
                t0 = time.perf_counter()
                eng.step()
                dt = time.perf_counter() - t0
                if full and eng.slots.active_count == S:
                    dts.append(dt)
            toks.setdefault(name, []).extend(
                f.tokens_so_far() for f in futs)
    q = {name: float(np.percentile(dts, 25))
         for name, (_, dts) in engines.items()}
    recompiles = {name: eng.decode_compilations - warm_compiles[name]
                  for name, (eng, _) in engines.items()}
    result = {
        "benchmark": "serving_tp",
        "chip": jax.devices()[0].device_kind,
        "tp": args.tp,
        "mesh": engines["tp"][0].stats()["mesh"],
        "model": _dc.asdict(cfg) | {"dtype": jnp.dtype(dtype).name},
        "slots": S,
        "steps_per_request": steps,
        "decode_tok_s_tp": round(S / q["tp"], 2),
        "decode_tok_s_tp1": round(S / q["tp1"], 2),
        "tp_decode_ratio": round(q["tp1"] / q["tp"], 3),
        "tp_equal_output_tokens": toks["tp"] == toks["tp1"],
        "decode_recompiles": recompiles["tp"],
        "decode_recompiles_tp1": recompiles["tp1"],
        "ab_steps_sampled": {n: len(d)
                             for n, (_, d) in engines.items()},
    }
    print(json.dumps(result))


def _ab_tracing(args, cfg, params):
    """The tracing-overhead A/B (docs/observability.md): steady-state
    decode tok/s with request tracing ENABLED vs DISABLED, identical
    overlapped-pipeline workload, reps interleaved and compared at the
    per-tick p25 exactly like :func:`_ab_decode`.  The disabled run IS
    the instrumented engine with no tracer attached — the cost of the
    hooks themselves (one global read per site) — so
    ``tracing_overhead_ratio`` near 1.0 demonstrates the off-by-default
    path is free, and the enabled ratio is the price of a full trace
    (bounds guarded by the perf-marked test in tests/test_obs.py:
    <=2% disabled, <=5% enabled).

    Extended to the SPAN layer (ISSUE 12): a third leg runs with a
    :class:`~horovod_tpu.obs.tracing.SpanRecorder` active under the
    DEFAULT tail-sampling policy — steady-state clean traffic buffers
    tick tuples and then tail-DROPS them at retirement (start/finish
    records only hit the stream), which is the deployed configuration
    — reporting ``span_tracing_overhead_ratio`` plus the
    retained-vs-dropped trace counts."""
    import tempfile

    from horovod_tpu import serving
    from horovod_tpu.obs import tracing as obs_tracing

    S = args.slots
    prompt = np.random.default_rng(2).integers(
        0, cfg.vocab_size, max(args.prompt_len // 2, 1)).tolist()

    tracer = obs_tracing.get()
    own_path = None
    if tracer is None:
        fd, own_path = tempfile.mkstemp(prefix="hvd_trace_ab_",
                                        suffix=".json")
        os.close(fd)
        tracer = obs_tracing.start(own_path)
    obs_tracing.deactivate()
    sfd, span_path = tempfile.mkstemp(prefix="hvd_span_ab_",
                                      suffix=".jsonl")
    os.close(sfd)
    prev_spans = None
    srec = None

    engines = {}
    try:
        # Inside the try so a constructor failure (unwritable tmp,
        # disk full) still restores the process's active recorder.
        prev_spans = obs_tracing.deactivate_spans()
        srec = obs_tracing.SpanRecorder(span_path, proc="bench",
                                        role="replica")
        for name in ("notracing", "tracing", "spans"):
            eng = serving.InferenceEngine(
                params, cfg, serving.EngineConfig(
                    n_slots=S, max_len=cfg.max_seq,
                    max_prefills_per_tick=args.max_prefills_per_tick,
                    max_queue_depth=max(2 * S, 8), overlap=True))
            eng.warmup([len(prompt)])
            engines[name] = (eng, [])

        steps = max(min(max(args.steps, 24),
                        cfg.max_seq - len(prompt) + 1), 1)
        for _ in range(max(args.iters, 4)):
            for name, (eng, dts) in engines.items():
                obs_tracing.activate(tracer if name == "tracing" else None)
                obs_tracing.activate_spans(srec if name == "spans"
                                           else None)
                futs = [eng.submit(prompt, max_new_tokens=steps)
                        for _ in range(S)]
                while not all(f.done() for f in futs):
                    full = eng.slots.active_count == S
                    t0 = time.perf_counter()
                    eng.step()
                    dt = time.perf_counter() - t0
                    if full and eng.slots.active_count == S:
                        dts.append(dt)
                obs_tracing.deactivate()
                obs_tracing.deactivate_spans()
    finally:
        obs_tracing.activate(tracer)
        obs_tracing.activate_spans(prev_spans)
        if srec is not None:
            srec.close()
        os.unlink(span_path)
        if own_path is not None:
            obs_tracing.stop()
            os.unlink(own_path)

    q = {name: float(np.percentile(dts, 25))
         for name, (_, dts) in engines.items()}
    return {
        "decode_tok_s_tracing": round(S / q["tracing"], 2),
        "decode_tok_s_notracing": round(S / q["notracing"], 2),
        "decode_tok_s_spans": round(S / q["spans"], 2),
        "tracing_overhead_ratio": round(q["tracing"] / q["notracing"], 4),
        "span_tracing_overhead_ratio": round(
            q["spans"] / q["notracing"], 4),
        "span_traces_retained": srec.n_retained,
        "span_traces_dropped": srec.n_dropped,
    }


def _ab_sampled(args, cfg, params):
    """Sampled-vs-greedy throughput A/B: per-slot sampling rides the
    SAME compiled tick as parameter columns, so the only cost is the
    in-tick sort/softmax/categorical — this measures it (same
    interleaved-rep p25 idiom as the overlap A/B), and asserts the
    zero-recompile property across the whole mix."""
    from horovod_tpu import serving

    S = args.slots
    prompt = np.random.default_rng(2).integers(
        0, cfg.vocab_size, max(args.prompt_len // 2, 1)).tolist()
    eng = serving.InferenceEngine(
        params, cfg, serving.EngineConfig(
            n_slots=S, max_len=cfg.max_seq,
            max_prefills_per_tick=args.max_prefills_per_tick,
            max_queue_depth=max(2 * S, 8)))
    eng.warmup([len(prompt)])
    base_compiles = eng.decode_compilations
    steps = max(min(max(args.steps, 24), cfg.max_seq - len(prompt) + 1), 1)
    dts = {"greedy": [], "sampled": []}
    for _ in range(max(args.iters, 4)):
        for name, kw in (("greedy", {}),
                         ("sampled", dict(temperature=1.0, top_k=16,
                                          top_p=0.9))):
            futs = [eng.submit(prompt, max_new_tokens=steps, seed=i,
                               **kw) for i in range(S)]
            while not all(f.done() for f in futs):
                full = eng.slots.active_count == S
                t0 = time.perf_counter()
                eng.step()
                dt = time.perf_counter() - t0
                if full and eng.slots.active_count == S:
                    dts[name].append(dt)
    q = {n: float(np.percentile(d, 25)) for n, d in dts.items()}
    return {
        "decode_tok_s_greedy": round(S / q["greedy"], 2),
        "decode_tok_s_sampled": round(S / q["sampled"], 2),
        "sampled_vs_greedy_ratio": round(q["greedy"] / q["sampled"], 3),
        "sampling_recompiles": eng.decode_compilations - base_compiles,
    }


def _ab_stream(args, cfg, params):
    """The streaming-transport leg (``--stream``): client-observed
    TTFB — request start to the FIRST SSE token event on the wire —
    p50/p99 against the non-streamed server-reported TTFT on the same
    closed-loop HTTP workload.  Streaming exists to close the gap
    between 'first token computed' and 'first byte a user sees'; this
    reports both ends of it."""
    import http.client

    from horovod_tpu import serving
    from horovod_tpu.serving import sse

    eng = serving.InferenceEngine(
        params, cfg, serving.EngineConfig(
            n_slots=args.slots, max_len=cfg.max_seq,
            max_prefills_per_tick=args.max_prefills_per_tick,
            max_queue_depth=max(args.n_requests, 8)))
    prompt = np.random.default_rng(3).integers(
        0, cfg.vocab_size, max(args.prompt_len // 2, 1)).tolist()
    eng.warmup([len(prompt)])
    srv = serving.ServingServer(eng, port=0).start()
    host, port = srv.address
    steps = max(min(args.steps, cfg.max_seq - len(prompt) + 1), 1)
    n = max(min(args.n_requests, 16), 8)

    def post(body):
        c = http.client.HTTPConnection(host, port, timeout=60)
        c.request("POST", "/generate", body=json.dumps(body).encode())
        return c, c.getresponse()

    ttft_ms, ttfb_ms, toks = [], [], {}
    try:
        for i in range(n):
            c, r = post({"tokens": prompt, "max_new_tokens": steps,
                         "temperature": 1.0, "seed": i})
            resp = json.loads(r.read())
            c.close()
            ttft_ms.append(resp["ttft_ms"])
            toks.setdefault("plain", []).append(resp["tokens"])
        for i in range(n):
            t0 = time.perf_counter()
            c, r = post({"tokens": prompt, "max_new_tokens": steps,
                         "temperature": 1.0, "seed": i,
                         "stream": True})
            if r.status != 200:
                raise RuntimeError(
                    f"stream request {i} rejected: {r.status} "
                    f"{r.read()!r}")
            parser = sse.SSEParser()
            events = []
            while not any(k == "token" for k, _ in events):
                data = r.read1(256)
                if not data:  # error stream / EOF before any token
                    raise RuntimeError(
                        f"stream {i} ended without a token event: "
                        f"{events}")
                events.extend(parser.feed(data))
            ttfb_ms.append((time.perf_counter() - t0) * 1e3)
            while True:
                data = r.read1(4096)
                if not data:
                    break
                events.extend(parser.feed(data))
            c.close()
            toks.setdefault("stream", []).append(
                [p["token"] for k, p in events if k == "token"])
    finally:
        srv.stop(drain_timeout=10)
    snap = eng.metrics.streamed_ttfb.snapshot()
    return {
        "stream_ttfb_ms_p50": round(float(np.percentile(ttfb_ms, 50)), 3),
        "stream_ttfb_ms_p99": round(float(np.percentile(ttfb_ms, 99)), 3),
        "nonstream_ttft_ms_p50":
            round(float(np.percentile(ttft_ms, 50)), 3),
        "nonstream_ttft_ms_p99":
            round(float(np.percentile(ttft_ms, 99)), 3),
        # server-side first-event histogram (arrival -> wire)
        "stream_ttfb_server_mean_s": snap["mean"],
        "stream_equal_output_tokens": toks["plain"] == toks["stream"],
        "streamed_tokens": eng.metrics.streamed_tokens.value,
    }


def _router_mode(args, cfg) -> None:
    """Open-loop benchmark through the replicated front tier: N
    replica PROCESSES behind the join-shortest-queue router, the same
    Poisson arrivals as ``--engine`` — aggregate tok/s plus
    per-replica occupancy/request spread in the JSON line.  Replicas
    init from the same seed (replica_main), so the answers are
    byte-identical no matter which replica serves them."""
    import json as _json
    import threading
    import urllib.error
    import urllib.request

    from horovod_tpu.serving.router import (
        ReplicaRegistry,
        ReplicaSpec,
        ReplicaSupervisor,
        RouterServer,
    )

    rng = np.random.default_rng(0)
    lengths = rng.integers(max(args.prompt_len // 2, 1),
                           args.prompt_len + 1, args.n_requests)
    prompts = [rng.integers(0, cfg.vocab_size, int(n)).tolist()
               for n in lengths]
    arrival = np.cumsum(rng.exponential(1.0 / args.arrival_rate,
                                        args.n_requests))

    spec = ReplicaSpec(
        seed=0, vocab=cfg.vocab_size, d_model=cfg.d_model,
        n_heads=cfg.n_heads, n_layers=cfg.n_layers, d_ff=cfg.d_ff,
        max_seq=cfg.max_seq, n_kv_heads=cfg.n_kv_heads or 0,
        slots=args.slots,
        max_prefills_per_tick=args.max_prefills_per_tick,
        max_queue_depth=max(args.n_requests, 8),
        warm=(max(args.prompt_len // 2, 1), args.prompt_len))
    registry = ReplicaRegistry(poll_interval=0.2)
    sup = ReplicaSupervisor(spec, args.router, registry=registry)
    rt = RouterServer(registry, port=0)
    try:
        sup.start()
        rt.start()
        if not sup.wait_ready(timeout=600):
            raise RuntimeError("replicas never became ready")
        host, port = rt.address
        base = f"http://{host}:{port}"

        results = {}
        occ_samples: dict = {}
        done = threading.Event()

        def occ_sampler():
            while not done.is_set():
                for s in registry.statuses():
                    occ_samples.setdefault(s.endpoint.rid,
                                           []).append(s.occupancy)
                time.sleep(0.05)

        def client(i):
            req = urllib.request.Request(
                base + "/generate",
                data=_json.dumps({
                    "tokens": prompts[i],
                    "max_new_tokens": args.steps}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=300) as r:
                    results[i] = (r.status, _json.loads(r.read()),
                                  r.headers.get("X-Router-Replica"))
            except urllib.error.HTTPError as e:
                results[i] = (e.code, _json.loads(e.read()), None)
            except Exception as e:
                # Transport-level failure: a DROPPED request.  It must
                # show in the accounting — the front tier's whole claim
                # is that this number stays 0.
                results[i] = (None, {"type": repr(e)}, None)

        sampler = threading.Thread(target=occ_sampler, daemon=True)
        sampler.start()
        threads = []
        t0 = time.monotonic()
        for i in range(args.n_requests):
            now = time.monotonic() - t0
            if now < arrival[i]:
                time.sleep(arrival[i] - now)
            th = threading.Thread(target=client, args=(i,))
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        wall = time.monotonic() - t0
        done.set()
        sampler.join(1.0)

        toks = sum(len(r[1].get("tokens", []))
                   for r in results.values())
        per_replica_req: dict = {}
        for code, _, rid in results.values():
            if rid is not None:
                per_replica_req[rid] = per_replica_req.get(rid, 0) + 1
        stats = rt.stats()
        result = {
            "metric": f"router open-loop tok/s ({args.router} replicas "
                      f"x S={args.slots} slots, {args.arrival_rate}/s "
                      f"Poisson, {args.n_requests} reqs x "
                      f"{args.steps} toks)",
            "value": round(toks / wall, 2) if wall else 0.0,
            "unit": "tok/s",
            "replicas": args.router,
            "requests": args.n_requests,
            "completed_with_tokens": sum(
                1 for c, _, _ in results.values() if c == 200),
            "typed_errors": sum(
                1 for c, _, _ in results.values()
                if c is not None and c != 200),
            "dropped": args.n_requests - sum(
                1 for c, _, _ in results.values() if c is not None),
            "per_replica_requests": per_replica_req,
            "per_replica_occupancy": {
                rid: round(float(np.mean(v)), 3)
                for rid, v in sorted(occ_samples.items())},
            "router_retries": stats["retries"],
            "router_failovers": stats["failovers"],
            "router_replica_restarts": stats["replica_restarts"],
            "proxy_latency_p50_s":
                stats["proxy_latency_seconds"]["p50"],
            "chip": jax.devices()[0].device_kind,
            "registry": registry.metrics.registry.snapshot(),
        }
        print(f"router   {args.router} replicas {result['value']:9.1f} "
              f"tok/s aggregate | spread {per_replica_req} | "
              f"retries {stats['retries']:.0f}")
        print(json.dumps(result))
    finally:
        rt.stop()
        sup.stop(drain=False)


def _rollout_mode(args, cfg) -> None:
    """Zero-downtime reconfiguration benchmark (``--rollout``): the
    candidate config comes out of ``tuning.replay.tune()`` (offline BO
    over replay runs of a synthetic trace — the full tuned-settings
    path docs/serving.md's rollout runbook deploys), then a 3-replica
    fleet behind the router serves a continuous closed-loop load while
    that candidate is rolled out replica-by-replica through the canary
    gate to full promotion.  The JSON line reports the tuned candidate,
    the canary/incumbent scores, the per-step durations, the rollback
    count (the claim is 0) and the number of rollout-attributable 5xx
    responses (the claim is 0: capacity never drops below N-1 and
    drains run to completion)."""
    import json as _json
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    from horovod_tpu import serving
    from horovod_tpu.models import transformer as T
    from horovod_tpu.serving.router import (
        ReplicaRegistry,
        ReplicaSpec,
        ReplicaSupervisor,
        RolloutController,
        RouterServer,
    )
    from horovod_tpu.tuning.replay import TraceRequest, tune, warm_lens

    n = args.router if args.router > 1 else 3
    rng = np.random.default_rng(0)
    lengths = rng.integers(max(args.prompt_len // 2, 1),
                           args.prompt_len + 1, 64)
    prompts = [rng.integers(0, cfg.vocab_size, int(m)).tolist()
               for m in lengths]

    # --- source the candidate from tuning.replay.tune() -------------
    # Offline BO over replay runs of a synthetic trace: one fresh
    # warmed engine per sample, constructor knobs in scope.  The
    # winner's ``settings`` dict is POSTed to /rollout verbatim — the
    # tuned-config deployment path end to end.
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    trace = [TraceRequest(
        id=i,
        prompt=tuple(int(t) for t in rng.integers(
            0, cfg.vocab_size,
            int(lengths[i % len(lengths)]))),
        max_new_tokens=args.steps) for i in range(8)]

    def build(settings):
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(
                n_slots=args.slots, max_len=cfg.max_seq,
                tick_timeout=0.0, **settings))
        engine.warmup(warm_lens(trace, engine))
        return engine

    tuned = tune(build, trace,
                 bounds={"max_prefills_per_tick": (1, 4)},
                 samples=2, seed=0)
    candidate = dict(tuned["best"]["settings"])
    print(f"replay-tuned candidate: {candidate} "
          f"(score {tuned['best']['score']})")

    spec = ReplicaSpec(
        seed=0, vocab=cfg.vocab_size, d_model=cfg.d_model,
        n_heads=cfg.n_heads, n_layers=cfg.n_layers, d_ff=cfg.d_ff,
        max_seq=cfg.max_seq, n_kv_heads=cfg.n_kv_heads or 0,
        slots=args.slots,
        max_prefills_per_tick=args.max_prefills_per_tick,
        max_queue_depth=64,
        warm=(max(args.prompt_len // 2, 1), args.prompt_len))
    registry = ReplicaRegistry(poll_interval=0.2)
    journal_dir = tempfile.mkdtemp(prefix="bench_rollout_")
    sup = ReplicaSupervisor(spec, n, registry=registry,
                            journal_dir=journal_dir)
    ctl = RolloutController(sup, canary_weight=0.3, canary_windows=2,
                            window_s=0.5, ready_timeout=600.0)
    rt = RouterServer(registry, port=0, rollout=ctl)
    counts = {"200": 0, "5xx": 0, "other": 0, "dropped": 0}
    counts_lock = threading.Lock()
    stop = threading.Event()

    def loader(worker):
        lrng = np.random.default_rng(worker)
        while not stop.is_set():
            prompt = prompts[int(lrng.integers(0, len(prompts)))]
            req = urllib.request.Request(
                base + "/generate",
                data=_json.dumps({
                    "tokens": prompt,
                    "max_new_tokens": args.steps}).encode(),
                headers={"Content-Type": "application/json"})
            key = "dropped"
            try:
                with urllib.request.urlopen(req, timeout=300) as r:
                    key = "200" if r.status == 200 else "other"
                    r.read()
            except urllib.error.HTTPError as e:
                key = "5xx" if e.code >= 500 else "other"
                e.read()
            except Exception:
                pass
            with counts_lock:
                counts[key] += 1

    try:
        sup.start()
        rt.start()
        if not sup.wait_ready(timeout=600):
            raise RuntimeError("replicas never became ready")
        host, port = rt.address
        base = f"http://{host}:{port}"

        workers = [threading.Thread(target=loader, args=(w,),
                                    daemon=True) for w in range(4)]
        for th in workers:
            th.start()
        time.sleep(1.0)  # pre-rollout traffic baseline

        req = urllib.request.Request(
            base + "/rollout",
            data=_json.dumps({"candidate": candidate}).encode(),
            headers={"Content-Type": "application/json"})
        t0 = time.monotonic()
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 202, r.status
            r.read()
        if not ctl.wait(timeout=600):
            raise RuntimeError("rollout never reached a terminal state")
        wall = time.monotonic() - t0
        time.sleep(1.0)  # post-rollout traffic on the new config
        stop.set()
        for th in workers:
            th.join(300.0)

        status = ctl.status()
        gens = {}
        for st in registry.statuses():
            with urllib.request.urlopen(st.endpoint.base_url + "/stats",
                                        timeout=5.0) as r:
                gens[st.endpoint.rid] = _json.loads(r.read()).get(
                    "config_generation")
        snap = registry.metrics.snapshot()
        result = {
            "metric": f"fleet rollout wall-clock ({n} replicas x "
                      f"S={args.slots} slots, candidate {candidate}, "
                      f"continuous closed-loop load)",
            "value": round(wall, 2),
            "unit": "s",
            "replicas": n,
            "candidate": candidate,
            "tune_trajectory": tuned["trajectory"],
            "terminal_state": status["state"],
            "trip_reason": status["trip_reason"],
            "canary_score": status["canary_score"],
            "incumbent_score": status["incumbent_score"],
            "step_durations_s": status["step_durations_s"],
            "rollbacks": int(snap["rollout_rollbacks"]),
            "promotions": int(snap["rollout_promotions"]),
            "rollout_steps": int(snap["rollout_steps"]),
            "requests_200": counts["200"],
            "http_5xx": counts["5xx"],
            "dropped": counts["dropped"],
            "config_generations": gens,
            "chip": jax.devices()[0].device_kind,
        }
        print(f"rollout  {n} replicas promoted in {wall:6.1f}s | "
              f"canary {status['canary_score']} vs incumbent "
              f"{status['incumbent_score']} | "
              f"5xx {counts['5xx']} | rollbacks "
              f"{int(snap['rollout_rollbacks'])}")
        print(json.dumps(result))
    finally:
        stop.set()
        rt.stop()
        sup.stop(drain=False)


def _chaos_mode(args, T, cfg, params) -> None:
    """Durability benchmark (``--chaos``): the open-loop workload with
    deterministic engine crashes injected mid-decode, restart-resume
    ON (the default).  Reports resumed-vs-restarted counts, the
    wasted-token ratio (tokens re-prefilled by resumes / tokens
    generated), and per-request oracle identity — the honest price
    and proof of durability under faults."""
    from horovod_tpu import serving

    rng = np.random.default_rng(0)
    lengths = rng.integers(max(args.prompt_len // 2, 1),
                           args.prompt_len + 1, args.n_requests)
    prompts = [rng.integers(0, cfg.vocab_size, n).tolist()
               for n in lengths]
    arrival = np.cumsum(rng.exponential(1.0 / args.arrival_rate,
                                        args.n_requests))

    inj = serving.FaultInjector(seed=0)
    engine = serving.InferenceEngine(
        params, cfg, serving.EngineConfig(
            n_slots=args.slots, max_len=cfg.max_seq,
            max_prefills_per_tick=args.max_prefills_per_tick,
            max_queue_depth=max(args.n_requests, 8),
            max_restarts=1000, restart_backoff=0.01,
            restart_backoff_max=0.05, faults=inj))
    # Warm the prompt buckets AND the resume buckets (prompt + emitted
    # can reach prompt_len + steps): a resumed re-admission must not
    # pay XLA compilation mid-benchmark.
    cap = engine.slots.max_len - 2
    warm = sorted({min(n, cap) for p in prompts
                   for n in (len(p), len(p) + args.steps)})
    engine.warmup(warm)
    # One crash roughly every ``steps`` decode ticks, spread across the
    # run — each one forces a restart with in-flight requests to
    # resume.
    base = inj.visits("decode_tick")
    n_faults = 4
    for i in range(n_faults):
        inj.add(serving.FaultSpec(
            site="decode_tick", kind="raise",
            skip=base + 5 + i * max(args.steps, 8)))

    engine.start()
    futs = []
    t0 = time.monotonic()
    for i in range(args.n_requests):
        now = time.monotonic() - t0
        if now < arrival[i]:
            time.sleep(arrival[i] - now)
        futs.append(engine.submit(prompts[i], max_new_tokens=args.steps))
    while not all(f.done() for f in futs):
        time.sleep(0.005)
    wall = time.monotonic() - t0
    engine.stop()

    # Byte-identity against the no-fault greedy oracle, per request.
    ok = typed = mismatched = 0
    for p, f in zip(prompts, futs):
        try:
            out = f.result(timeout=0)
        except serving.ServingError:
            typed += 1
            continue
        ref = np.asarray(T.greedy_decode(
            params, jnp.asarray([p], jnp.int32), args.steps,
            cfg))[0].tolist()
        if out == ref:
            ok += 1
        else:
            mismatched += 1

    snap = engine.stats()
    toks = snap["tokens_generated"]
    wasted = snap["resume_wasted_tokens"]
    result = {
        "metric": f"chaos durability: wasted-token ratio under "
                  f"{n_faults} injected crashes "
                  f"(S={args.slots}, {args.n_requests} reqs x "
                  f"{args.steps} toks, restart-resume on)",
        "value": round(wasted / toks, 4) if toks else None,
        "unit": "re-prefilled/generated",
        "requests_resumed": snap["requests_resumed"],
        "engine_restarts": snap["engine_restarts"],
        "engine_failures": snap["engine_failures"],
        "requests_oracle_identical": ok,
        "requests_typed_error": typed,
        "requests_mismatched": mismatched,
        "resume_wasted_tokens": wasted,
        "tokens_generated": toks,
        "wall_s": round(wall, 3),
        "faults_fired": [list(f) for f in inj.fired],
        "journal_inflight": snap["journal_inflight"],
        "decode_compilations": snap["decode_compilations"],
        "chip": jax.devices()[0].device_kind,
    }
    print(f"chaos    {snap['requests_resumed']:.0f} resumed across "
          f"{snap['engine_restarts']:.0f} restarts | "
          f"{ok}/{len(futs)} oracle-identical ({typed} typed, "
          f"{mismatched} mismatched) | wasted-token ratio "
          f"{result['value']}")
    print(json.dumps(result))


def _slo_mode(args, T) -> None:
    """SLO-scheduling benchmark (``--slo``, docs/serving.md
    "Scheduling"): a scenario-diverse workload — bursty arrivals,
    BIMODAL prompt lengths (short interactive queries sharing the
    engine with a stream of long batch prompts), mixed priority
    classes — served twice over identical arrivals:

    * **slo**: chunked prefill (``prefill_chunk_tokens``) + priority
      classes + preemption — the PR 14 scheduler;
    * **fcfs**: whole-prompt prefill, every request one class — the
      historical engine.

    The JSON line reports per-class TTFT p50/p99 for both, the
    interactive-class p99 ratio (the acceptance criterion: >= 2x
    better under the long-prompt interference leg), total tok/s (must
    stay within 10%), preemption counts, per-request oracle identity
    for the SLO leg (chunked + preempted + resumed output must be
    token-identical), and ``decode_recompiles`` (must be 0 — chunk
    boundaries and priorities are data)."""
    from horovod_tpu import serving

    steps = min(args.steps, 16)
    long_len, chunk = 288, 32
    cfg = T.TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model,
        n_heads=args.n_heads, n_layers=args.n_layers, d_ff=args.d_ff,
        max_seq=long_len + 2 * steps + 32,
        n_kv_heads=args.kv_heads[-1] if args.kv_heads else 0,
        attention_impl="reference",
        dtype=jnp.float32 if jax.devices()[0].platform == "cpu"
        else jnp.bfloat16)
    params = T.init_params(jax.random.PRNGKey(0), cfg)

    rng = np.random.default_rng(0)
    # Bimodal, bursty: two waves, each an interleaved mix of LONG
    # batch prompts and bursts of short interactive ones — the
    # interference leg: in FCFS order every short prompt behind a long
    # one waits out its whole prefill.
    work = []  # (arrival_s, prompt, priority)
    t = 0.0
    for wave in range(2):
        for j in range(2):  # long batch prompts lead the wave
            n = int(rng.integers(long_len - 48, long_len + 1))
            work.append((t, rng.integers(0, cfg.vocab_size, n).tolist(),
                         "batch"))
        for j in range(6):  # ... then a burst of interactive queries
            n = int(rng.integers(3, 13))
            work.append((t + 0.01 * (j + 1),
                         rng.integers(0, cfg.vocab_size, n).tolist(),
                         "interactive"))
        t += 0.25

    def run(slo: bool):
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(
                n_slots=4, max_len=cfg.max_seq,
                max_prefills_per_tick=args.max_prefills_per_tick,
                max_queue_depth=64,
                prefill_chunk_tokens=chunk if slo else 0))
        warm_lens = sorted({len(p) for _, p, _ in work})
        engine.warmup([warm_lens[0], warm_lens[len(warm_lens) // 2],
                       warm_lens[-1]])
        warm_compiles = engine.decode_compilations
        engine.metrics = serving.ServingMetrics()
        engine.start()
        futs = []
        t0 = time.monotonic()
        for arrival, prompt, pri in work:
            now = time.monotonic() - t0
            if now < arrival:
                time.sleep(arrival - now)
            futs.append((pri, prompt, engine.submit(
                prompt, max_new_tokens=steps,
                priority=pri if slo else "interactive")))
        while not all(f.done() for _, _, f in futs):
            time.sleep(0.002)
        wall = time.monotonic() - t0
        engine.stop()
        snap = engine.stats()
        by_class = {"interactive": [], "batch": []}
        oracle_ok = oracle_bad = 0
        for pri, prompt, f in futs:
            if f.ttft is not None:
                by_class[pri].append(f.ttft)
            if slo:
                ref = np.asarray(T.greedy_decode(
                    params, jnp.asarray([prompt], jnp.int32), steps,
                    cfg))[0].tolist()
                if f.result(timeout=0) == ref:
                    oracle_ok += 1
                else:
                    oracle_bad += 1
        toks = sum(len(f.tokens_so_far()) for _, _, f in futs)
        out = {
            "tok_s": round(toks / wall, 1),
            "wall_s": round(wall, 3),
            "preemptions": snap["preemptions"],
            "decode_recompiles":
                engine.decode_compilations - warm_compiles,
        }
        for cls, vals in by_class.items():
            vals.sort()
            out[f"{cls}_ttft_p50_ms"] = round(
                vals[len(vals) // 2] * 1e3, 2) if vals else None
            out[f"{cls}_ttft_p99_ms"] = round(
                vals[min(len(vals) - 1,
                         int(len(vals) * 0.99))] * 1e3, 2) \
                if vals else None
        if slo:
            out["oracle_identical"] = oracle_ok
            out["oracle_mismatched"] = oracle_bad
        return out

    fcfs = run(slo=False)
    slo = run(slo=True)
    ratio = (fcfs["interactive_ttft_p99_ms"]
             / slo["interactive_ttft_p99_ms"]
             if slo["interactive_ttft_p99_ms"] else None)
    tput_ratio = (slo["tok_s"] / fcfs["tok_s"]
                  if fcfs["tok_s"] else None)
    result = {
        "metric": f"slo scheduling: interactive TTFT p99 improvement "
                  f"(chunk={chunk} prio+preempt vs FCFS whole-prefill; "
                  f"bimodal {long_len}-token batch stream + "
                  f"interactive bursts, S=4, {len(work)} reqs x "
                  f"{steps} toks)",
        "value": round(ratio, 2) if ratio else None,
        "unit": "x (fcfs_p99 / slo_p99; >= 2 is the acceptance bar)",
        "throughput_ratio": round(tput_ratio, 3) if tput_ratio else None,
        "prefill_chunk_tokens": chunk,
        "decode_recompiles": slo["decode_recompiles"],
        "slo": slo,
        "fcfs": fcfs,
        "chip": jax.devices()[0].device_kind,
    }
    print(f"slo      interactive TTFT p99 {slo['interactive_ttft_p99_ms']}ms "
          f"(chunked+prio) vs {fcfs['interactive_ttft_p99_ms']}ms (fcfs) "
          f"= {result['value']}x | tok/s {slo['tok_s']} vs "
          f"{fcfs['tok_s']} ({result['throughput_ratio']}x) | "
          f"{slo['preemptions']} preemptions, "
          f"{slo['decode_recompiles']} decode recompiles")
    print(json.dumps(result))


def _autotune_mode(args, T) -> None:
    """Autotuning A/B (``--autotune``, docs/serving.md "Autotuning"):
    TWO CONTRASTING workloads — a short-prompt interactive burst and a
    long-prompt batch stream (with an interactive trickle whose TTFT
    constraint the tuner must respect) — each served twice over
    identical arrivals:

    * **static**: the engine's config defaults, untouched;
    * **tuned**: the online tuner converges on a separate convergence
      drive drawn from the same workload distribution, PINS, and then
      the measured run replays the identical arrivals under the
      pinned knobs.

    The JSON line carries ``tuned_knobs``, ``tuning_samples``, the
    objective trajectory, per-class TTFT, throughput, and
    ``decode_recompiles`` (must be 0: every knob the online tuner may
    touch maps to an already-warmed executable shape)."""
    from horovod_tpu import serving
    from horovod_tpu.tuning import Objective, OnlineTuner

    steps = min(args.steps, 12)
    long_len, chunk = 160, 32
    cfg = T.TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model,
        n_heads=args.n_heads, n_layers=args.n_layers, d_ff=args.d_ff,
        max_seq=long_len + 2 * steps + 32,
        n_kv_heads=args.kv_heads[-1] if args.kv_heads else 0,
        attention_impl="reference",
        dtype=jnp.float32 if jax.devices()[0].platform == "cpu"
        else jnp.bfloat16)
    params = T.init_params(jax.random.PRNGKey(0), cfg)

    def make_workload(name):
        rng = np.random.default_rng(1)
        work = []  # (arrival_s, prompt, priority)
        if name == "interactive_burst":
            # Bursty waves of short prompts: the tuner should favor
            # wide admission (high k) — prefill dominates.
            t = 0.0
            for wave in range(4):
                for j in range(6):
                    n = int(rng.integers(3, 13))
                    work.append((t + 0.004 * j,
                                 rng.integers(0, cfg.vocab_size,
                                              n).tolist(),
                                 "interactive"))
                t += 0.08
        else:  # long_batch
            # A stream of long batch prompts with an interactive
            # trickle riding along: throughput tuning must not buy
            # tokens by starving the trickle past its TTFT SLO.
            t = 0.0
            for wave in range(3):
                for j in range(3):
                    n = int(rng.integers(long_len - 32, long_len + 1))
                    work.append((t, rng.integers(0, cfg.vocab_size,
                                                 n).tolist(), "batch"))
                for j in range(2):
                    n = int(rng.integers(3, 13))
                    work.append((t + 0.02 * (j + 1),
                                 rng.integers(0, cfg.vocab_size,
                                              n).tolist(),
                                 "interactive"))
                t += 0.15
        return work

    slo = {"interactive": 0.5}

    def run(work, tuned: bool):
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(
                n_slots=4, max_len=cfg.max_seq,
                max_prefills_per_tick=args.max_prefills_per_tick,
                max_queue_depth=64, prefill_chunk_tokens=chunk))
        lens = sorted({len(p) for _, p, _ in work})
        engine.warmup([lens[0], lens[len(lens) // 2], lens[-1]])
        warm_compiles = engine.decode_compilations
        tuning = None
        engine.start()
        if tuned:
            # Convergence drive: waves drawn from the same workload
            # distribution until the tuner pins (cap bounds the run).
            tuner = OnlineTuner.install(
                engine, window_ticks=8, bo_samples=6,
                objective=Objective(ttft_slo=slo))
            for wave in range(120):
                if tuner.phase == "pinned":
                    break
                futs = [engine.submit(p, max_new_tokens=steps,
                                      priority=pri)
                        for _, p, pri in work[:8]]
                while not all(f.done() for f in futs):
                    time.sleep(0.002)
            snap = tuner.snapshot()
            tuning = {
                "tuned_knobs": snap["best"]["settings"],
                "tuning_samples": snap["samples"],
                "converged": tuner.converged,
                "trajectory": [
                    {"sample": e["sample"], "phase": e["phase"],
                     "settings": e["settings"],
                     "objective": e["objective"],
                     "violated": e["violated"]}
                    for e in snap["trajectory"]],
            }
        # The measured leg: identical arrivals for both A/B sides;
        # fresh metrics so the tuner's convergence traffic (tuned leg)
        # does not pollute the measurement (the tuner's window resets
        # on the metrics swap).
        engine.metrics = serving.ServingMetrics()
        futs = []
        t0 = time.monotonic()
        for arrival, prompt, pri in work:
            now = time.monotonic() - t0
            if now < arrival:
                time.sleep(arrival - now)
            futs.append((pri, engine.submit(
                prompt, max_new_tokens=steps, priority=pri)))
        while not all(f.done() for _, f in futs):
            time.sleep(0.002)
        wall = time.monotonic() - t0
        engine.stop()
        by_class = {}
        for pri, f in futs:
            if f.ttft is not None:
                by_class.setdefault(pri, []).append(f.ttft)
        toks = sum(len(f.tokens_so_far()) for _, f in futs)
        out = {
            "tok_s": round(toks / wall, 1),
            "wall_s": round(wall, 3),
            "decode_recompiles":
                engine.decode_compilations - warm_compiles,
        }
        for cls, vals in sorted(by_class.items()):
            vals.sort()
            out[f"{cls}_ttft_p99_ms"] = round(
                vals[min(len(vals) - 1,
                         int(len(vals) * 0.99))] * 1e3, 2)
        if tuning is not None:
            out.update(tuning)
        return out

    result = {
        "metric": "autotuned vs static serving knobs (online tuner, "
                  f"pinned before measurement; S=4, chunk={chunk}, "
                  f"{steps} toks/req)",
        "unit": "tok_s ratio (tuned / static) per workload",
        "ttft_slo_ms": {k: v * 1e3 for k, v in slo.items()},
        "chip": jax.devices()[0].device_kind,
    }
    for name in ("interactive_burst", "long_batch"):
        work = make_workload(name)
        static = run(work, tuned=False)
        tuned = run(work, tuned=True)
        ratio = (tuned["tok_s"] / static["tok_s"]
                 if static["tok_s"] else None)
        slo_ms = slo["interactive"] * 1e3
        result[name] = {
            "ratio": round(ratio, 3) if ratio else None,
            "interactive_ttft_ok":
                tuned.get("interactive_ttft_p99_ms") is not None
                and tuned["interactive_ttft_p99_ms"] <= slo_ms,
            "static": static,
            "tuned": tuned,
        }
        print(f"autotune {name}: tok/s {tuned['tok_s']} (tuned, "
              f"{tuned['tuned_knobs']}) vs {static['tok_s']} (static) "
              f"= {result[name]['ratio']}x | interactive TTFT p99 "
              f"{tuned.get('interactive_ttft_p99_ms')}ms (SLO "
              f"{slo_ms:.0f}ms) | {tuned['tuning_samples']} samples, "
              f"{tuned['decode_recompiles']} decode recompiles")
    print(json.dumps(result))


def _engine_mode(args, T, cfg, params) -> None:
    """Open-loop continuous-batching benchmark: Poisson arrivals at
    ``--arrival-rate`` req/s with prompt lengths mixed over
    [prompt_len/2, prompt_len], against the engine's S-slot pool
    (overlapped pipeline — the production default), followed by the
    steady-state overlap-vs-sync decode A/B (:func:`_ab_decode`), the
    tracing-overhead A/B (:func:`_ab_tracing`), and the static-batch
    closed-loop ceiling.  With ``--trace`` the open-loop run records a
    Perfetto trace + JSONL request log, and the JSON line carries the
    trace file path; the line always carries the full metrics-registry
    snapshot so BENCH_r* runs double as observability fixtures."""
    rng = np.random.default_rng(0)
    lengths = rng.integers(max(args.prompt_len // 2, 1),
                           args.prompt_len + 1, args.n_requests)
    prompts = [rng.integers(0, cfg.vocab_size, n).tolist()
               for n in lengths]
    arrival = np.cumsum(rng.exponential(1.0 / args.arrival_rate,
                                        args.n_requests))

    tracer = None
    if args.trace:
        from horovod_tpu.obs import tracing as obs_tracing

        tracer = obs_tracing.start(args.trace,
                                   jsonl_path=args.trace + ".jsonl")
    over = _run_engine_once(args, cfg, params, prompts, arrival,
                            overlap=True)
    if tracer is not None:
        from horovod_tpu.obs import tracing as obs_tracing

        obs_tracing.stop()
    ab = None if args.overlap_only else _ab_decode(args, cfg, params)
    pab = None if args.overlap_only else _ab_paged(args, cfg, params)
    tab = None if args.overlap_only else _ab_tracing(args, cfg, params)
    sab = None if args.overlap_only else _ab_spec(args, T, cfg)
    smab = None if args.overlap_only else _ab_sampled(args, cfg, params)
    stab = _ab_stream(args, cfg, params) if args.stream else None

    engine, snap = over["engine"], over["snap"]
    ttft = snap["ttft_seconds"]
    result = {
        "metric": f"continuous-batching open-loop tok/s "
                  f"(S={args.slots} slots, K={args.max_prefills_per_tick}, "
                  f"{args.arrival_rate}/s Poisson, "
                  f"{args.n_requests} reqs x {args.steps} toks, "
                  f"overlapped pipeline)",
        "value": round(over["tok_s"], 2),
        "unit": "tok/s",
        "ttft_p50_s": ttft["p50"],
        "ttft_p99_s": ttft["p99"],
        "ttft_mean_s": ttft["mean"],
        "mean_slot_occupancy": round(over["occ"], 3),
        "requests_completed": snap["requests_completed"],
        "engine_state": engine.health,
        "engine_restarts": snap["engine_restarts"],
        "decode_compilations": engine.decode_compilations,
        "decode_recompiles_after_warmup": over["recompiles"],
        "overlap_efficiency": over["overlap_efficiency"],
        "host_syncs": snap["host_syncs"],
        "host_syncs_per_tick": over["host_syncs_per_tick"],
        "tick_dispatch_mean_s": snap["tick_dispatch_seconds"]["mean"],
        "tick_device_wait_mean_s":
            snap["tick_device_wait_seconds"]["mean"],
        "tick_host_mean_s": snap["tick_host_seconds"]["mean"],
        "model_flops_per_token": snap["model_flops_per_token"],
        "achieved_flops_per_sec": snap["achieved_flops_per_sec"],
        # Tokens emitted per slot per tick (p50/p95 + mean): 1.0 on
        # this non-speculative open-loop run by construction — the
        # same axis the speculative A/B's multiplier reports on, so
        # the two compose with the PR 4 overlap ratio directly.
        "tokens_per_tick_mean": engine.metrics.tokens_per_tick.mean(),
        "tokens_per_tick_p50":
            engine.metrics.tokens_per_tick.percentile(0.50),
        "tokens_per_tick_p95":
            engine.metrics.tokens_per_tick.percentile(0.95),
        # Page-pool pressure for the (paged-by-default) open-loop run:
        # per-token cache cost, pool size, and the high-water mark that
        # sizes n_pages for this traffic shape.
        "paged": snap["paged"],
        "kv_bytes_per_token": snap["kv_bytes_per_token"],
        "kv_pages_total": snap["kv_pages_total"],
        "kv_pages_high_water": snap.get("kv_pages_high_water"),
        "kv_page_size": snap.get("page_size"),
        "chip": jax.devices()[0].device_kind,
        # The full registry snapshot rides the JSON line so BENCH_r*
        # artifacts carry the observability data (counters, gauges,
        # and histogram populations) for the run that produced them.
        "registry": engine.metrics.registry.snapshot(),
    }
    if args.trace:
        result["trace_file"] = args.trace
        result["trace_jsonl"] = args.trace + ".jsonl"
    if ab is not None:
        result.update(ab)
    if pab is not None:
        result.update(pab)
    if tab is not None:
        result.update(tab)
    if sab is not None:
        result.update(sab)
    if smab is not None:
        result.update(smab)
    if stab is not None:
        result.update(stab)

    # Static-batch reference at B = n_slots: the closed-loop ceiling the
    # engine is measured against (same cfg, full batch decoding in
    # lockstep with no admission dynamics).
    B = args.slots
    prompt = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (B, args.prompt_len)), jnp.int32)
    cache = T.init_cache(cfg, B, cfg.max_seq)
    logits, cache = jax.jit(
        lambda p, t, c: T.prefill(p, t, c, cfg))(params, prompt, cache)

    def decode_only(p, cache, logits):
        def gen(carry, _):
            cache, logits = carry
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            logits, cache = T.decode_step(p, tok, cache, cfg)
            return (cache, logits), tok

        _, toks = jax.lax.scan(gen, (cache, logits), None,
                               length=args.steps)
        return jnp.moveaxis(toks, 0, 1)

    dec = jax.jit(decode_only)
    np.asarray(dec(params, cache, logits))  # warm + sync
    best = float("inf")
    for _ in range(args.iters):
        t1 = time.perf_counter()
        np.asarray(dec(params, cache, logits))
        best = min(best, time.perf_counter() - t1)
    result["static_batch_decode_tok_s"] = round(B * args.steps / best, 2)
    result["vs_static_batch"] = round(
        result["value"] / result["static_batch_decode_tok_s"], 3)

    print(f"openloop S={args.slots} {result['value']:9.1f} tok/s | "
          f"TTFT p50 {ttft['p50']}s p99 {ttft['p99']}s | "
          f"occupancy {result['mean_slot_occupancy']:.2f} | "
          f"efficiency {result['overlap_efficiency']} | "
          f"syncs/tick {result['host_syncs_per_tick']}")
    if ab is not None:
        print(f"A/B      steady decode {ab['decode_tok_s_overlap']:9.1f} "
              f"tok/s overlapped vs {ab['decode_tok_s_sync']:9.1f} sync "
              f"-> {ab['overlap_decode_speedup']}x")
    if pab is not None:
        print(f"paged    steady decode {pab['decode_tok_s_paged']:9.1f} "
              f"tok/s paged vs {pab['decode_tok_s_unpaged']:9.1f} "
              f"contiguous -> {pab['paged_decode_ratio']}x | "
              f"{pab['fixed_budget_tokens']}-token budget holds "
              f"{pab['max_concurrent_paged']} concurrent paged vs "
              f"{pab['max_concurrent_unpaged']} slot-contiguous")
    if tab is not None:
        print(f"tracing  {tab['decode_tok_s_tracing']:9.1f} tok/s traced "
              f"vs {tab['decode_tok_s_notracing']:9.1f} untraced -> "
              f"{tab['tracing_overhead_ratio']}x per-tick")
        print(f"spans    {tab['decode_tok_s_spans']:9.1f} tok/s -> "
              f"{tab['span_tracing_overhead_ratio']}x per-tick "
              f"(tail sampling: {tab['span_traces_retained']} retained "
              f"/ {tab['span_traces_dropped']} dropped)")
    if sab is not None:
        print(f"spec     K={sab['spec_k']} ({sab['spec_draft']}) "
              f"repetitive {sab['spec_decode_tok_s_repetitive']:9.1f} "
              f"vs {sab['plain_decode_tok_s_repetitive']:9.1f} tok/s -> "
              f"{sab['spec_repetitive_speedup']}x (acceptance "
              f"{sab['spec_acceptance_rate']}, "
              f"{sab['spec_tokens_per_tick_mean']:.2f} tok/tick) | "
              f"adversarial {sab['spec_adversarial_ratio']}x")
    if smab is not None:
        print(f"sampled  {smab['decode_tok_s_sampled']:9.1f} tok/s vs "
              f"{smab['decode_tok_s_greedy']:9.1f} greedy -> "
              f"{smab['sampled_vs_greedy_ratio']}x "
              f"({smab['sampling_recompiles']} recompiles)")
    if stab is not None:
        print(f"stream   TTFB p50 {stab['stream_ttfb_ms_p50']}ms "
              f"p99 {stab['stream_ttfb_ms_p99']}ms vs non-stream TTFT "
              f"p50 {stab['nonstream_ttft_ms_p50']}ms "
              f"p99 {stab['nonstream_ttft_ms_p99']}ms")
    print(f"static   B={B} {result['static_batch_decode_tok_s']:9.1f} "
          f"tok/s (closed-loop ceiling)")
    print(json.dumps(result))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--d-model", type=int, default=1024)
    ap.add_argument("--n-layers", type=int, default=8)
    ap.add_argument("--n-heads", type=int, default=16)
    ap.add_argument("--d-ff", type=int, default=4096)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--prompt-len", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=128)
    ap.add_argument("--batches", type=int, nargs="+", default=[1, 8, 32])
    ap.add_argument("--kv-heads", type=int, nargs="+", default=[0, 4])
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--engine", action="store_true",
                    help="continuous-batching open-loop benchmark "
                         "(horovod_tpu/serving/) instead of the "
                         "static-batch sweep")
    ap.add_argument("--router", type=int, default=0, metavar="N",
                    help="open-loop benchmark through the replicated "
                         "front tier: N replica processes behind the "
                         "join-shortest-queue router "
                         "(docs/serving.md 'Front tier')")
    ap.add_argument("--rollout", action="store_true",
                    help="zero-downtime reconfiguration benchmark: a "
                         "3-replica fleet (or --router N) serves a "
                         "continuous load while a candidate config is "
                         "canaried and promoted replica-by-replica; "
                         "reports canary/incumbent scores, per-step "
                         "durations, rollback count (claim: 0) and "
                         "rollout-attributable 5xx (claim: 0) "
                         "(docs/serving.md 'Fleet rollouts')")
    ap.add_argument("--chaos", action="store_true",
                    help="durability benchmark: the open-loop workload "
                         "with deterministic engine crashes injected "
                         "mid-decode (restart-resume on); reports "
                         "resumed-vs-restarted counts, wasted-token "
                         "ratio, and per-request oracle identity")
    ap.add_argument("--slo", action="store_true",
                    help="SLO-scheduling benchmark: bursty bimodal "
                         "mixed-class workload served with chunked "
                         "prefill + priorities + preemption vs the "
                         "FCFS whole-prefill baseline; reports "
                         "per-class TTFT p50/p99, the interactive p99 "
                         "ratio, throughput, and oracle identity")
    ap.add_argument("--autotune", action="store_true",
                    help="autotuning A/B: tuned-then-pinned online "
                         "knobs vs static defaults on two contrasting "
                         "workloads (short-prompt interactive burst, "
                         "long-prompt batch stream); reports tuned "
                         "knobs, objective trajectory, per-class "
                         "TTFT, and the zero-recompile guard")
    ap.add_argument("--slots", type=int, default=8,
                    help="engine mode: cache slots S")
    ap.add_argument("--max-prefills-per-tick", type=int, default=2,
                    help="engine mode: prefill/decode interleave K")
    ap.add_argument("--arrival-rate", type=float, default=4.0,
                    help="engine mode: Poisson arrivals per second")
    ap.add_argument("--n-requests", type=int, default=32)
    ap.add_argument("--tp", type=int, default=0, metavar="N",
                    help="tensor-parallel A/B: a tp=N GSPMD-sharded "
                         "engine vs the tp=1 single-device engine on "
                         "the identical workload — steady-state "
                         "decode tok/s, full-sequence token-identity "
                         "check, zero-recompile guard (docs/serving.md "
                         "'Tensor-parallel replicas').  CPU hosts get "
                         "N forced host devices automatically")
    ap.add_argument("--spec-k", type=int, default=3,
                    help="speculative A/B: draft tokens per tick "
                         "(verify window is K+1 wide)")
    ap.add_argument("--spec-draft", default="ngram",
                    choices=["ngram", "model"],
                    help="speculative A/B draft source: n-gram "
                         "prompt lookup (no second model) or a "
                         "half-depth trained draft model")
    ap.add_argument("--overlap-only", action="store_true",
                    help="engine mode: skip the synchronous-baseline "
                         "run (no overlap A/B, no tracing A/B)")
    ap.add_argument("--stream", action="store_true",
                    help="engine mode: add the SSE streaming leg — "
                         "client-observed TTFB p50/p99 (first token "
                         "event on the wire) vs non-streamed TTFT on "
                         "the same closed-loop HTTP workload")
    ap.add_argument("--trace", default="",
                    help="engine mode: record the open-loop run as a "
                         "Perfetto/Chrome trace at this path (plus "
                         "<path>.jsonl request log) and report the "
                         "path in the JSON line")
    args = ap.parse_args()

    if args.tp > 1:
        # Devices must exist before the backend spins up; harmless
        # when the flag (or a real accelerator topology) is already
        # there.  This runs before the first jax.devices() call below.
        from horovod_tpu.serving.sharding import ensure_devices

        ensure_devices(args.tp)

    from horovod_tpu.models import transformer as T

    dtype = jnp.bfloat16
    if jax.devices()[0].platform == "cpu":
        # Same failure mode bench.py guards against: on CPU fallback a
        # TPU-sized run can't finish inside the harness budget — clamp
        # to a smoke configuration (disclosed on stderr).  float32,
        # not bf16: CPU emulates bf16 matmuls several-fold slower, and
        # the smoke config should measure the serving path, not the
        # emulation.
        dtype = jnp.float32
        smoke = {"d_model": 128, "n_layers": 2, "n_heads": 4, "d_ff": 256,
                 "vocab": 512, "prompt_len": 32, "steps": 16,
                 "n_requests": 16}
        clamped = {k: v for k, v in smoke.items() if getattr(args, k) > v}
        for k, v in clamped.items():
            setattr(args, k, v)
        args.batches = [b for b in args.batches if b <= 8] or [1]
        if (args.engine or args.router or args.chaos) \
                and args.arrival_rate < 64.0:
            # Saturate arrivals on the smoke config: at TPU-shaped
            # arrival rates the CPU run is dominated by waiting for the
            # Poisson clock and the overlap A/B would measure sleep().
            clamped["arrival_rate"] = args.arrival_rate = 64.0
        if clamped:
            print(f"running on CPU; clamped {clamped} to a smoke "
                  "configuration", file=sys.stderr)

    kind = jax.devices()[0].device_kind
    print(f"chip={kind} d{args.d_model} L{args.n_layers} "
          f"h{args.n_heads} d_ff{args.d_ff} vocab{args.vocab} "
          f"{jnp.dtype(dtype).name}")

    if args.tp:
        _tp_mode(args, T)
        return

    if args.slo:
        _slo_mode(args, T)
        return

    if args.autotune:
        _autotune_mode(args, T)
        return

    if args.router or args.rollout:
        kv = args.kv_heads[-1] if args.kv_heads else 0
        cfg = T.TransformerConfig(
            vocab_size=args.vocab, d_model=args.d_model,
            n_heads=args.n_heads, n_layers=args.n_layers, d_ff=args.d_ff,
            max_seq=args.prompt_len + args.steps,
            n_kv_heads=kv, attention_impl="reference", dtype=dtype,
        )
        if args.rollout:
            _rollout_mode(args, cfg)
        else:
            _router_mode(args, cfg)
        return

    if args.engine or args.chaos:
        kv = args.kv_heads[-1] if args.kv_heads else 0
        cfg = T.TransformerConfig(
            vocab_size=args.vocab, d_model=args.d_model,
            n_heads=args.n_heads, n_layers=args.n_layers, d_ff=args.d_ff,
            max_seq=args.prompt_len + args.steps,
            n_kv_heads=kv, attention_impl="reference", dtype=dtype,
        )
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        if args.chaos:
            _chaos_mode(args, T, cfg, params)
        else:
            _engine_mode(args, T, cfg, params)
        return

    for kv in args.kv_heads:
        cfg = T.TransformerConfig(
            vocab_size=args.vocab, d_model=args.d_model,
            n_heads=args.n_heads, n_layers=args.n_layers, d_ff=args.d_ff,
            max_seq=args.prompt_len + args.steps,
            n_kv_heads=kv, attention_impl="reference", dtype=dtype,
        )
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        kv_tag = f"kv{kv or args.n_heads}"

        for B in args.batches:
            prompt = jax.random.randint(
                jax.random.PRNGKey(1), (B, args.prompt_len), 0,
                cfg.vocab_size, jnp.int32)

            # ---- prefill latency --------------------------------------
            pre = jax.jit(lambda p, t: T.prefill(
                p, t, T.init_cache(cfg, B, cfg.max_seq), cfg))
            logits, cache = pre(params, prompt)
            float(jnp.sum(logits))  # warm + sync
            best_pre = float("inf")
            for _ in range(args.iters):
                t0 = time.perf_counter()
                logits, cache = pre(params, prompt)
                float(jnp.sum(logits))
                best_pre = min(best_pre, time.perf_counter() - t0)

            # ---- decode throughput (one scanned call) -----------------
            # Time the decode scan DIRECTLY from a prefilled cache: the
            # old best-of-N(total) - best-of-N(prefill) subtraction can
            # go small or negative under chip variance and overstate
            # tok/s (ADVICE r5).
            def decode_only(p, cache, logits):
                def gen(carry, _):
                    cache, logits = carry
                    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    logits, cache = T.decode_step(p, tok, cache, cfg)
                    return (cache, logits), tok

                _, toks = jax.lax.scan(
                    gen, (cache, logits), None, length=args.steps)
                return jnp.moveaxis(toks, 0, 1)

            dec = jax.jit(decode_only)
            np.asarray(dec(params, cache, logits))  # warm + sync
            best_dec = float("inf")
            for _ in range(args.iters):
                t0 = time.perf_counter()
                toks = dec(params, cache, logits)
                np.asarray(toks)
                best_dec = min(best_dec, time.perf_counter() - t0)

            # Raw combined prefill+decode (the end-to-end serving call),
            # reported alongside so the decomposition is auditable.
            e2e = jax.jit(lambda p, t: T.sample_decode(
                p, t, args.steps, cfg, rng=jax.random.PRNGKey(2),
                temperature=0.0))
            np.asarray(e2e(params, prompt))  # warm + sync
            best_e2e = float("inf")
            for _ in range(args.iters):
                t0 = time.perf_counter()
                np.asarray(e2e(params, prompt))
                best_e2e = min(best_e2e, time.perf_counter() - t0)

            tps = B * args.steps / best_dec
            per_tok_ms = best_dec / args.steps * 1e3
            print(f"{kv_tag} B={B:<3} prefill({args.prompt_len}) "
                  f"{best_pre * 1e3:7.1f}ms | decode {tps:8.0f} tok/s "
                  f"({per_tok_ms:.2f} ms/token-step) | "
                  f"combined {best_e2e * 1e3:7.1f}ms")


if __name__ == "__main__":
    main()
