"""Serving-path benchmark: prefill latency + autoregressive decode
throughput on the current chip.

The decode loop is ONE compiled ``lax.scan`` (``sample_decode``), so the
tunneled chip's ~10 ms per-call floor amortizes over all steps; timing
closes with a value fetch of the final tokens (axon ``block_until_ready``
returns early).  GQA rows show the KV-cache bandwidth lever
(`n_kv_heads` shrinks the cache the decode step streams every token).

    python benchmarks/serving.py [--batches 1 8 32] [--steps 128]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--d-model", type=int, default=1024)
    ap.add_argument("--n-layers", type=int, default=8)
    ap.add_argument("--n-heads", type=int, default=16)
    ap.add_argument("--d-ff", type=int, default=4096)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--prompt-len", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=128)
    ap.add_argument("--batches", type=int, nargs="+", default=[1, 8, 32])
    ap.add_argument("--kv-heads", type=int, nargs="+", default=[0, 4])
    ap.add_argument("--iters", type=int, default=3)
    args = ap.parse_args()

    from horovod_tpu.models import transformer as T

    kind = jax.devices()[0].device_kind
    print(f"chip={kind} d{args.d_model} L{args.n_layers} "
          f"h{args.n_heads} d_ff{args.d_ff} vocab{args.vocab} bf16")

    for kv in args.kv_heads:
        cfg = T.TransformerConfig(
            vocab_size=args.vocab, d_model=args.d_model,
            n_heads=args.n_heads, n_layers=args.n_layers, d_ff=args.d_ff,
            max_seq=args.prompt_len + args.steps,
            n_kv_heads=kv, attention_impl="reference",
        )
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        kv_tag = f"kv{kv or args.n_heads}"

        for B in args.batches:
            prompt = jax.random.randint(
                jax.random.PRNGKey(1), (B, args.prompt_len), 0,
                cfg.vocab_size, jnp.int32)

            # ---- prefill latency --------------------------------------
            pre = jax.jit(lambda p, t: T.prefill(
                p, t, T.init_cache(cfg, B, cfg.max_seq), cfg))
            logits, cache = pre(params, prompt)
            float(jnp.sum(logits))  # warm + sync
            best_pre = float("inf")
            for _ in range(args.iters):
                t0 = time.perf_counter()
                logits, cache = pre(params, prompt)
                float(jnp.sum(logits))
                best_pre = min(best_pre, time.perf_counter() - t0)

            # ---- decode throughput (one scanned call) -----------------
            # Time the decode scan DIRECTLY from a prefilled cache: the
            # old best-of-N(total) - best-of-N(prefill) subtraction can
            # go small or negative under chip variance and overstate
            # tok/s (ADVICE r5).
            def decode_only(p, cache, logits):
                def gen(carry, _):
                    cache, logits = carry
                    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    logits, cache = T.decode_step(p, tok, cache, cfg)
                    return (cache, logits), tok

                _, toks = jax.lax.scan(
                    gen, (cache, logits), None, length=args.steps)
                return jnp.moveaxis(toks, 0, 1)

            dec = jax.jit(decode_only)
            np.asarray(dec(params, cache, logits))  # warm + sync
            best_dec = float("inf")
            for _ in range(args.iters):
                t0 = time.perf_counter()
                toks = dec(params, cache, logits)
                np.asarray(toks)
                best_dec = min(best_dec, time.perf_counter() - t0)

            # Raw combined prefill+decode (the end-to-end serving call),
            # reported alongside so the decomposition is auditable.
            e2e = jax.jit(lambda p, t: T.sample_decode(
                p, t, args.steps, cfg, rng=jax.random.PRNGKey(2),
                temperature=0.0))
            np.asarray(e2e(params, prompt))  # warm + sync
            best_e2e = float("inf")
            for _ in range(args.iters):
                t0 = time.perf_counter()
                np.asarray(e2e(params, prompt))
                best_e2e = min(best_e2e, time.perf_counter() - t0)

            tps = B * args.steps / best_dec
            per_tok_ms = best_dec / args.steps * 1e3
            print(f"{kv_tag} B={B:<3} prefill({args.prompt_len}) "
                  f"{best_pre * 1e3:7.1f}ms | decode {tps:8.0f} tok/s "
                  f"({per_tok_ms:.2f} ms/token-step) | "
                  f"combined {best_e2e * 1e3:7.1f}ms")


if __name__ == "__main__":
    main()
