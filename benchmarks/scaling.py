"""Scaling-efficiency harness — the proxy for the reference's headline
claim (90% scaling efficiency for ResNet-101/Inception V3 at 512 GPUs,
``docs/benchmarks.rst:13-14``; protocol in
``examples/tensorflow2_synthetic_benchmark.py:36-131``).

Real multi-chip hardware is not available in this environment, so this
measures **weak scaling of the compiled SPMD train step over an N-device
host-platform (CPU) mesh**: per-device batch held constant, devices swept
1..8 via ``--xla_force_host_platform_device_count``.  That bounds the cost
the framework itself adds at scale — collective insertion, shard_map
partitioning, fusion buckets — though not ICI latency (virtual devices
share one host's memory bus; disclosed in the output).  The same step
function is what ``bench.py`` times on the real chip.

Efficiency definition matches the reference: ``(total img/s at N) /
(N x img/s at 1)`` (``docs/benchmarks.rst``: scaling efficiency).

Run:  python benchmarks/scaling.py [--devices 1 2 4 8] [--out SCALING.json]

Each device count runs in a fresh subprocess because
``xla_force_host_platform_device_count`` is fixed at backend init.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

WORKER = "__scaling_worker__"


def worker(n_devices: int, batch_per_device: int, iters: int, model: str) -> None:
    # The sandbox's sitecustomize imports jax at interpreter startup, so env
    # vars are too late — jax.config works until a backend is initialized
    # (same reasoning as tests/conftest.py).
    import jax

    jax.config.update("jax_platforms", "cpu")
    from horovod_tpu._compat import set_cpu_device_count

    set_cpu_device_count(n_devices)
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import spmd

    hvd.init()
    assert hvd.size() == n_devices, (hvd.size(), n_devices)

    if model == "mlp":
        from horovod_tpu.models import mlp

        params = mlp.init_params(jax.random.PRNGKey(0), (784, 512, 512, 10))
        in_dim, n_classes = 784, 10

        def loss_fn(p, batch):
            return mlp.loss_fn(p, (batch["x"], batch["y"]))

    else:  # tiny resnet variant, CPU-sized
        from horovod_tpu.models import resnet

        net = resnet.ResNet(
            stage_sizes=[1, 1], block_cls=resnet.ResNetBlock, num_classes=10,
            num_filters=16, dtype=jnp.float32,
        )
        rng = jax.random.PRNGKey(0)
        variables = net.init(rng, jnp.zeros((2, 32, 32, 3), jnp.float32), train=True)
        params, stats = variables["params"], variables["batch_stats"]
        in_dim, n_classes = (32, 32, 3), 10

        def loss_fn(p, batch):
            logits, _ = net.apply(
                {"params": p, "batch_stats": stats}, batch["x"], train=True,
                mutable=["batch_stats"],
            )
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"]
            ).mean()

    opt = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
    # Control: identical step WITHOUT the gradient exchange.  Virtual CPU
    # devices share the host's physical cores, so raw weak-scaling numbers
    # mostly measure core contention; dividing by the exchange-free step on
    # the SAME n-device mesh cancels that and isolates what the reference's
    # scaling-efficiency claim actually measures — the cost the framework
    # adds for synchronous data parallelism.
    opt_local = optax.sgd(0.01, momentum=0.9)

    global_batch = batch_per_device * n_devices
    if model == "mlp":
        x = np.random.rand(global_batch, in_dim).astype(np.float32)
    else:
        x = np.random.rand(global_batch, *in_dim).astype(np.float32)
    y = np.random.randint(0, n_classes, (global_batch,))
    batch = spmd.shard_batch({"x": jnp.asarray(x), "y": jnp.asarray(y)})

    # Host-side master copy: the train step donates its params/opt-state
    # args, and device_put with an unchanged sharding can alias (not copy)
    # a device array — re-uploading from numpy gives each timed() run a
    # fresh donatable tree.
    params = jax.device_get(params)

    def timed(optimizer):
        step = spmd.make_train_step(loss_fn, optimizer)
        p = spmd.init_replicated(params)
        s = spmd.init_replicated(optimizer.init(params))
        for _ in range(3):  # warmup / compile
            p, s, loss = step(p, s, batch)
        float(loss)
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            p, s, loss = step(p, s, batch)
            float(loss)  # value fetch = watertight barrier (see bench.py)
            times.append(time.perf_counter() - t0)
        return float(np.median(times))

    t_full = timed(opt)
    t_local = timed(opt_local)
    print(json.dumps({
        "n_devices": n_devices,
        "median_step_s": t_full,
        "median_step_s_no_exchange": t_local,
        "img_per_sec_total": global_batch / t_full,
        "dp_overhead_efficiency": min(t_local / t_full, 1.0),
    }))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, nargs="+", default=[1, 2, 4, 8])
    ap.add_argument("--batch-per-device", type=int, default=64)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--model", default="resnet", choices=["mlp", "resnet"])
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    results = []
    for n in args.devices:
        proc = subprocess.run(
            [sys.executable, __file__, WORKER, str(n),
             str(args.batch_per_device), str(args.iters), args.model],
            capture_output=True, text=True, timeout=900,
        )
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr)
            raise SystemExit(f"worker n={n} failed")
        line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
        results.append(json.loads(line))
        sys.stderr.write(f"n={n}: {results[-1]['img_per_sec_total']:.1f} img/s total\n")

    base = results[0]["img_per_sec_total"] / results[0]["n_devices"]
    curve = []
    for r in results:
        raw_eff = r["img_per_sec_total"] / (r["n_devices"] * base)
        curve.append({**r, "raw_weak_scaling_efficiency": round(raw_eff, 4)})

    out = {
        "protocol": (
            "compiled SPMD train step over an N-virtual-device CPU mesh, "
            "per-device batch fixed. dp_overhead_efficiency = (step time "
            "without gradient exchange) / (step time with exchange) on the "
            "SAME mesh — the framework's synchronous-DP cost, which is what "
            "the reference's scaling-efficiency claim measures, with host "
            "core contention cancelled. raw_weak_scaling_efficiency = "
            "total/(N x single) is also reported but on one host it mostly "
            "measures physical-core sharing, NOT the framework."
        ),
        "model": args.model,
        "batch_per_device": args.batch_per_device,
        "reference_claim": {
            "value": "90% scaling efficiency @ 512 GPUs (ResNet-101/Inception V3)",
            "source": "docs/benchmarks.rst:13-14",
        },
        "curve": curve,
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == WORKER:
        worker(int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]), sys.argv[5])
    else:
        main()
