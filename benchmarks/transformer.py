"""Transformer-LM training benchmark on the real chip: tokens/sec + MFU,
with the attention implementation as the variable — XLA softmax attention
vs the Pallas flash kernel (``horovod_tpu/ops/attention.py``).

The reference has no LM benchmark (its headline is ResNet/Inception
throughput, ``docs/benchmarks.rst``); this measures the framework's
long-context extension the same way ``bench.py`` measures the DP path:
synthetic data on device, warmup, median over timed iterations, MFU from
XLA's cost analysis of the compiled step.

Run:  python benchmarks/transformer.py [--seq 2048] [--attention flash]
Prints one JSON line per configuration.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

# Runnable as `python benchmarks/transformer.py` without PYTHONPATH
# (same shim as benchmarks/serving.py).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--d-model", type=int, default=1024)
    ap.add_argument("--n-layers", type=int, default=8)
    ap.add_argument("--n-heads", type=int, default=16)
    ap.add_argument("--d-ff", type=int, default=4096)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--attention", default="flash",
                    choices=["reference", "flash", "ring", "ring_reference"])
    ap.add_argument("--sp", type=int, default=0,
                    help="ring attention: sequence-parallel axis size "
                         "(0 = all chips). sp=1 measures the ring "
                         "plumbing + flash-chunk path against plain "
                         "flash on identical shapes.")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize layers in backward (jax.checkpoint)")
    ap.add_argument("--remat-policy", default="full",
                    choices=["full", "dots"],
                    help="full: recompute everything; dots: keep matmul "
                         "outputs, recompute elementwise only")
    ap.add_argument("--n-experts", type=int, default=0,
                    help="MoE experts per layer (0 = dense MLP)")
    ap.add_argument("--moe-impl", default="switch",
                    choices=["switch", "dense", "dropless"],
                    help="MoE dispatch: sparse capacity-factor token "
                         "dispatch (each token computes ONE expert), "
                         "the dense all-experts oracle, or grouped "
                         "ragged matmuls (dropless, serving path)")
    ap.add_argument("--moe-dispatch", default="sort",
                    choices=["sort", "cumsum"],
                    help="switch dispatch mechanism (sort = argsort + "
                         "gathers; cumsum = one-hot running-position "
                         "oracle)")
    ap.add_argument("--capacity-factor", type=float, default=1.25)
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="grouped-query attention: K/V heads "
                         "(0 = n_heads); the ring rotates shards this "
                         "many heads wide")
    ap.add_argument("--num-iters", type=int, default=5)
    ap.add_argument("--steps-per-iter", type=int, default=5)
    args = ap.parse_args()

    import horovod_tpu as hvd
    from horovod_tpu import spmd
    from horovod_tpu.models import transformer as T
    from jax.sharding import NamedSharding, PartitionSpec as P

    hvd.init()

    cfg = T.TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff, max_seq=args.seq,
        attention_impl=args.attention, remat=args.remat,
        remat_policy=args.remat_policy,
        n_experts=args.n_experts,
        moe_impl=args.moe_impl,
        moe_dispatch=args.moe_dispatch,
        capacity_factor=args.capacity_factor,
        n_kv_heads=args.kv_heads,
    )
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    if args.attention.startswith("ring"):
        # Ring runs under a (dp, sp) shard_map; gradients are pmean'd
        # over both axes in the step, so the inner optimizer is plain.
        opt = optax.adamw(3e-4)
    else:
        opt = hvd.DistributedOptimizer(optax.adamw(3e-4))
    opt_state = opt.init(params)

    n = hvd.size()
    ring = args.attention.startswith("ring")
    if ring:
        # Sequence-parallel: the sp axis must be BOUND (shard_map) so K/V
        # shards can ppermute around the ring through the flash kernels.
        # Gradients are pmean'd explicitly (the optimizer is plain optax).
        from horovod_tpu.parallel.meshes import MeshSpec, make_mesh

        sp = args.sp or n
        dp = n // sp
        mesh = make_mesh(MeshSpec(dp=dp, sp=sp))
        data_axes = ("dp", "sp")
        batch_spec = P("dp", "sp")
        rows = args.batch_size * dp
    else:
        mesh = hvd.mesh()
        data_axes = (hvd.AXIS,)
        batch_spec = P(hvd.AXIS)
        rows = args.batch_size * n

    def _step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: T.loss_fn(p, batch, cfg))(params)
        if ring:
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, data_axes), grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state,
                jax.lax.pmean(loss, data_axes))

    step = jax.jit(spmd.shard(
        _step, in_specs=(P(), P(), batch_spec),
        out_specs=(P(), P(), P()), mesh=mesh), donate_argnums=(0, 1))
    # Targets are the FULL-sequence next-token shift, computed before
    # sharding: a per-shard roll inside the step would wrap around each
    # sp chunk, silently training a different objective on the ring path.
    tok_host = np.random.randint(0, args.vocab, (rows, args.seq))
    tokens = {
        "tokens": jax.device_put(
            jnp.asarray(tok_host, jnp.int32),
            NamedSharding(mesh, batch_spec)),
        "targets": jax.device_put(
            jnp.asarray(np.roll(tok_host, -1, axis=1), jnp.int32),
            NamedSharding(mesh, batch_spec)),
    }

    from horovod_tpu.obs import xprof

    step = step.lower(params, opt_state, tokens).compile()
    # Peak-HBM and the chip-peak table come from obs.xprof (the
    # library-ized form of bench.py's cost_analysis trick); the MFU
    # numerator stays ANALYTIC on purpose — XLA's cost analysis counts
    # a lax.scan body ONCE, so it undercounts the per-layer work
    # n_layers-fold here: 6 x matmul-params x tokens for the dense path
    # + causal attention scores, fwd+bwd.
    report = xprof.introspect(step, fn="transformer_train_step")
    n_matmul = xprof.matmul_param_count(params)
    moe_removed = 0
    if args.n_experts > 1:
        # MODEL FLOPs for top-1 MoE: each token's MLP runs ONE expert, so
        # the expert stacks contribute 1/E of their parameter count (the
        # PaLM useful-work convention; reported as "mfu").  Dense dispatch
        # EXECUTES all E experts — that hardware utilization is reported
        # separately as "mfu_executed" (the r3 table's ¹ convention).
        expert_params = sum(
            int(np.prod(params["layers"][k].shape))
            for k in ("w_gate", "w_up", "w_down"))
        moe_removed = expert_params * (args.n_experts - 1) // args.n_experts
        n_matmul -= moe_removed
    # Per-chip FLOPs: global batch rows / n chips (for ring, the sequence
    # is sharded too, so per-chip work is global work / n either way).
    B = rows / n
    S = args.seq
    dense_flops = 6 * n_matmul * B * S
    attn_flops = 6 * args.n_layers * B * S * S * args.d_model  # causal
    # MFU convention (PaLM appendix B): model FLOPs only — remat's
    # recompute is NOT counted, so --remat runs report the honest
    # utilization of useful work.
    step_flops = float(dense_flops + attn_flops)

    kind = jax.devices()[0].device_kind
    peak = xprof.chip_peak_flops()
    # Arm the live training_mfu gauge; one measured unit below is an
    # iteration of steps_per_iter steps closed by a sync.
    xprof.set_training_cost(
        step_flops * args.steps_per_iter if step_flops else None, peak)

    def _sync(x):
        return float(np.asarray(jax.device_get(x)))

    for _ in range(2):  # warmup
        for _ in range(args.steps_per_iter):
            params, opt_state, loss = step(params, opt_state, tokens)
    _sync(loss)

    from horovod_tpu import obs

    times = []
    for _ in range(args.num_iters):
        t0 = time.perf_counter()
        with obs.training_step("transformer_bench_iter"):
            for _ in range(args.steps_per_iter):
                params, opt_state, loss = step(params, opt_state, tokens)
            _sync(loss)
        times.append((time.perf_counter() - t0) / args.steps_per_iter)

    med = float(np.median(times))
    tokens_per_step = rows * args.seq / n  # per chip
    result = {
        "metric": (f"TransformerLM d{args.d_model} L{args.n_layers} "
                   f"seq{args.seq}"
                   + (f" moe{args.n_experts}-{args.moe_impl}"
                      + (f"-{args.moe_dispatch}"
                         if args.moe_impl == "switch" else "")
                      + f"-cf{args.capacity_factor:g}"
                      if args.n_experts > 1 else "")
                   + f" {args.attention}-attention train "
                   f"throughput per chip"),
        "value": round(tokens_per_step / med, 1),
        "unit": "tokens/sec/chip",
        "median_step_s": round(med, 5),
        "mfu": (round(step_flops / med / peak, 4) if peak and step_flops
                else None),
        "tflops_per_sec": (round(step_flops / med / 1e12, 1)
                           if step_flops else None),
        "hbm_peak_bytes": report.peak_hbm_bytes,
        "chip": kind,
    }
    if args.n_experts > 1 and args.moe_impl == "dense" and peak:
        # Dense dispatch actually executes every expert: report that
        # hardware utilization alongside the model MFU (r3's convention,
        # kept reproducible).
        executed = step_flops + 6 * moe_removed * B * S
        result["mfu_executed"] = round(executed / med / peak, 4)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
