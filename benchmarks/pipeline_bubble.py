"""Measure the pipeline bubble: throughput vs microbatch count M for the
gpipe / 1f1b / interleaved schedules on a P-device virtual mesh.

Why this measures the bubble even on serialized virtual CPU devices: the
schedules are ONE lax.scan over ticks and every device executes its
stage computation every tick, valid or not (SPMD — fill/drain ticks run
on zeros).  Idle ticks therefore burn host time exactly the way real
bubbles burn chip time, and samples/s as a function of M traces the
schedule's tick-efficiency curve:

    gpipe        ~ M / (M + P - 1)      (forward scan and its autodiff
                                         reverse each pay P-1 fill ticks)
    1f1b         ~ M / (M + 2P - 2)     (one combined fwd+bwd wavefront
                                         scan with 2(P-1) fill/drain)
    interleaved  ~ Mv / (Mv + P - 1)    (chunk-granularity fill: the
                                         bubble divided by ~v)

Each schedule's curve is normalized to its own ideal (per-tick work
differs across schedules — 1f1b ticks carry fwd+bwd; interleaved ticks
carry 1/v of a stage), so the printed efficiency is comparable to the
predicted fraction, and the absolute samples/s column shows the real
cost.

Run:
    JAX_PLATFORMS=cpu python benchmarks/pipeline_bubble.py [--p 8]
"""

from __future__ import annotations

import argparse
import time

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--p", type=int, default=8, help="pipeline stages")
    ap.add_argument("--d", type=int, default=512)
    ap.add_argument("--mb", type=int, default=8, help="microbatch rows")
    ap.add_argument("--layers-per-stage", type=int, default=2)
    ap.add_argument("--virtual", type=int, default=2)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--ms", type=int, nargs="+", default=[4, 8, 16, 32])
    args = ap.parse_args()

    from horovod_tpu._compat import set_cpu_device_count

    set_cpu_device_count(args.p)

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.parallel import pipeline as PL

    p, d, mb, v = args.p, args.d, args.mb, args.virtual
    layers = args.layers_per_stage * p * v  # divisible for every schedule
    mesh = Mesh(np.array(jax.devices()[:p]), axis_names=("pp",))
    w_all = jax.random.normal(jax.random.PRNGKey(0), (layers, d, d)) * 0.1

    def stage_fn(w_stack, x):
        def layer(h, w):
            return jnp.tanh(h @ w), None

        out, _ = jax.lax.scan(layer, x, w_stack)
        return out

    def loss_fn(y, tgt):
        return jnp.sum((y - tgt) ** 2)

    def build(schedule, m):
        x = jax.random.normal(jax.random.PRNGKey(1), (m, mb, d))
        tgt = jax.random.normal(jax.random.PRNGKey(2), (m, mb, d)) * 0.1

        def inner(w_full, xs, ts):
            s = jax.lax.axis_index("pp")
            if schedule == "interleaved":
                params = PL.stack_to_chunks(w_full, p, v, s)
            else:
                params = jax.tree_util.tree_map(
                    lambda l: jax.lax.dynamic_index_in_dim(
                        l, s, keepdims=False),
                    PL.stack_to_stages(w_full, p))
            loss, g = PL.pipeline_value_and_grad(
                stage_fn, params, xs, ts, loss_fn, axis_name="pp",
                schedule=schedule, n_virtual=v)
            return loss

        fn = jax.jit(jax.shard_map(
            inner, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P()))
        return fn, (w_all, x, tgt)

    def predicted(schedule, m):
        if schedule == "gpipe":
            return m / (m + p - 1)
        if schedule == "1f1b":
            return m / (m + 2 * p - 2)
        return (m * v) / (m * v + p - 1)

    print(f"P={p} stages, {layers} layers, d={d}, mb={mb}, "
          f"v={v} (interleaved), {args.iters} timed iters")
    print(f"{'schedule':<12} {'M':>3} {'samples/s':>10} {'eff':>6} "
          f"{'predicted':>9}")
    results = {}
    for schedule in ("gpipe", "1f1b", "interleaved"):
        rows = []
        ms = [m for m in args.ms
              if schedule != "interleaved" or m % p == 0]
        for m in ms:
            fn, fargs = build(schedule, m)
            fn(*fargs).block_until_ready()  # compile + warm
            t0 = time.perf_counter()
            for _ in range(args.iters):
                out = fn(*fargs)
            out.block_until_ready()
            dt = (time.perf_counter() - t0) / args.iters
            rows.append((m, m * mb / dt))
        if not rows:  # e.g. interleaved with no M divisible by P
            continue
        # Efficiency normalized to this schedule's own per-sample ideal:
        # time/sample extrapolated from the largest-M run's predicted
        # fraction (bubble-free tick cost is schedule-specific).
        m_big, sps_big = rows[-1]
        ideal_sps = sps_big / predicted(schedule, m_big)
        for m, sps in rows:
            eff = sps / ideal_sps
            print(f"{schedule:<12} {m:>3} {sps:>10.1f} {eff:>6.2f} "
                  f"{predicted(schedule, m):>9.2f}")
        results[schedule] = rows
    # Headline: throughput gained by interleaving at the smallest common M.
    # Either schedule may be absent (e.g. no --ms entry divisible by --p
    # leaves interleaved without rows) — skip the headline, don't KeyError.
    common = [m for m, _ in results.get("interleaved", [])
              if m in dict(results.get("1f1b", []))]
    if common:
        m0 = common[0]
        g0 = dict(results["1f1b"])[m0]
        i0 = dict(results["interleaved"])[m0]
        print(f"interleaved vs 1f1b at M={m0}: {i0 / g0:.2f}x samples/s")


if __name__ == "__main__":
    main()
