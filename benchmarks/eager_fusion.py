"""Eager/native control-plane benchmark under BERT-style many-small-tensor
load (BASELINE.md's "tensor-fusion + autotune" keep-honest config).

The reference's entire layer-2 C++ (negotiation controller.cc:631-752,
response cache response_cache.h:45-102, 64MB fusion threshold
operations.cc:408) exists to make op-by-op training fast.  This benchmark
measures OUR re-design of that machinery end to end: ~340 gradient-sized
tensors (1KB-512KB, BERT-base-like mix) allreduced per step across real
launcher-spawned processes, comparing

  direct    HOROVOD_NATIVE=0 — every tensor its own immediate collective
  native    negotiation + tensor fusion + response-cache fast path
  autotune  native + the Bayesian parameter manager tuning fusion/cycle

and, separately, a 74-parameter-tensor torch model driven through
``hvd.torch.DistributedOptimizer`` (per-parameter hook submissions, the
reference's op-by-op pattern).

Run the driver (spawns everything):

    python benchmarks/eager_fusion.py [--nproc 2] [--steps 12]

Per-mode JSON lands on stdout; the driver prints a comparison table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# --- workload -----------------------------------------------------------------


def bert_style_tensors(layers: int = 24, hidden: int = 256, seed: int = 0):
    """~14 tensors per layer mirroring a transformer's gradient mix:
    4 square attention mats, 2 FFN mats, and 8 small vectors."""
    import numpy as np

    rng = np.random.RandomState(seed)
    out = []
    for layer in range(layers):
        for nm, shape in (
            ("wq", (hidden, hidden)), ("wk", (hidden, hidden)),
            ("wv", (hidden, hidden)), ("wo", (hidden, hidden)),
            ("w1", (hidden, 2 * hidden)), ("w2", (2 * hidden, hidden)),
            ("bq", (hidden,)), ("bk", (hidden,)), ("bv", (hidden,)),
            ("bo", (hidden,)), ("b1", (2 * hidden,)), ("b2", (hidden,)),
            ("ln1", (hidden,)), ("ln2", (hidden,)),
        ):
            out.append((f"grad.l{layer}.{nm}",
                        rng.randn(*shape).astype("float32")))
    return out


def run_allreduce_mode(args) -> dict:
    """Per-tensor async allreduce of the whole tensor set each step (the
    torch-hook submission pattern), timed after warmup."""
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu import eager_runtime

    hvd.init()
    rt = eager_runtime.get()
    tensors = bert_style_tensors(args.layers, args.hidden)
    total_bytes = sum(a.nbytes for _, a in tensors)

    def one_step():
        handles = [hvd.allreduce_async(a, hvd.Average, name=nm)
                   for nm, a in tensors]
        for h in handles:
            hvd.synchronize(h)

    tuner = None
    if args.mode == "autotune":
        from horovod_tpu.autotune import Autotuner

        tuner = Autotuner(warmup_samples=1, steps_per_sample=3,
                          bo_samples=args.bo_samples)

    for _ in range(args.warmup):
        one_step()

    hits0 = rt.cache_hits() if rt else 0
    resp0 = rt.responses_executed if rt else 0
    tens0 = rt.tensors_executed if rt else 0
    steps = args.steps if tuner is None else args.autotune_steps
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        one_step()
        dt = time.perf_counter() - t0
        times.append(dt)
        if tuner is not None:
            tuner.record(total_bytes, dt)

    # Autotune: score the FINAL settings over a clean window, with the
    # observability counters re-snapshotted so hit rate / fusion ratio
    # describe the frozen settings, not the tuning transient.
    if tuner is not None:
        if rt is not None:
            hits0 = rt.cache_hits()
            resp0 = rt.responses_executed
            tens0 = rt.tensors_executed
        times = []
        for _ in range(args.steps):
            t0 = time.perf_counter()
            one_step()
            times.append(time.perf_counter() - t0)

    n = len(tensors)
    med = sorted(times)[len(times) // 2]
    result = {
        "mode": args.mode,
        "nproc": hvd.num_processes(),
        "tensors_per_step": n,
        "mbytes_per_step": round(total_bytes / 2**20, 1),
        "steps_per_s": round(1.0 / med, 3),
        "tensor_mb_per_s": round(total_bytes / 2**20 / med, 1),
    }
    if rt is not None:
        measured = len(times) * n
        result["cache_hit_rate"] = round(
            (rt.cache_hits() - hits0) / max(measured, 1), 3)
        dresp = rt.responses_executed - resp0
        dtens = rt.tensors_executed - tens0
        result["fusion_ratio"] = round(dtens / max(dresp, 1), 1)
    if tuner is not None:
        result["tuned_settings"] = {
            k: v for k, v in tuner.settings.items()
            if k in ("fusion_threshold", "cycle_time_ms", "cache_capacity")}
    if hvd.process_rank() == 0:
        print("EAGER-BENCH " + json.dumps(result), flush=True)
    hvd.shutdown()
    return result


def run_torch_mode(args) -> dict:
    """torch.DistributedOptimizer step loop: per-parameter grad-hook
    submissions through the runtime (reference torch/__init__.py:61-216
    op-by-op pattern)."""
    import torch

    import horovod_tpu.torch as hvd
    from horovod_tpu import eager_runtime

    hvd.init()
    rt = eager_runtime.get()
    torch.manual_seed(0)
    h = args.hidden
    blocks = []
    for _ in range(args.layers // 2):
        blocks += [torch.nn.Linear(h, h), torch.nn.Tanh(),
                   torch.nn.Linear(h, 2 * h), torch.nn.Tanh(),
                   torch.nn.Linear(2 * h, h)]
    model = torch.nn.Sequential(*blocks, torch.nn.Linear(h, 1))
    n_params = sum(1 for _ in model.parameters())
    total_bytes = sum(p.numel() * 4 for p in model.parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=1e-3),
        named_parameters=model.named_parameters())
    x = torch.randn(32, h)
    y = x.sum(dim=1, keepdim=True)

    def one_step():
        opt.zero_grad()
        torch.nn.functional.mse_loss(model(x), y).backward()
        opt.step()

    for _ in range(args.warmup):
        one_step()
    hits0 = rt.cache_hits() if rt else 0
    times = []
    for _ in range(args.steps):
        t0 = time.perf_counter()
        one_step()
        times.append(time.perf_counter() - t0)
    med = sorted(times)[len(times) // 2]
    result = {
        "mode": args.mode,
        "nproc": hvd.cross_size(),
        "params": n_params,
        "mbytes_per_step": round(total_bytes / 2**20, 1),
        "steps_per_s": round(1.0 / med, 3),
    }
    if rt is not None:
        result["cache_hit_rate"] = round(
            (rt.cache_hits() - hits0) / max(len(times) * n_params, 1), 3)
    if hvd.cross_rank() == 0:
        print("EAGER-BENCH " + json.dumps(result), flush=True)
    hvd.shutdown()
    return result


# --- driver -------------------------------------------------------------------


MODES = ("direct", "native", "autotune", "torch-direct", "torch-native")


def spawn(mode: str, args) -> dict:
    import socket

    from horovod_tpu.runner import launch
    from horovod_tpu.runner.hosts import HostSpec

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    out_dir = os.path.join(args.output_dir, mode)
    # Workers inherit the driver's full environment (XLA/thread config
    # materially changes CPU collective throughput) with the per-mode
    # knobs overriding.  Only --xla_force_host_platform_device_count is
    # stripped from XLA_FLAGS: the test harness exports it (8 virtual
    # chips), which would silently change both the semantics
    # (chip-weighted local_size) and the timings being compared; other
    # user XLA flags stay in force.
    xla_flags = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env = {
        **os.environ,
        "XLA_FLAGS": xla_flags,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
        "PALLAS_AXON_POOL_IPS": "",
        "HOROVOD_NUM_PROC": str(args.nproc),
        "HOROVOD_JAX_PORT": str(free_port()),
        "HOROVOD_NATIVE_PORT": str(free_port()),
        "HOROVOD_NATIVE": "0" if mode.endswith("direct") else "1",
        "HOROVOD_CYCLE_TIME": str(args.cycle_ms),
    }
    cmd = [sys.executable, os.path.abspath(__file__), "--worker",
           "--mode", mode, "--steps", str(args.steps),
           "--warmup", str(args.warmup), "--layers", str(args.layers),
           "--hidden", str(args.hidden),
           "--autotune-steps", str(args.autotune_steps),
           "--bo-samples", str(args.bo_samples),
           "--cycle-ms", str(args.cycle_ms)]
    rc = launch.launch_job(cmd, [HostSpec("localhost", 1)] * args.nproc,
                           env=env, output_filename=out_dir)
    if rc != 0:
        err_path = os.path.join(out_dir, "rank.0.stderr")
        err = (open(err_path).read()[-3000:]
               if os.path.exists(err_path) else "<no rank output captured>")
        raise SystemExit(f"mode {mode} failed (rc={rc}):\n{err}")
    for line in open(os.path.join(out_dir, "rank.0.stdout")):
        # lines may carry the launcher's "[rank]<stream>:" tee prefix
        if "EAGER-BENCH " in line:
            return json.loads(line.split("EAGER-BENCH ", 1)[1])
    raise SystemExit(f"mode {mode}: no EAGER-BENCH line in rank 0 stdout")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--mode", default="native", choices=MODES)
    ap.add_argument("--modes", default="direct,native,autotune,"
                    "torch-direct,torch-native")
    ap.add_argument("--nproc", type=int, default=2)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--layers", type=int, default=24)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--autotune-steps", type=int, default=60)
    ap.add_argument("--bo-samples", type=int, default=8)
    ap.add_argument("--cycle-ms", type=float, default=1.0)
    ap.add_argument("--output-dir", default="/tmp/eager_fusion_bench")
    args = ap.parse_args()

    if args.worker:
        import jax

        jax.config.update("jax_platforms", "cpu")
        if args.mode.startswith("torch"):
            run_torch_mode(args)
        else:
            run_allreduce_mode(args)
        return

    results = [spawn(m, args) for m in args.modes.split(",")]
    print(f"\n== eager/native control plane, {args.nproc} processes ==")
    for r in results:
        extra = []
        if "cache_hit_rate" in r:
            extra.append(f"cache_hit={r['cache_hit_rate']:.0%}")
        if "fusion_ratio" in r:
            extra.append(f"fusion={r['fusion_ratio']}x")
        if "tuned_settings" in r:
            extra.append(f"tuned={r['tuned_settings']}")
        print(f"{r['mode']:>13}: {r['steps_per_s']:7.3f} steps/s  "
              + " ".join(extra))
    by_mode = {r["mode"]: r for r in results}
    if "native" in by_mode and "direct" in by_mode:
        speedup = (by_mode["native"]["steps_per_s"]
                   / by_mode["direct"]["steps_per_s"])
        print(json.dumps({
            "metric": "eager_fusion_native_vs_direct",
            "value": round(speedup, 2), "unit": "x",
            "detail": {m: r.get("steps_per_s") for m, r in by_mode.items()},
            "native_fusion_ratio": by_mode["native"].get("fusion_ratio"),
            "native_cache_hit_rate": by_mode["native"].get("cache_hit_rate"),
        }))


if __name__ == "__main__":
    main()
