"""A/B the MoE dispatch mechanisms on the current chip, PAIRWISE in one
process.

Chip-state variance dominates cross-process comparisons on the tunneled
TPU (±15–50% swings between runs), so comparisons interleave inside one
process.  An E=8 model with f32 AdamW state is ~5 GB, so only two live
at once: each comparison is a PAIR round-robined for several rounds
(minimum kept), with the sort-dispatch candidate appearing in every pair
as the common reference.

Shapes default to the docs/benchmarks.md E-sweep row (d1024 L8 seq2048
b4 d_ff2048, flash + remat(dots)) so rows are directly comparable.

Run:  python benchmarks/moe_dispatch_ab.py [--es 2 8]
"""

from __future__ import annotations

import argparse
import dataclasses
import gc
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--d-model", type=int, default=1024)
    ap.add_argument("--n-layers", type=int, default=8)
    ap.add_argument("--n-heads", type=int, default=16)
    ap.add_argument("--d-ff", type=int, default=2048)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--capacity-factor", type=float, default=1.25)
    ap.add_argument("--es", type=int, nargs="+", default=[2, 8])
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--steps-per-round", type=int, default=3)
    ap.add_argument("--pairs", nargs="+",
                    default=["cumsum", "dense-dispatch", "dense-mlp"],
                    help="which comparisons to run against switch-sort "
                         "(each pair compiles two full models; select a "
                         "subset to fit a time budget)")
    ap.add_argument("--prefill", action="store_true",
                    help="instead of training steps, A/B the PREFILL "
                         "pass (dropless grouped-matmul dispatch vs the "
                         "dense every-expert oracle) at each E")
    args = ap.parse_args()

    from horovod_tpu.models import transformer as T

    base = T.TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff, max_seq=args.seq,
        attention_impl="flash", capacity_factor=args.capacity_factor,
        remat=True, remat_policy="dots",
    )
    batch = T.synthetic_batch(0, base, batch=args.batch_size, seq=args.seq)
    opt = optax.adamw(3e-4)
    tokens = args.batch_size * args.seq

    def build(cfg):
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state):
            loss, g = jax.value_and_grad(
                lambda p: T.loss_fn(p, batch, cfg))(params)
            up, opt_state = opt.update(g, opt_state, params)
            return optax.apply_updates(params, up), opt_state, loss

        params, opt_state, loss = step(params, opt_state)  # compile+warm
        float(loss)
        return [step, params, opt_state]

    def ab(named_cfgs):
        """Round-robin the pair; returns {name: best_sec_per_step}."""
        slots = {name: build(cfg) for name, cfg in named_cfgs}
        best = {name: float("inf") for name, _ in named_cfgs}
        for _ in range(args.rounds):
            for name, slot in slots.items():
                step, params, opt_state = slot
                t0 = time.perf_counter()
                for _ in range(args.steps_per_round):
                    params, opt_state, loss = step(params, opt_state)
                float(loss)  # value fetch closes the timing loop (axon)
                best[name] = min(
                    best[name],
                    (time.perf_counter() - t0) / args.steps_per_round)
                slot[1], slot[2] = params, opt_state
        del slots
        gc.collect()
        return best

    kind = jax.devices()[0].device_kind
    print(f"chip={kind} d{args.d_model} L{args.n_layers} seq{args.seq} "
          f"b{args.batch_size} d_ff{args.d_ff} cf{args.capacity_factor:g} "
          f"remat=dots flash")

    if args.prefill:
        # A/B the serving prefill: dropless vs dense dispatch, one
        # params set, two jitted prefill fns interleaved.
        for E in args.es:
            cfg = dataclasses.replace(base, n_experts=E, remat=False,
                                      attention_impl="reference")
            params = T.init_params(jax.random.PRNGKey(0), cfg)
            prompt = batch["tokens"]
            fns = {}
            for impl in ("dropless", "dense"):
                fns[impl] = jax.jit(lambda p, t, impl=impl: T.prefill(
                    p, t, T.init_cache(cfg, t.shape[0], args.seq), cfg,
                    moe_impl=impl)[0])
                float(jnp.sum(fns[impl](params, prompt)))  # compile
            best = {k: float("inf") for k in fns}
            for _ in range(args.rounds):
                for impl, fn in fns.items():
                    t0 = time.perf_counter()
                    for _ in range(args.steps_per_round):
                        out = fn(params, prompt)
                    float(jnp.sum(out))
                    best[impl] = min(
                        best[impl],
                        (time.perf_counter() - t0) / args.steps_per_round)
            print(f"E={E} prefill: dropless {best['dropless'] * 1e3:.1f}ms"
                  f" | dense {best['dense'] * 1e3:.1f}ms | dropless = "
                  f"{best['dense'] / best['dropless']:.2f}x faster")
        return

    for E in args.es:
        moe = dataclasses.replace(base, n_experts=E)
        sort_cfg = dataclasses.replace(moe, moe_dispatch="sort")
        all_pairs = {
            "cumsum": (("switch-cumsum",
                        dataclasses.replace(moe, moe_dispatch="cumsum")),
                       ("switch-sort", sort_cfg)),
            "dense-dispatch": (("dense-dispatch",
                                dataclasses.replace(moe, moe_impl="dense")),
                               ("switch-sort", sort_cfg)),
            "dense-mlp": (("dense-mlp", base), ("switch-sort", sort_cfg)),
        }
        for key in args.pairs:
            pair = all_pairs[key]
            best = ab(pair)
            names = list(best)
            a, b = names[0], names[1]
            print(f"E={E}  {a:<15} {tokens / best[a]:>8.0f} tok/s | "
                  f"{b:<12} {tokens / best[b]:>8.0f} tok/s | "
                  f"{a} = {best[b] / best[a]:.2f}x of {b}")


if __name__ == "__main__":
    main()
