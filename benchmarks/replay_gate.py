"""Replay-based perf-regression gate (docs/serving.md "Autotuning").

Replays the COMMITTED miniature journal trace
(``benchmarks/data/replay_trace.jsonl``) through a freshly built
engine — same seeded toy model the trace was captured against — and
fails if either:

* any request's replayed output is not token-identical to what the
  journal recorded (greedy decode is a pure function of the token
  sequence, sampled decode of (sequence, seed): a mismatch means the
  serving oracle broke), or
* the replay score drops more than ``--tolerance`` (default 20%)
  below the committed baseline (``benchmarks/data/replay_baseline.
  json``): a serving-path perf regression.

CPU smoke by design: the committed trace is tiny (toy model, short
prompts) so the gate runs anywhere tier-1 does.  After an INTENDED
serving change shifts the score, re-record with::

    python benchmarks/replay_gate.py --record

which regenerates BOTH files — the trace (fresh capture of the fixed
workload below) and the baseline (score of replaying it).  Commit the
pair together; a baseline from someone else's machine gates relative
score, not absolute wall-clock, so the 20% band absorbs host noise
(score is dominated by tokens-per-tick, which is deterministic for a
synchronous replay).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
TRACE = os.path.join(DATA, "replay_trace.jsonl")
BASELINE = os.path.join(DATA, "replay_baseline.json")


def _build_engine(journal_path=None):
    import jax
    import jax.numpy as jnp

    from horovod_tpu import serving
    from horovod_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    return serving.InferenceEngine(
        params, cfg, serving.EngineConfig(
            n_slots=4, max_len=48, max_queue_depth=64,
            max_prefills_per_tick=2, prefill_chunk_tokens=16,
            tick_timeout=0.0, journal_path=journal_path))


def record() -> dict:
    """Capture the fixed workload into the committed trace, then
    score a replay of it as the new baseline."""
    import numpy as np

    from horovod_tpu.tuning.replay import read_trace, replay, warm_lens

    os.makedirs(DATA, exist_ok=True)
    if os.path.exists(TRACE):
        os.remove(TRACE)
    engine = _build_engine(journal_path=TRACE)
    engine.warmup([6, 16, 30])
    rng = np.random.RandomState(7)
    futs = []
    for i in range(20):
        n = int(rng.randint(3, 31))
        prompt = [int(x) for x in rng.randint(1, 60, size=n)]
        sampled = (i % 4 == 0)
        futs.append(engine.submit(
            prompt, max_new_tokens=int(rng.randint(4, 9)),
            temperature=0.7 if sampled else 0.0,
            seed=100 + i if sampled else None,
            priority="interactive" if i % 3 else "batch"))
    while not all(f.done() for f in futs):
        engine.step()
    for f in futs:
        f.result(timeout=1)
    engine.stop()

    trace = read_trace(TRACE)
    engine = _build_engine()
    engine.warmup(warm_lens(trace, engine))
    report = replay(engine, trace, timing="afap")
    engine.stop()
    assert report.token_identical == report.compared, \
        f"fresh capture must replay identically: {report.mismatched_ids}"
    baseline = {"score": report.score,
                "tokens_per_tick": report.tokens_per_tick,
                "requests": report.requests,
                "report": report.to_json()}
    with open(BASELINE, "w", encoding="utf-8") as f:
        json.dump(baseline, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"recorded {report.requests} requests -> {TRACE}\n"
          f"baseline score {report.score} -> {BASELINE}")
    return baseline


def gate(tolerance: float = 0.2) -> dict:
    """Replay the committed trace; return the verdict dict (and the
    full report).  Raises SystemExit(1) on failure when run as a
    script — callers (the slow-marked test) check ``ok`` instead."""
    from horovod_tpu.tuning.replay import read_trace, replay, warm_lens

    with open(BASELINE, "r", encoding="utf-8") as f:
        baseline = json.load(f)
    trace = read_trace(TRACE)
    engine = _build_engine()
    engine.warmup(warm_lens(trace, engine))
    report = replay(engine, trace, timing="afap")
    engine.stop()
    floor = baseline["score"] * (1.0 - tolerance)
    verdict = {
        "ok": (report.token_identical == report.compared
               and report.score >= floor
               and report.decode_recompiles == 0),
        "token_identical": report.token_identical,
        "compared": report.compared,
        "mismatched_ids": report.mismatched_ids,
        "score": report.score,
        "baseline_score": baseline["score"],
        "floor": round(floor, 6),
        "decode_recompiles": report.decode_recompiles,
        "report": report.to_json(),
    }
    return verdict


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--record", action="store_true",
                    help="regenerate the committed trace AND baseline "
                         "(after an intended serving change)")
    ap.add_argument("--tolerance", type=float, default=0.2,
                    help="allowed fractional score drop vs baseline")
    args = ap.parse_args()
    if args.record:
        record()
        return 0
    verdict = gate(args.tolerance)
    print(json.dumps({k: v for k, v in verdict.items()
                      if k != "report"}))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
