"""One-off profiling harness for the ResNet-50 bench step (Task: chase MFU).

Times the same compiled step as bench.py across configurations and prints
XLA cost-analysis FLOPs so MFU is measured, not estimated.

Usage: python benchmarks/profile_resnet.py [--batch 128 256] [--scan 0 8]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


TPU_PEAK_BF16 = {
    # chip -> peak bf16 TFLOP/s (public spec sheets)
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
}


def peak_flops() -> float:
    kind = jax.devices()[0].device_kind
    for k, v in TPU_PEAK_BF16.items():
        if kind.startswith(k):
            return v
    return float("nan")


def build(batch_size: int, scan_len: int, image_size: int = 224):
    import horovod_tpu as hvd
    from horovod_tpu import spmd
    from horovod_tpu.models import resnet
    from jax.sharding import PartitionSpec as P, NamedSharding

    hvd.init()
    model = resnet.create("ResNet50", num_classes=1000)
    rng = jax.random.PRNGKey(42)
    variables = resnet.init_variables(model, rng, image_size, batch=2)
    params, batch_stats = variables["params"], variables["batch_stats"]

    opt = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
    opt_state = opt.init(params)

    def loss_fn(p, batch):
        images, labels, stats = batch["images"], batch["labels"], batch["stats"]
        logits, new_model_state = model.apply(
            {"params": p, "batch_stats": stats}, images, train=True,
            mutable=["batch_stats"],
        )
        one_hot = jax.nn.one_hot(labels, 1000)
        loss = optax.softmax_cross_entropy(logits, one_hot).mean()
        return loss, new_model_state["batch_stats"]

    axis = hvd.AXIS
    mesh = hvd.mesh()

    def _one(params, opt_state, stats, images, labels):
        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, {"images": images, "labels": labels, "stats": stats}
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, new_stats, jax.lax.pmean(loss, axis)

    if scan_len:
        def _step(params, opt_state, stats, images, labels):
            def body(carry, _):
                p, o, s = carry
                p, o, s, loss = _one(p, o, s, images, labels)
                return (p, o, s), loss
            (params, opt_state, stats), losses = jax.lax.scan(
                body, (params, opt_state, stats), None, length=scan_len
            )
            return params, opt_state, stats, losses[-1]
    else:
        _step = _one

    step = jax.jit(
        spmd.shard(
            _step,
            in_specs=(P(), P(), P(), P(axis), P(axis)),
            out_specs=(P(), P(), P(), P()),
            mesh=mesh,
        ),
        donate_argnums=(0, 1, 2),
    )

    n = hvd.size()
    global_batch = batch_size * n
    sh = NamedSharding(mesh, P(axis))
    images = jax.device_put(
        jnp.asarray(np.random.rand(global_batch, image_size, image_size, 3),
                    jnp.bfloat16), sh)
    labels = jax.device_put(
        jnp.asarray(np.random.randint(0, 1000, (global_batch,)), jnp.int32), sh)
    return step, (params, opt_state, batch_stats, images, labels), global_batch


def run(batch_size: int, scan_len: int, iters: int = 5, inner: int = 10):
    step, args, global_batch = build(batch_size, scan_len)
    params, opt_state, stats, images, labels = args

    lowered = step.lower(params, opt_state, stats, images, labels)
    compiled = lowered.compile()
    from horovod_tpu.obs import xprof

    report = xprof.introspect(compiled, fn="profile_resnet_step")
    flops = report.flops if report.flops is not None else float("nan")

    # warmup
    for _ in range(2):
        params, opt_state, stats, loss = step(params, opt_state, stats, images, labels)
    float(np.asarray(jax.device_get(loss)))

    steps_per_call = scan_len or 1
    rates = []
    for _ in range(iters):
        t0 = time.perf_counter()
        for _ in range(inner):
            params, opt_state, stats, loss = step(
                params, opt_state, stats, images, labels)
        float(np.asarray(jax.device_get(loss)))
        dt = time.perf_counter() - t0
        rates.append(global_batch * inner * steps_per_call / dt)

    med = float(np.median(rates))
    step_flops = flops  # for the whole jitted call
    flops_per_img = step_flops / (global_batch * steps_per_call)
    tflops = med * flops_per_img / 1e12
    mfu = med * flops_per_img / peak_flops()
    print(f"batch={batch_size} scan={scan_len}: {med:.1f} img/s  "
          f"flops/img={flops_per_img/1e9:.2f}G  {tflops:.1f} TF/s  MFU={mfu*100:.1f}%")
    return med


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, nargs="+", default=[128])
    ap.add_argument("--scan", type=int, nargs="+", default=[0])
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--inner", type=int, default=10)
    args = ap.parse_args()
    for b in args.batch:
        for s in args.scan:
            run(b, s, args.iters, args.inner)


if __name__ == "__main__":
    main()
