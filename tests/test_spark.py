"""Spark integration tests with a mocked SparkContext.

Reference test strategy: ``test/test_spark.py`` runs against a local
SparkSession; pyspark is not installable in this image (documented gate in
``horovod_tpu/spark/__init__.py``), so these tests drive the REAL driver/
task plumbing — registration over the signed KV, ring NIC probe,
host-contiguous rank assignment, env wiring, fn shipping, result ordering,
failure propagation — through a SparkContext stand-in whose "executors"
are real forked processes (like Spark's python workers), not the local-
launcher fallback path.
"""

import multiprocessing
import os
import queue

import cloudpickle
import pytest

from horovod_tpu import spark as hvd_spark
from horovod_tpu.spark.driver import SparkDriverService
from horovod_tpu.spark import task as task_mod


def _worker(payload, index, q, extra_env):
    try:
        if extra_env.pop("__SCRUB_SECRET__", None):
            # Simulate a REAL executor on another machine: forked workers
            # inherit the driver's env, a remote one would not — the job
            # secret must arrive through the task closure instead.
            os.environ.pop("HOROVOD_SECRET_KEY", None)
        os.environ.update(extra_env)
        f = cloudpickle.loads(payload)
        q.put((index, "ok", list(f(index, iter([index])))))
    except BaseException as e:  # noqa: BLE001
        q.put((index, "error", repr(e)))


class FakeRDD:
    def __init__(self, sc, n):
        self.sc = sc
        self.n = n
        self._fn = None

    def mapPartitionsWithIndex(self, f):
        self._fn = f
        return self

    def collect(self):
        if self.sc.drop_tasks:
            raise RuntimeError("job group cancelled")  # executor starvation
        ctx = multiprocessing.get_context("fork")
        q = ctx.Queue()
        payload = cloudpickle.dumps(self._fn)
        procs = []
        for i in range(self.n):
            extra = {"HOROVOD_HOST_HASH": self.sc.host_hash_for(i)}
            if self.sc.scrub_secret:
                extra["__SCRUB_SECRET__"] = "1"
            p = ctx.Process(target=_worker, args=(payload, i, q, extra))
            p.start()
            procs.append(p)
        results = {}
        try:
            for _ in range(self.n):
                idx, kind, val = q.get(timeout=120)
                if kind == "error":
                    raise RuntimeError(f"task {idx} failed: {val}")
                results[idx] = val
        finally:
            for p in procs:
                p.join(timeout=10)
                if p.is_alive():
                    p.terminate()
        # Spark preserves partition order in collect()
        return [r for i in sorted(results) for r in results[i]]


class FakeSparkContext:
    """The subset of the SparkContext surface horovod_tpu.spark.run uses,
    with executors as forked processes."""

    defaultParallelism = 2

    def __init__(self, host_hashes=None, drop_tasks=False,
                 scrub_secret=False):
        self.host_hashes = host_hashes or {}
        self.drop_tasks = drop_tasks
        self.scrub_secret = scrub_secret
        self.job_groups = []
        self.cancelled = []

    def host_hash_for(self, index):
        return self.host_hashes.get(index, "testhost")

    def parallelize(self, rng, num_slices):
        assert len(list(rng)) == num_slices
        return FakeRDD(self, num_slices)

    def setJobGroup(self, gid, desc, interruptOnCancel=False):
        self.job_groups.append(gid)

    def cancelJobGroup(self, gid):
        self.cancelled.append(gid)


def _fn_report(tag):
    """Runs inside the forked 'executor': report the env the task wired."""
    return {
        "tag": tag,
        "rank": os.environ["HOROVOD_RANK"],
        "size": os.environ["HOROVOD_NUM_PROC"],
        "local_rank": os.environ["HOROVOD_LOCAL_RANK"],
        "local_size": os.environ["HOROVOD_LOCAL_SIZE"],
        "coord": os.environ["HOROVOD_COORDINATOR_ADDR"],
        "pid": os.getpid(),
    }


class TestSparkRunPath:
    def test_two_tasks_end_to_end(self):
        sc = FakeSparkContext()
        out = hvd_spark._spark_run(
            sc, _fn_report, ("t1",), {}, num_proc=2, env={"MYVAR": "7"},
            verbose=0, start_timeout=60)
        assert len(out) == 2
        assert [o["rank"] for o in out] == ["0", "1"]  # rank-ordered
        assert all(o["size"] == "2" for o in out)
        assert all(o["tag"] == "t1" for o in out)
        # fn really ran in separate processes (Spark python workers)
        assert len({o["pid"] for o in out}) == 2
        assert os.getpid() not in {o["pid"] for o in out}
        assert sc.job_groups, "job group must be set for cancellation"

    def test_multi_host_rank_assignment(self):
        # 4 tasks on 2 "hosts" interleaved: ranks must come out
        # host-contiguous with correct local_rank/local_size.
        sc = FakeSparkContext(
            host_hashes={0: "hostB", 1: "hostA", 2: "hostB", 3: "hostA"})
        out = hvd_spark._spark_run(
            sc, _fn_report, ("t2",), {}, num_proc=4, env=None,
            verbose=0, start_timeout=60)
        by_rank = {int(o["rank"]): o for o in out}
        assert sorted(by_rank) == [0, 1, 2, 3]
        assert all(o["local_size"] == "2" for o in out)
        assert sorted(int(o["local_rank"]) for o in out) == [0, 0, 1, 1]

    def test_task_failure_propagates(self):
        sc = FakeSparkContext()

        def boom():
            raise ValueError("task exploded")

        with pytest.raises(RuntimeError, match="Spark job failed"):
            hvd_spark._spark_run(sc, boom, (), {}, num_proc=2, env=None,
                                 verbose=0, start_timeout=60)

    def test_secret_ships_in_task_closure(self, monkeypatch):
        # Signed-KV mode with executors whose env does NOT carry the
        # secret (a real cluster's remote machines): the key must travel
        # inside the task closure or no task can read the KV at all.
        from horovod_tpu.runner import secret

        key = secret.make_secret_key()
        monkeypatch.setenv(secret.ENV_KEY, key)
        sc = FakeSparkContext(scrub_secret=True)

        def fn():
            return os.environ.get("HOROVOD_SECRET_KEY")

        out = hvd_spark._spark_run(sc, fn, (), {}, num_proc=2, env=None,
                                   verbose=0, start_timeout=60)
        assert out == [key, key]

    def test_registration_timeout_cancels_job_group(self):
        sc = FakeSparkContext(drop_tasks=True)
        with pytest.raises(Exception):
            hvd_spark._spark_run(
                sc, _fn_report, ("t",), {}, num_proc=2, env=None,
                verbose=0, start_timeout=3)
        assert sc.cancelled == sc.job_groups


class TestSparkDispatch:
    def test_run_dispatches_to_spark_branch(self, monkeypatch):
        """hvd_spark.run() itself (not just _spark_run) must take the
        Spark branch when a pyspark module with an active context is
        importable — covers the dispatch glue: module import, active-
        context lookup, argument forwarding."""
        import sys
        import types

        sc = FakeSparkContext()
        fake_pyspark = types.ModuleType("pyspark")

        class _SC:
            _active_spark_context = sc

        fake_pyspark.SparkContext = _SC
        monkeypatch.setitem(sys.modules, "pyspark", fake_pyspark)

        out = hvd_spark.run(_fn_report, ("dispatch",), num_proc=2,
                            verbose=0)
        assert [o["rank"] for o in out] == ["0", "1"]
        assert all(o["tag"] == "dispatch" for o in out)
        assert sc.job_groups, "must have gone through _spark_run"


class TestRankAssignment:
    def test_host_contiguous(self):
        tasks = [
            {"index": 0, "host_hash": "b", "addrs": ["1.1.1.1"]},
            {"index": 1, "host_hash": "a", "addrs": ["2.2.2.2"]},
            {"index": 2, "host_hash": "b", "addrs": ["1.1.1.1"]},
        ]
        m = SparkDriverService.assign_ranks(tasks)
        # host "a" sorts first: its task gets rank 0; host b contiguous.
        assert m == {1: 0, 0: 1, 2: 2}

    def test_host_hash_env_override(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_HOST_HASH", "custom")
        assert task_mod.host_hash() == "custom"

    def test_host_hash_stable(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_HOST_HASH", raising=False)
        assert task_mod.host_hash() == task_mod.host_hash()


class TestFallback:
    def test_run_without_pyspark_uses_local_launcher(self, monkeypatch):
        calls = {}

        def fake_run(fn, args, kwargs, num_proc=None, env=None):
            calls["num_proc"] = num_proc
            return ["a", "b"]

        from horovod_tpu.runner import run_func
        monkeypatch.setattr(run_func, "run", fake_run)
        out = hvd_spark.run(lambda: None, num_proc=2, verbose=0)
        assert out == ["a", "b"]
        assert calls["num_proc"] == 2
