"""ElasticDriver fault-injection worker: one rank of a supervised
elastic job.

Driven by tests/test_elastic.py::TestElasticDriver — the full recovery
loop: ELASTIC_CRASH_RANK dies mid-training in epoch ELASTIC_CRASH_EPOCH
(after a commit), the driver detects it, survivors hit a CollectiveError
(peer gone mid-negotiation), roll back via ``elastic.run`` and exit with
EXIT_CODE_RESTART; the driver blacklists the failed host, re-rendezvouses
over the survivors (fresh epoch env/ports), and the respawned ranks
restore the last committed State and run to completion.
"""

import json
import os
import sys

sys.path.insert(0, os.environ["REPO"])

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import elastic  # noqa: E402

CKPT = os.environ["ELASTIC_CKPT"]
RESULTS = os.environ["ELASTIC_RESULTS"]
EPOCH = int(os.environ.get("HOROVOD_ELASTIC_EPOCH", "0"))
CRASH_RANK = int(os.environ.get("ELASTIC_CRASH_RANK", "-1"))
CRASH_EPOCH = int(os.environ.get("ELASTIC_CRASH_EPOCH", "0"))
CRASH_AT_STEP = int(os.environ.get("ELASTIC_CRASH_AT_STEP", "7"))
COMMIT_EVERY = 5
TOTAL_STEPS = 10

hvd.init()
rank = hvd.process_rank()
size = hvd.num_processes()

journal = open(os.path.join(RESULTS, f"journal.e{EPOCH}.r{rank}"), "w")

state = elastic.State(
    params={"w": np.zeros(8, np.float32)},
    step=0,
)
resumed_from = int(state.step) if state.restore(CKPT) else None


@elastic.run
def train(state):
    while int(state.step) < TOTAL_STEPS:
        step = int(state.step)
        grad = np.full(8, float(rank + 1), np.float32)
        reduced = hvd.allreduce(grad, hvd.Average, name=f"e{EPOCH}.g.{step}")
        state.params["w"] = state.params["w"] - 0.1 * np.asarray(reduced)
        state.step = step + 1
        journal.write(f"{step + 1}\n")
        journal.flush()
        if state.step % COMMIT_EVERY == 0:
            state.commit(CKPT)
            hvd.barrier()  # commit durable before anyone can crash past it
        if (rank == CRASH_RANK and EPOCH == CRASH_EPOCH
                and state.step == CRASH_AT_STEP):
            print(f"ELASTIC-WORKER-CRASH rank={rank} step={state.step}",
                  flush=True)
            os._exit(17)  # simulated host failure: no cleanup, no shutdown
    return int(state.step)


final_step = train(state)
checksum = float(np.sum(state.params["w"]))
with open(os.path.join(RESULTS, f"final.e{EPOCH}.r{rank}.json"), "w") as f:
    json.dump({"rank": rank, "size": size, "epoch": EPOCH,
               "step": final_step, "resumed_from": resumed_from,
               "checksum": checksum}, f)
journal.close()
hvd.shutdown()
print(f"ELASTIC-WORKER-OK rank={rank} step={final_step}", flush=True)
