"""Fleet-observability worker: one rank of a supervised job that only
*trains a pretend model* — `obs.training_step()` around a sleep — while
the real code under test runs underneath: the WorkerNotificationManager
publishes the structured heartbeat (step durations, step count) and the
registry export over the rendezvous KV, and the driver aggregates them
into /metrics + /fleet and flags the artificially slowed rank
(FLEET_SLOW_RANK x FLEET_SLOW_FACTOR).

Deliberately collective-free (like elastic_hang_worker.py): the fleet
path is KV-and-HTTP only, so the test stays fast and native-lib-free.
"""

import os
import sys
import time

sys.path.insert(0, os.environ["REPO"])

from horovod_tpu.elastic.worker import notification_manager  # noqa: E402
from horovod_tpu.obs import training_step  # noqa: E402
from horovod_tpu.obs.registry import default_registry  # noqa: E402

rank = int(os.environ["HOROVOD_RANK"])
step_s = float(os.environ.get("FLEET_STEP_S", "0.05"))
if rank == int(os.environ.get("FLEET_SLOW_RANK", "-1")):
    step_s *= float(os.environ.get("FLEET_SLOW_FACTOR", "5.0"))
run_s = float(os.environ.get("FLEET_RUN_S", "6.0"))

# A worker-local counter the driver's fleet view must SUM across ranks.
items = default_registry().counter(
    "fleet_test_items_total", "items processed by this rank",
    exist_ok=True)
# And a gauge it must roll up per-rank (min/median/max).
pace = default_registry().gauge(
    "fleet_test_step_pace_seconds", "configured step pace", exist_ok=True)
pace.set(step_s)

notification_manager.init()

deadline = time.monotonic() + run_s
steps = 0
while time.monotonic() < deadline:
    with training_step():
        time.sleep(step_s)
    items.inc(2)
    steps += 1

notification_manager.stop()
print(f"FLEET-WORKER-OK rank={rank} steps={steps}", flush=True)
