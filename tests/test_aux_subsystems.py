"""Auxiliary-subsystem tests: checkpoint/resume (orbax), HMAC secret,
NIC discovery handshake, TF/keras shim gating (roles of the reference's
test_timeline.py / secret usage / driver-task service tests)."""

import os

import jax
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import checkpoint
from horovod_tpu.runner import secret
from horovod_tpu.runner.rendezvous import KVClient, RendezvousServer


class TestCheckpoint:
    def _tree(self):
        return {
            "w": np.arange(12, dtype=np.float32).reshape(3, 4),
            "b": np.ones(4, np.float32),
            "inner": {"step": np.asarray(7)},
        }

    def test_save_restore_roundtrip(self, hvd, tmp_path):
        tree = self._tree()
        checkpoint.save(str(tmp_path / "ck"), tree)
        out = checkpoint.restore(str(tmp_path / "ck"),
                                 jax.tree_util.tree_map(np.zeros_like, tree))
        for a, b in zip(jax.tree_util.tree_leaves(out),
                        jax.tree_util.tree_leaves(tree)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_manager_retention_and_latest(self, hvd, tmp_path):
        mgr = checkpoint.CheckpointManager(str(tmp_path / "runs"),
                                           max_to_keep=2)
        assert mgr.latest_step() is None
        for s in (10, 20, 30):
            mgr.save(s, {"x": np.full(3, float(s))})
        assert mgr.all_steps() == [20, 30]  # 10 evicted
        step, tree = mgr.restore_latest({"x": np.zeros(3)})
        assert step == 30
        np.testing.assert_array_equal(tree["x"], np.full(3, 30.0))

    def test_restore_latest_empty(self, hvd, tmp_path):
        mgr = checkpoint.CheckpointManager(str(tmp_path / "empty"))
        step, tree = mgr.restore_latest({"x": np.ones(2)})
        assert step is None
        np.testing.assert_array_equal(tree["x"], np.ones(2))

    def test_async_save_roundtrip(self, hvd, tmp_path):
        """save_async returns immediately; wait() makes the write
        durable; the readback matches."""
        tree = self._tree()
        h = checkpoint.save_async(str(tmp_path / "ack"), tree)
        h.wait()
        out = checkpoint.restore(str(tmp_path / "ack"),
                                 jax.tree_util.tree_map(np.zeros_like, tree))
        for a, b in zip(jax.tree_util.tree_leaves(out),
                        jax.tree_util.tree_leaves(tree)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        h.wait()  # idempotent

    def test_restore_latest_skips_corrupt_newest(self, hvd, tmp_path):
        """A truncated newest checkpoint falls back to the previous
        intact one instead of raising (the crash-mid-write resume
        story)."""
        mgr = checkpoint.CheckpointManager(str(tmp_path / "cruns"),
                                           max_to_keep=3)
        mgr.save(1, {"x": np.full(3, 1.0)})
        mgr.save(2, {"x": np.full(3, 2.0)})
        # truncate every file in the newest step dir (torn write)
        newest = mgr._step_dir(2)
        for root, _, files in os.walk(newest):
            for f in files:
                open(os.path.join(root, f), "wb").close()
        with pytest.warns(UserWarning, match="step 2.*unreadable"):
            step, tree = mgr.restore_latest({"x": np.zeros(3)})
        assert step == 1
        np.testing.assert_array_equal(tree["x"], np.full(3, 1.0))

    def test_saves_are_atomic_tmp_invisible(self, hvd, tmp_path):
        """A crash-abandoned step_N.tmp directory is never listed nor
        restored; a clean save commits via rename (no .tmp left)."""
        mgr = checkpoint.CheckpointManager(str(tmp_path / "atomic"))
        mgr.save(5, {"x": np.full(2, 5.0)})
        assert not any(n.endswith(".tmp")
                       for n in os.listdir(mgr.directory))
        # simulate a crash mid-save: a half-written tmp for step 6
        os.makedirs(mgr._step_dir(6) + ".tmp")
        assert mgr.all_steps() == [5]
        step, tree = mgr.restore_latest({"x": np.zeros(2)})
        assert step == 5
        np.testing.assert_array_equal(tree["x"], np.full(2, 5.0))
        # the next save sweeps the crash-abandoned tmp (no disk leak)
        mgr.save(7, {"x": np.full(2, 7.0)})
        assert not os.path.isdir(mgr._step_dir(6) + ".tmp")
        assert mgr.all_steps() == [5, 7]

    def test_manager_async_saves(self, hvd, tmp_path):
        """async_saves=True: saves overlap the 'training' between them
        (at most one in flight); restore paths wait before reading;
        retention still holds."""
        mgr = checkpoint.CheckpointManager(str(tmp_path / "aruns"),
                                           max_to_keep=2, async_saves=True)
        for s in (1, 2, 3):
            mgr.save(s, {"x": np.full(3, float(s))})
        step, tree = mgr.restore_latest({"x": np.zeros(3)})
        assert step == 3
        np.testing.assert_array_equal(tree["x"], np.full(3, 3.0))
        mgr.wait()
        assert mgr.all_steps() == [2, 3]


class TestSecret:
    def test_sign_verify_roundtrip(self, monkeypatch):
        monkeypatch.setenv(secret.ENV_KEY, secret.make_secret_key())
        payload = secret.sign(b"hello")
        assert payload != b"hello"
        assert secret.verify(payload) == b"hello"

    def test_tamper_rejected(self, monkeypatch):
        monkeypatch.setenv(secret.ENV_KEY, secret.make_secret_key())
        payload = bytearray(secret.sign(b"hello"))
        payload[-1] ^= 0xFF
        with pytest.raises(ValueError, match="HMAC"):
            secret.verify(bytes(payload))

    def test_disabled_without_key(self, monkeypatch):
        monkeypatch.delenv(secret.ENV_KEY, raising=False)
        assert secret.sign(b"x") == b"x"
        assert secret.verify(b"x") == b"x"

    def test_kv_signed_end_to_end(self, monkeypatch):
        key = secret.make_secret_key()
        monkeypatch.setenv(secret.ENV_KEY, key)
        server = RendezvousServer(0)  # picks up the env key
        port = server.start()
        try:
            kv = KVClient("127.0.0.1", port)
            kv.put("s", "k", b"payload")
            assert kv.get("s", "k") == b"payload"
            # unsigned writer (no key) is rejected AT THE SERVER (403), so
            # a stray process can neither inject state nor DoS readers
            monkeypatch.delenv(secret.ENV_KEY, raising=False)
            from urllib import error as urlerror

            with pytest.raises(urlerror.HTTPError) as ei:
                kv.put("s", "raw", b"unsigned")
            assert ei.value.code == 403
            # keyless reader of a signed value fails loudly, not garbage
            with pytest.raises(ValueError, match="no HOROVOD_SECRET_KEY"):
                kv.get("s", "k")
        finally:
            server.stop()


class TestDiscovery:
    def test_ring_discovery_localhost(self):
        from horovod_tpu.runner import discovery

        server = RendezvousServer(0)
        port = server.start()
        try:
            import threading

            size = 3
            threads = [
                threading.Thread(
                    target=discovery.run_task_discovery,
                    args=(KVClient("127.0.0.1", port), r, size),
                    kwargs={"timeout": 30},
                )
                for r in range(size)
            ]
            for t in threads:
                t.start()
            routable = discovery.discover(
                KVClient("127.0.0.1", port), size, timeout=30)
            for t in threads:
                t.join(timeout=30)
            assert sorted(routable) == [0, 1, 2]
            for addr in routable.values():
                assert addr  # a concrete address string
        finally:
            server.stop()

    def test_local_addresses_nonempty(self):
        from horovod_tpu.runner import discovery

        assert discovery.local_addresses()


tf = pytest.importorskip("tensorflow")


class TestTensorFlowShim:
    """Role of the reference's test_tensorflow.py op/tape/optimizer tests
    at single-worker scope (multi-rank covered by the launcher workers)."""

    def test_allreduce(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf

        # Sum is chip-weighted (one process speaks for local_size chips);
        # Average is the identity at one process.
        ls = hvd_tf.local_size()
        x = tf.constant([1.0, 2.0, 3.0])
        out = hvd_tf.allreduce(x, op=hvd_tf.Sum)
        np.testing.assert_allclose(out.numpy(), [ls * 1.0, ls * 2.0, ls * 3.0])
        out = hvd_tf.allreduce(x)  # default Average
        np.testing.assert_allclose(out.numpy(), [1.0, 2.0, 3.0])

    def test_allgather_broadcast(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf

        x = tf.constant([[1.0, 2.0]])
        assert hvd_tf.allgather(x).shape == (1, 2)
        np.testing.assert_allclose(
            hvd_tf.broadcast(x, 0).numpy(), x.numpy())

    def test_broadcast_variables(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf

        v = tf.Variable([5.0, 6.0])
        hvd_tf.broadcast_variables([v], 0)
        np.testing.assert_allclose(v.numpy(), [5.0, 6.0])

    def test_distributed_gradient_tape(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf

        w = tf.Variable([2.0, 3.0])
        with hvd_tf.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = tf.reduce_sum(w * w)
        (g,) = tape.gradient(loss, [w])
        np.testing.assert_allclose(g.numpy(), [4.0, 6.0])

    def test_distributed_optimizer_trains(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf

        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(1, input_shape=(4,))])
        opt = hvd_tf.DistributedOptimizer(tf.keras.optimizers.SGD(0.05))
        x = tf.random.normal((64, 4), seed=0)
        y = tf.reduce_sum(x, axis=1, keepdims=True)
        losses = []
        for _ in range(20):
            with tf.GradientTape() as tape:
                loss = tf.reduce_mean((model(x) - y) ** 2)
            grads = tape.gradient(loss, model.trainable_variables)
            opt.apply_gradients(zip(grads, model.trainable_variables))
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses[::5]


class TestTFCompression:
    def test_tape_fp16_compression_close_to_exact(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf

        w = tf.Variable([[1.0, -2.0], [0.5, 3.0]])
        with tf.GradientTape() as t0:
            loss = tf.reduce_sum(w * w)
        exact = t0.gradient(loss, [w])[0].numpy()

        with hvd_tf.DistributedGradientTape(
                tf.GradientTape(),
                compression=hvd_tf.Compression.fp16) as tape:
            loss = tf.reduce_sum(w * w)
        (g,) = tape.gradient(loss, [w])
        # fp16 wire round-trip: close, dtype restored to f32
        assert g.dtype == tf.float32
        np.testing.assert_allclose(g.numpy(), exact, rtol=1e-3)

    def test_backward_passes_per_step_aggregates(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf

        v = tf.Variable([0.0])
        opt = hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(1.0), backward_passes_per_step=3)
        # two accumulation passes apply nothing...
        for g in ([1.0], [2.0]):
            opt.apply_gradients([(tf.constant(g), v)])
            np.testing.assert_allclose(v.numpy(), [0.0])
        # ...the third applies the mean of the window: (1+2+3)/3 = 2
        opt.apply_gradients([(tf.constant([3.0]), v)])
        np.testing.assert_allclose(v.numpy(), [-2.0])
        # next window starts fresh
        opt.apply_gradients([(tf.constant([6.0]), v)])
        np.testing.assert_allclose(v.numpy(), [-2.0])

    def test_optimizer_compression_trains(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf

        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(1, input_shape=(4,))])
        opt = hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.05),
            compression=hvd_tf.Compression.bf16)
        x = tf.random.normal((64, 4), seed=0)
        y = tf.reduce_sum(x, axis=1, keepdims=True)
        losses = []
        for _ in range(20):
            with tf.GradientTape() as tape:
                loss = tf.reduce_mean((model(x) - y) ** 2)
            grads = tape.gradient(loss, model.trainable_variables)
            opt.apply_gradients(zip(grads, model.trainable_variables))
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses[::5]


class TestKerasShim:
    def test_callbacks_in_fit(self, hvd):
        import horovod_tpu.keras as hvd_keras

        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(1, input_shape=(3,))])
        model.compile(optimizer=tf.keras.optimizers.SGD(0.05), loss="mse")
        x = np.random.randn(64, 3).astype(np.float32)
        y = x.sum(axis=1, keepdims=True)
        hist = model.fit(
            x, y, epochs=2, batch_size=16, verbose=0,
            callbacks=[
                hvd_keras.BroadcastGlobalVariablesCallback(0),
                hvd_keras.MetricAverageCallback(),
            ])
        assert len(hist.history["loss"]) == 2

    def test_lr_schedule_callback(self, hvd):
        import horovod_tpu.keras as hvd_keras

        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(1, input_shape=(3,))])
        model.compile(optimizer=tf.keras.optimizers.SGD(0.1, momentum=0.9),
                      loss="mse")
        x = np.random.randn(32, 3).astype(np.float32)
        y = x.sum(axis=1, keepdims=True)
        cb = hvd_keras.LearningRateScheduleCallback(
            multiplier=lambda epoch: 0.5 ** epoch, staircase=True)
        hist = model.fit(x, y, epochs=3, batch_size=16, verbose=0,
                         callbacks=[cb])
        # base LR read from the optimizer; epoch e runs at 0.1 * 0.5^e
        lrs = hist.history["lr"]
        np.testing.assert_allclose(lrs, [0.1, 0.05, 0.025], rtol=1e-5)
        # momentum correction restored after the adjusting batch
        assert abs(float(model.optimizer.momentum) - 0.9) < 1e-6

    def test_lr_warmup_callback(self, hvd):
        import horovod_tpu.keras as hvd_keras

        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(1, input_shape=(3,))])
        model.compile(optimizer=tf.keras.optimizers.SGD(0.1), loss="mse")
        x = np.random.randn(64, 3).astype(np.float32)
        y = x.sum(axis=1, keepdims=True)
        cb = hvd_keras.LearningRateWarmupCallback(
            warmup_epochs=2, steps_per_epoch=4, verbose=0)
        hist = model.fit(x, y, epochs=3, batch_size=16, verbose=0,
                         callbacks=[cb])
        # hvd.size() counts the 8 virtual chips: warmup ramps the LR from
        # base/8 toward base*1 at epoch warmup_epochs, then leaves it.
        lrs = hist.history["lr"]
        assert lrs[0] < lrs[1] <= lrs[2] * (1 + 1e-6), lrs
        assert abs(lrs[-1] - 0.1) / 0.1 < 0.25, lrs

    def test_load_model_rewraps(self, hvd, tmp_path):
        import horovod_tpu.keras as hvd_keras

        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(1, input_shape=(2,))])
        model.compile(optimizer=tf.keras.optimizers.Adam(1e-3), loss="mse")
        path = str(tmp_path / "model.keras")
        model.save(path)
        loaded = hvd_keras.load_model(path)
        assert loaded.optimizer is not None


class TestTFBroadcastGlobalVariables:
    def test_graph_mode_points_to_callback(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf

        with tf.Graph().as_default():
            with pytest.raises(
                NotImplementedError,
                match="BroadcastGlobalVariablesCallback",
            ):
                hvd_tf.broadcast_global_variables(0)

    def test_eager_raises_with_pointer(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf

        with pytest.raises(ValueError, match="broadcast_variables"):
            hvd_tf.broadcast_global_variables(0)

    def test_broadcast_callback_in_fit(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf

        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(1, input_shape=(3,))])
        model.compile(optimizer=tf.keras.optimizers.SGD(0.05), loss="mse")
        x = np.random.randn(32, 3).astype(np.float32)
        y = x.sum(axis=1, keepdims=True)
        cb = hvd_tf.BroadcastGlobalVariablesCallback(0)
        hist = model.fit(x, y, epochs=1, batch_size=16, verbose=0,
                         callbacks=[cb])
        assert cb._done
        assert len(hist.history["loss"]) == 1


class TestLogLevel:
    def test_env_configures_logger(self, monkeypatch):
        import logging

        from horovod_tpu import basics

        logger = logging.getLogger("horovod_tpu")
        old = logger.level
        try:
            monkeypatch.setenv("HOROVOD_LOG_LEVEL", "debug")
            basics._configure_logging()
            assert logger.level == logging.DEBUG
            monkeypatch.setenv("HOROVOD_LOG_LEVEL", "error")
            basics._configure_logging()
            assert logger.level == logging.ERROR
        finally:
            logger.setLevel(old)

    def test_native_logging_emits(self, tmp_path):
        """HOROVOD_LOG_LEVEL=info makes the native runtime log its init
        line (native/src/logging.h reads the same env the reference's
        logger did)."""
        import subprocess
        import sys

        code = (
            "import os\n"
            "os.environ['HOROVOD_LOG_LEVEL'] = 'info'\n"
            "os.environ.setdefault('HOROVOD_NUM_PROC', '1')\n"
            "from horovod_tpu import native\n"
            "rt = native.NativeRuntime()\n"
            "rt.init(0, 1, '127.0.0.1', 19393)\n"
            "rt.shutdown()\n"
        )
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=120,
        )
        assert r.returncode == 0, r.stderr
        assert "[hvd_native rank 0 Info] init:" in r.stderr


class TestTFFunctionAllreduce:
    def test_allreduce_inside_tf_function(self, hvd):
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        @tf.function
        def reduced_sum(t):
            return hvd_tf.allreduce(t, op=hvd_tf.Sum, name="tf.fn.t")

        ls = hvd_tf.local_size()
        x = tf.constant([1.0, 2.0, 3.0])
        out = reduced_sum(x)
        np.testing.assert_allclose(out.numpy(), [ls * v for v in (1., 2., 3.)])
        # re-invocation reuses the same trace + collective name
        out2 = reduced_sum(tf.constant([4.0, 5.0, 6.0]))
        np.testing.assert_allclose(out2.numpy(), [ls * v for v in (4., 5., 6.)])

    def test_auto_name_from_symbolic_tensor(self, hvd):
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        @tf.function
        def fn(t):
            return hvd_tf.allreduce(t * 2.0, op=hvd_tf.Average)

        out = fn(tf.constant([2.0]))
        np.testing.assert_allclose(out.numpy(), [4.0])

    def test_gradient_through_function_allreduce(self, hvd):
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        # The reference DistributedGradientTape pattern (reduce GRADIENTS)
        # composing with tf.function compute.
        v = tf.Variable([1.0, 2.0])
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(v * v)
        grads = tape.gradient(loss, [v])
        reduced = hvd_tf.allreduce(grads[0], op=hvd_tf.Average)
        np.testing.assert_allclose(reduced.numpy(), [2.0, 4.0])

    def test_tape_flows_through_eager_allreduce(self, hvd):
        """hvd.allreduce INSIDE a taped loss must be differentiable
        (reference tensorflow/mpi_ops.py:110-121 _allreduce_grad): the
        custom gradient is an allreduce of the upstream gradient — the
        numpy bridge must not silently detach the tape."""
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        ls = hvd_tf.local_size()
        v = tf.Variable([1.0, 2.0])
        with tf.GradientTape() as tape:
            y = hvd_tf.allreduce(v * v, op=hvd_tf.Sum, name="tape.e")
            loss = tf.reduce_sum(y)
        (g,) = tape.gradient(loss, [v])
        # y = ls * v^2 (chip-weighted Sum) so dL/dv = ls * 2v — and the
        # backward allreduce(dy, Sum) = ls * dy delivers exactly that:
        # the chip-weighted Sum is its own VJP.
        np.testing.assert_allclose(g.numpy(), [ls * 2.0, ls * 4.0])

    def test_tape_flows_through_function_allreduce(self, hvd):
        """Same through tf.function: the py_function bridge carries the
        custom gradient."""
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        ls = hvd_tf.local_size()
        v = tf.Variable([3.0])

        @tf.function
        def loss_fn():
            y = hvd_tf.allreduce(v * v, op=hvd_tf.Average, name="tape.f")
            return tf.reduce_sum(y)

        with tf.GradientTape() as tape:
            loss = loss_fn()
        (g,) = tape.gradient(loss, [v])
        # Average is the identity at one process (for any chip count):
        # grad(Average) is Average — also the identity — so g = 2v
        # exactly.  A backward that leaked the chip-weighted Sum would
        # return ls * 2v and fail this on the 8-virtual-chip test mesh.
        np.testing.assert_allclose(g.numpy(), [6.0])
        assert ls > 1, "test mesh must have >1 chip to discriminate"

    def test_sparse_cotangent_through_allreduce(self, hvd):
        """A loss that GATHERS rows of the reduced tensor produces an
        IndexedSlices cotangent; the backward must densify it instead of
        handing a dtype=object array to the native runtime."""
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        v = tf.Variable([[1.0, 2.0], [3.0, 4.0]])
        with tf.GradientTape() as tape:
            y = hvd_tf.allreduce(v, op=hvd_tf.Average, name="tape.sp")
            loss = tf.reduce_sum(tf.gather(y, [0]))
        (g,) = tape.gradient(loss, [v])
        g = tf.convert_to_tensor(g)
        np.testing.assert_allclose(g.numpy(), [[1.0, 1.0], [0.0, 0.0]])

    def test_tape_flows_through_allgather_and_broadcast(self, hvd):
        """allgather/broadcast carry the reference's registered gradients
        (mpi_ops.py:143-166, 186-201): process-level sum of the
        cotangent, slice own rows / zero on non-root.  Unlike allreduce
        (whose forward is chip-weighted), these forwards are process-
        level, so the tape gradient must be finite-difference-correct —
        NO local_size factor."""
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu.tensorflow as hvd_tf

        assert hvd_tf.local_size() > 1  # else this can't catch chip leaks
        v = tf.Variable([[1.0, 2.0]])
        with tf.GradientTape() as tape:
            y = hvd_tf.allgather(v, name="tape.ag")
            loss = tf.reduce_sum(y * 3.0)
        (g,) = tape.gradient(loss, [v])
        # d(3*sum(v))/dv == 3 exactly (allgather is the identity at one
        # process; a chip-weighted backward would return 3*local_size).
        np.testing.assert_allclose(g.numpy(), [[3.0, 3.0]])

        w = tf.Variable([5.0])
        with tf.GradientTape() as tape:
            y = hvd_tf.broadcast(w, 0, name="tape.bc")
            loss = tf.reduce_sum(y * 2.0)
        (g,) = tape.gradient(loss, [w])
        np.testing.assert_allclose(g.numpy(), [2.0])

        @tf.function
        def fn_loss():
            y = hvd_tf.allgather(v, name="tape.ag.fn")
            return tf.reduce_sum(y)

        with tf.GradientTape() as tape:
            loss = fn_loss()
        (g,) = tape.gradient(loss, [v])
        np.testing.assert_allclose(g.numpy(), [[1.0, 1.0]])


@pytest.mark.slow
class TestTFMultiProcess:
    def _spawn(self, tmp_path, scenario, nproc):
        import socket
        import sys

        from horovod_tpu.runner import launch
        from horovod_tpu.runner.hosts import HostSpec

        REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        out = tmp_path / "out"
        env = {
            "PATH": os.environ.get("PATH", ""),
            "REPO": REPO,
            "PALLAS_AXON_POOL_IPS": "",
            "HOROVOD_NUM_PROC": str(nproc),
            "HOROVOD_JAX_PORT": str(free_port()),
            "HOROVOD_NATIVE_PORT": str(free_port()),
        }
        args = [sys.executable, os.path.join(REPO, "tests", "tf_worker.py")]
        if scenario:
            args.append(scenario)
        rc = launch.launch_job(
            args,
            [HostSpec("localhost", 1)] * nproc,
            env=env,
            output_filename=str(out),
        )
        assert rc == 0, (out / "rank.0.stderr").read_text() + (
            out / f"rank.{nproc - 1}.stderr").read_text()
        for r in range(nproc):
            assert "TF-WORKER-OK" in (out / f"rank.{r}.stdout").read_text()

    def test_two_process_tf(self, tmp_path):
        self._spawn(tmp_path, None, 2)

    def test_tf_adasum_delta_two_process(self, tmp_path):
        """TF delta-model Adasum vs the pairwise oracle, 2 ranks
        (reference _DistributedAdasumOptimizer,
        tensorflow/__init__.py:313-407)."""
        self._spawn(tmp_path, "adasum", 2)


class TestTFAdasumDispatch:
    def test_factory_dispatch_and_single_process_identity(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf

        opt = hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.1), op=hvd_tf.Adasum)
        assert getattr(opt, "_hvd_adasum", False), type(opt).__mro__
        # With one process the Adasum-combined delta IS the local delta,
        # so one step must equal the unwrapped optimizer's step.
        v = tf.Variable([1.0, 2.0])
        g = tf.constant([0.5, -1.0])
        opt.apply_gradients([(g, v)])
        np.testing.assert_allclose(v.numpy(), [0.95, 2.1], rtol=1e-6)


class TestSparseAllreduce:
    def test_indexed_slices_single_process(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf

        slices = tf.IndexedSlices(
            values=tf.ones([2, 3]), indices=tf.constant([0, 2], tf.int64),
            dense_shape=tf.constant([4, 3], tf.int64))
        red = hvd_tf.allreduce(slices, op=hvd_tf.Average)
        assert isinstance(red, tf.IndexedSlices)
        np.testing.assert_allclose(red.values.numpy(), np.ones((2, 3)))

    def test_adasum_sparse_raises(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf

        slices = tf.IndexedSlices(
            values=tf.ones([1, 2]), indices=tf.constant([0], tf.int64))
        with pytest.raises(NotImplementedError):
            hvd_tf.allreduce(slices, op=hvd_tf.Adasum)


class TestEstimatorPlatformResolution:
    def test_explicit_platform_passthrough(self):
        from horovod_tpu.estimator.estimator import (
            EstimatorParams, resolve_platform)

        assert resolve_platform(EstimatorParams(jax_platform="cpu")) == "cpu"
        assert resolve_platform(EstimatorParams(jax_platform="tpu")) == "tpu"
        assert resolve_platform(EstimatorParams(jax_platform=None)) == ""

    def test_auto_falls_back_to_cpu_without_enough_tpus(self):
        from horovod_tpu.estimator.estimator import (
            EstimatorParams, resolve_platform)

        # Test session runs on the CPU backend: no TPUs visible -> cpu.
        assert resolve_platform(
            EstimatorParams(jax_platform="auto", num_proc=2)) == "cpu"
