"""Subprocess body for the pp x sp x ep triple-composition test
(tests/test_pipeline.py::TestPipelineTripleComposition): 1F1B pipeline
over pp, ring attention over sp, expert-parallel switch-MoE over ep, one
shard_map — loss and every gradient exact vs the unsharded reference.
Shares the ep shard/unshard helpers and the gradient-tree assertion with
test_pipeline.py (one source of truth for the gradient contract)."""

import dataclasses
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))
sys.path.insert(0, _here)

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.models import transformer as T
from test_pipeline import (
    _assert_grad_trees_match,
    _ep_shard_params,
    _ep_unshard_grads,
)

pp, sp, ep = 2, 2, 2
cfg = T.TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
    max_seq=16, dtype=jnp.float32, n_experts=4, capacity_factor=4.0,
    moe_impl="switch", moe_axis="ep", attention_impl="ring", n_kv_heads=2)
cfg_ref = dataclasses.replace(cfg, moe_axis=None,
                              attention_impl="reference")
params = T.init_params(jax.random.PRNGKey(0), cfg)
batch = T.synthetic_batch(0, cfg, batch=4)
l_ref, g_ref = jax.value_and_grad(
    lambda p: T.loss_fn(p, batch, cfg_ref))(params)

mesh = Mesh(np.array(jax.devices()).reshape(pp, sp, ep),
            axis_names=("pp", "sp", "ep"))


def inner(pr, b):
    pr_sh = _ep_shard_params(pr, cfg.n_experts, ep)
    loss, grads = T.pipelined_value_and_grad(
        pr_sh, b, cfg, axis_name="pp", schedule="1f1b")
    grads = _ep_unshard_grads(grads, cfg.n_experts, ep)
    loss = lax.pmean(loss, ("sp", "ep"))
    grads = jax.tree_util.tree_map(lambda x: lax.pmean(x, "sp"), grads)
    return loss, grads


l, g = jax.jit(jax.shard_map(
    inner, mesh=mesh, in_specs=(P(), P("ep", "sp")), out_specs=(P(), P()),
    check_vma=False))(params, batch)
np.testing.assert_allclose(float(l), float(l_ref), atol=1e-5)
_assert_grad_trees_match(g, g_ref)
print("TRIPLE-COMPOSITION-OK")
