"""Multi-process execution of the COMPILED GSPMD path — the pod shape.

The reference's product is N processes training synchronously under a
launcher (``run/gloo_run.py``: one process per slot; SURVEY.md §4 runs
every test body that way).  These tests spawn real processes through the
same ``horovod_tpu.runner`` launcher and run the compiled
``make_gspmd_train_step`` over a GLOBAL mesh that spans them:

* 2 processes × 4 virtual CPU devices each == one 8-device dp4×tp2 mesh;
* batches are global arrays assembled from per-process input shards
  (``DataLoader`` global-array mode);
* checkpoints are written/restored collaboratively (multihost orbax);
* the 2-process run must produce BIT-IDENTICAL per-step losses and
  final parameter checksums to the single-process 8-device run of the
  exact same program — the "works in the sandbox" ⇔ "works on the pod"
  equivalence.
"""

import os
import re
import socket
import sys

import pytest

pytestmark = pytest.mark.slow  # tier-1 budget: see tests/DURATIONS.md

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "gspmd_worker.py")

from horovod_tpu.runner import launch  # noqa: E402
from horovod_tpu.runner.hosts import HostSpec  # noqa: E402

OK_RE = re.compile(
    r"GSPMD-WORKER-OK rank=(\d+) nproc=(\d+) "
    r"losses=(\S+) resume=(\S+) check=(\S+)"
)
RESUME_RE = re.compile(
    r"GSPMD-RESUME-OK rank=(\d+) nproc=(\d+) resume=(\S+)"
)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_job(tmp_path, tag, nproc, local_devices, restore_from=None):
    out = tmp_path / tag
    ckpt = tmp_path / f"ckpt-{tag}"
    env = {
        "PATH": os.environ.get("PATH", ""),
        "REPO": REPO,
        "PALLAS_AXON_POOL_IPS": "",  # keep subprocesses off the TPU
        "HOROVOD_NUM_PROC": str(nproc),
        "HOROVOD_JAX_PORT": str(_free_port()),
        "HOROVOD_NATIVE_PORT": str(_free_port()),
        "GSPMD_LOCAL_DEVICES": str(local_devices),
        "GSPMD_CKPT_DIR": str(ckpt),
    }
    if restore_from is not None:
        env["GSPMD_RESTORE_FROM"] = str(restore_from)
    rc = launch.launch_job(
        [sys.executable, WORKER],
        [HostSpec("localhost", 1)] * nproc,
        env=env,
        output_filename=str(out),
    )
    stderr = "".join(
        (out / f"rank.{r}.stderr").read_text() for r in range(nproc)
        if (out / f"rank.{r}.stderr").exists()
    )
    assert rc == 0, stderr[-4000:]
    results = {}
    regex = RESUME_RE if restore_from is not None else OK_RE
    for r in range(nproc):
        text = (out / f"rank.{r}.stdout").read_text()
        m = regex.search(text)
        assert m, f"rank {r} produced no OK line:\n{text}\n{stderr[-2000:]}"
        if restore_from is not None:
            results[r] = dict(resume=m.group(3))
        else:
            results[r] = dict(
                losses=m.group(3), resume=m.group(4), check=m.group(5)
            )
    return results


class TestGspmdMultiProcess:
    def test_two_process_matches_single_process_bitwise(self, tmp_path):
        """The SAME compiled dp4×tp2 training program run as 2 processes
        × 4 devices and as 1 process × 8 devices must agree bit-for-bit
        on every step loss and on the final parameter checksum — plus
        each job internally proves multihost save→restore→resume
        replays its own losses exactly."""
        multi = _run_job(tmp_path, "np2", nproc=2, local_devices=4)
        single = _run_job(tmp_path, "np1", nproc=1, local_devices=8)

        # Both ranks of the 2-process job see identical replicated values.
        assert multi[0] == multi[1], (multi[0], multi[1])
        # Pod run ≡ sandbox run, bitwise.
        assert multi[0]["losses"] == single[0]["losses"], (
            multi[0]["losses"], single[0]["losses"])
        assert multi[0]["check"] == single[0]["check"], (
            multi[0]["check"], single[0]["check"])

        # Cross-topology resume: the checkpoint the 2-process job wrote
        # collaboratively restores into a DIFFERENT process layout (one
        # process, 8 devices) and continues bit-identically — pod
        # checkpoints are portable across deployment shapes (elastic
        # pod-resize resume).
        resumed = _run_job(tmp_path, "resume1", nproc=1, local_devices=8,
                           restore_from=tmp_path / "ckpt-np2")
        assert resumed[0]["resume"] == multi[0]["resume"], (
            resumed[0]["resume"], multi[0]["resume"])
