"""Subprocess body for the pipeline composition tests
(tests/test_pipeline.py): argv[1] selects the mesh —

  sp             (pp, sp):     1F1B x ring-attention sequence parallelism
  ep             (pp, ep):     1F1B x expert-parallel switch-MoE
  triple         (pp, sp, ep): all three in one shard_map
  sp_interleaved (pp, sp):     INTERLEAVED schedule (v=2) x ring attention
  sp_zigzag      (pp, sp):     1F1B x ZIGZAG ring (causal load balance)

Each asserts loss and EVERY parameter gradient exact vs the unsharded
single-device reference.  Run in subprocesses because the XLA CPU
runtime's collective rendezvous accumulates state across distinct
multi-axis meshes in one process and aborts (each composition passes
standalone).  Shares the ep shard/unshard helpers and the gradient-tree
assertion with test_pipeline.py (one source of truth)."""

import dataclasses
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))
sys.path.insert(0, _here)

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.models import transformer as T
from test_pipeline import (
    _assert_grad_trees_match,
    _ep_shard_params,
    _ep_unshard_grads,
)

MODE = sys.argv[1] if len(sys.argv) > 1 else "triple"

SCHEDULE = "1f1b"
ATTN_SP = "ring"
if MODE in ("sp", "sp_interleaved", "sp_zigzag"):
    pp, sp, ep = 2, 4, 1
    axes, shape = ("pp", "sp"), (2, 4)
    batch_spec = P(None, "sp")  # sequence sharded over sp
    if MODE == "sp_interleaved":
        SCHEDULE = "interleaved"  # v=2 virtual stages over pp=2
    elif MODE == "sp_zigzag":
        ATTN_SP = "ring_zigzag"
elif MODE == "ep":
    pp, sp, ep = 2, 1, 4
    axes, shape = ("pp", "ep"), (2, 4)
    batch_spec = P("ep")  # batch sharded over ep (dp-style)
elif MODE == "triple":
    pp, sp, ep = 2, 2, 2
    axes, shape = ("pp", "sp", "ep"), (2, 2, 2)
    batch_spec = P("ep", "sp")
else:
    raise SystemExit(f"unknown mode {MODE!r}")

n_experts = 4 * (ep > 1)
cfg = T.TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
    max_seq=16 if sp == 1 else 8 * sp, dtype=jnp.float32,
    n_experts=n_experts, capacity_factor=float(max(n_experts, 1)),
    moe_impl="switch", moe_axis="ep" if ep > 1 else None,
    attention_impl=ATTN_SP if sp > 1 else "reference", n_kv_heads=2)
cfg_ref = dataclasses.replace(cfg, moe_axis=None,
                              attention_impl="reference")
params = T.init_params(jax.random.PRNGKey(0), cfg)
batch = T.synthetic_batch(0, cfg, batch=4 if ep == 1 else 8 // sp)
l_ref, g_ref = jax.value_and_grad(
    lambda p: T.loss_fn(p, batch, cfg_ref))(params)
if MODE == "sp_zigzag":
    # Zigzag layout: shard columns permuted so device i holds global
    # chunks (i, 2P-1-i); the reference above used the UNPERMUTED batch
    # (loss mean and token/target pairing are permutation-invariant).
    from horovod_tpu.ops import attention as ATT

    zperm, _ = ATT.zigzag_perm(cfg.max_seq, sp)
    batch = {k: v[:, zperm] for k, v in batch.items()}

mesh = Mesh(np.array(jax.devices()).reshape(shape), axis_names=axes)


def inner(pr, b):
    pr_sh = _ep_shard_params(pr, cfg.n_experts, ep) if ep > 1 else pr
    loss, grads = T.pipelined_value_and_grad(
        pr_sh, b, cfg, axis_name="pp", schedule=SCHEDULE, n_virtual=2)
    if ep > 1:
        grads = _ep_unshard_grads(grads, cfg.n_experts, ep)
    data_axes = tuple(a for a in ("sp", "ep") if a in axes)
    loss = lax.pmean(loss, data_axes)
    if "sp" in axes:
        grads = jax.tree_util.tree_map(
            lambda x: lax.pmean(x, "sp"), grads)
    return loss, grads


l, g = jax.jit(jax.shard_map(
    inner, mesh=mesh, in_specs=(P(), batch_spec), out_specs=(P(), P()),
    check_vma=False))(params, batch)
np.testing.assert_allclose(float(l), float(l_ref), atol=1e-5)
_assert_grad_trees_match(g, g_ref)
print(f"COMPOSITION-{MODE.upper()}-OK")
