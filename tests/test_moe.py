"""Expert-parallel MoE dispatch (horovod_tpu.ops.moe): exactness vs the
dense oracle, capacity-drop semantics, the ep all_to_all exchange under
shard_map, and the flat-in-E compute claim."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd  # noqa: F401 — device count via conftest
from horovod_tpu.ops import moe


def _params(key, E, D, F):
    ks = jax.random.split(key, 4)
    return dict(
        router=jax.random.normal(ks[0], (D, E)) * 0.5,
        w_gate=jax.random.normal(ks[1], (E, D, F)) / np.sqrt(D),
        w_up=jax.random.normal(ks[2], (E, D, F)) / np.sqrt(D),
        w_down=jax.random.normal(ks[3], (E, F, D)) / np.sqrt(F),
    )


def _dense_oracle(x, p):
    """Dense top-1 dispatch (the transformer's _moe_mlp_dense math)."""
    logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                        p["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    top = jnp.argmax(probs, axis=-1)
    gate = jnp.max(probs, axis=-1)
    onehot = jax.nn.one_hot(top, p["router"].shape[1], dtype=x.dtype)
    g = jnp.einsum("td,edf->tef", x, p["w_gate"].astype(x.dtype))
    u = jnp.einsum("td,edf->tef", x, p["w_up"].astype(x.dtype))
    y = jnp.einsum("tef,efd->ted", jax.nn.silu(g) * u,
                   p["w_down"].astype(x.dtype))
    y = jnp.einsum("ted,te->td", y, onehot)
    return y * gate[:, None].astype(x.dtype)


class TestSwitchDispatchLocal:
    def test_exact_vs_dense_oracle_no_drops(self):
        """With capacity_factor >= E no token can be dropped, and the
        sparse dispatch must equal the dense oracle — outputs AND every
        gradient (router included)."""
        E, D, F, T = 4, 16, 32, 24
        p = _params(jax.random.PRNGKey(0), E, D, F)
        x = jax.random.normal(jax.random.PRNGKey(1), (T, D))

        def loss_sparse(p):
            y = moe.switch_moe(x, p["router"], p["w_gate"], p["w_up"],
                               p["w_down"], capacity_factor=float(E))
            return jnp.sum(y ** 2)

        def loss_dense(p):
            return jnp.sum(_dense_oracle(x, p) ** 2)

        l_s, g_s = jax.value_and_grad(loss_sparse)(p)
        l_d, g_d = jax.value_and_grad(loss_dense)(p)
        np.testing.assert_allclose(float(l_s), float(l_d), rtol=1e-5)
        for k in p:
            np.testing.assert_allclose(
                np.asarray(g_s[k]), np.asarray(g_d[k]),
                atol=1e-4, rtol=1e-4, err_msg=k)

    def test_capacity_drops_zero_overflow_tokens(self):
        """Force every token onto expert 0: tokens past the capacity must
        contribute ZERO (residual-only), earlier ones must match the
        oracle."""
        E, D, F, T = 2, 8, 16, 10
        p = _params(jax.random.PRNGKey(0), E, D, F)
        # Router hugely biased to expert 0.
        p["router"] = jnp.zeros((D, E)).at[:, 0].set(100.0)
        x = jnp.ones((T, D)) * 0.1
        cf = 1.0  # cap = ceil(1.0 * 10 / 2) = 5 -> tokens 5..9 dropped
        y = moe.switch_moe(x, p["router"], p["w_gate"], p["w_up"],
                           p["w_down"], capacity_factor=cf)
        oracle = _dense_oracle(x, p)
        np.testing.assert_allclose(np.asarray(y[:5]), np.asarray(oracle[:5]),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(y[5:]), 0.0, atol=1e-7)

    def test_aux_loss_balance(self):
        """Perfectly balanced routing gives aux ~= 1 (its minimum)."""
        E, D, F = 4, 8, 16
        p = _params(jax.random.PRNGKey(0), E, D, F)
        p["router"] = jnp.eye(D, E) * 100.0  # token i%... route by argmax dim
        # Tokens one-hot on dims 0..E-1 in equal numbers -> balanced.
        x = jnp.tile(jnp.eye(E, D), (3, 1)).astype(jnp.float32)
        _, aux = moe.switch_moe(x, p["router"], p["w_gate"], p["w_up"],
                                p["w_down"], capacity_factor=4.0,
                                return_aux=True)
        np.testing.assert_allclose(float(aux), 1.0, atol=0.05)

    def test_sort_dispatch_identical_to_cumsum(self):
        """The sort-based fast dispatch must reproduce the cumsum oracle
        EXACTLY — outputs, every gradient, and the drop pattern (stable
        sort preserves each expert's arrival order) — both dropless and
        under forced overflow."""
        E, D, F, T = 4, 16, 32, 24
        p = _params(jax.random.PRNGKey(0), E, D, F)

        def run(x, cf, dispatch):
            def loss(p):
                y, aux = moe.switch_moe(
                    x, p["router"], p["w_gate"], p["w_up"], p["w_down"],
                    capacity_factor=cf, dispatch=dispatch, return_aux=True)
                return jnp.sum(y ** 2) + 0.1 * aux, y

            (l, y), g = jax.value_and_grad(loss, has_aux=True)(p)
            return l, y, g

        x = jax.random.normal(jax.random.PRNGKey(1), (T, D))
        for cf in (float(E), 1.0, 0.5):  # dropless, tight, overflowing
            l_s, y_s, g_s = run(x, cf, "sort")
            l_c, y_c, g_c = run(x, cf, "cumsum")
            np.testing.assert_array_equal(np.asarray(y_s), np.asarray(y_c))
            np.testing.assert_allclose(float(l_s), float(l_c), rtol=1e-7)
            for k in p:
                np.testing.assert_allclose(
                    np.asarray(g_s[k]), np.asarray(g_c[k]),
                    atol=1e-6, rtol=1e-6, err_msg=f"cf={cf} {k}")

    def test_sort_dispatch_ep2_matches_local(self):
        """Sort dispatch under the ep all_to_all exchange (the buffer
        contract is dispatch-mechanism independent)."""
        E, D, F, T_loc, EP = 4, 16, 32, 12, 2
        p = _params(jax.random.PRNGKey(0), E, D, F)
        x = jax.random.normal(jax.random.PRNGKey(1), (EP, T_loc, D))
        mesh = Mesh(np.array(jax.devices()[:EP]), axis_names=("ep",))

        out = jax.jit(jax.shard_map(
            lambda x, r, wg, wu, wd: moe.switch_moe(
                x[0], r, wg, wu, wd, capacity_factor=1.25, axis_name="ep",
                dispatch="sort")[None],
            mesh=mesh,
            in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep")),
            out_specs=P("ep")))(
            x, p["router"], p["w_gate"], p["w_up"], p["w_down"])
        for s in range(EP):
            ref = moe.switch_moe(x[s], p["router"], p["w_gate"], p["w_up"],
                                 p["w_down"], capacity_factor=1.25,
                                 dispatch="cumsum")
            np.testing.assert_allclose(np.asarray(out[s]), np.asarray(ref),
                                       atol=1e-5, rtol=1e-5)

    def test_bad_dispatch_raises(self):
        p = _params(jax.random.PRNGKey(0), 2, 8, 16)
        with pytest.raises(ValueError, match="dispatch"):
            moe.switch_moe(jnp.zeros((4, 8)), p["router"], p["w_gate"],
                           p["w_up"], p["w_down"], dispatch="bogus")

    def test_flops_flat_in_experts(self):
        """The headline claim, statically: dense dispatch FLOPs grow with
        E; switch dispatch FLOPs stay ~flat (total expert compute is
        cf*T*FFN regardless of E)."""
        D, F, T = 64, 128, 256

        def flops(fn, *args):
            # _cost_dict normalizes the list-wrapped cost_analysis()
            # shape older jax returns — the ONE copy of that rule
            from horovod_tpu.obs.xprof import _cost_dict

            c = jax.jit(fn).lower(*args).compile()
            return _cost_dict(c)["flops"]

        def sparse(E):
            p = _params(jax.random.PRNGKey(0), E, D, F)
            x = jax.random.normal(jax.random.PRNGKey(1), (T, D))
            return flops(
                lambda x: moe.switch_moe(
                    x, p["router"], p["w_gate"], p["w_up"], p["w_down"],
                    capacity_factor=1.25), x)

        def dense(E):
            p = _params(jax.random.PRNGKey(0), E, D, F)
            x = jax.random.normal(jax.random.PRNGKey(1), (T, D))
            return flops(lambda x: _dense_oracle(x, p), x)

        s2, s8 = sparse(2), sparse(8)
        d2, d8 = dense(2), dense(8)
        assert d8 > d2 * 3, (d2, d8)  # dense: ~linear in E
        assert s8 < s2 * 1.5, (s2, s8)  # switch: ~flat in E
        assert s8 < d8 / 2.5, (s8, d8)  # and far below dense at E=8


class TestDroplessMoE:
    def test_matches_dense_oracle_outputs_and_grads(self):
        """Grouped ragged-matmul dispatch is EXACT (nothing dropped): it
        must match the dense every-expert oracle at 1/E of its FLOPs —
        the serving/prefill dispatch."""
        E, D, F, T = 4, 16, 32, 24
        p = _params(jax.random.PRNGKey(0), E, D, F)
        x = jax.random.normal(jax.random.PRNGKey(1), (T, D))

        def loss_dl(p):
            return jnp.sum(moe.dropless_moe(
                x, p["router"], p["w_gate"], p["w_up"], p["w_down"]) ** 2)

        def loss_dense(p):
            return jnp.sum(_dense_oracle(x, p) ** 2)

        l_d, g_d = jax.value_and_grad(loss_dl)(p)
        l_o, g_o = jax.value_and_grad(loss_dense)(p)
        np.testing.assert_allclose(float(l_d), float(l_o), rtol=1e-5)
        for k in p:
            np.testing.assert_allclose(
                np.asarray(g_d[k]), np.asarray(g_o[k]),
                atol=1e-4, rtol=1e-4, err_msg=k)

    def test_skewed_routing_still_exact(self):
        """All tokens on one expert — the case capacity dispatch drops;
        dropless must still equal the oracle."""
        E, D, F, T = 2, 8, 16, 10
        p = _params(jax.random.PRNGKey(0), E, D, F)
        p["router"] = jnp.eye(D, E) * 50.0
        x = jnp.abs(jax.random.normal(jax.random.PRNGKey(1), (T, D)))
        y = moe.dropless_moe(x, p["router"], p["w_gate"], p["w_up"],
                             p["w_down"])
        np.testing.assert_allclose(np.asarray(y), np.asarray(_dense_oracle(x, p)),
                                   atol=1e-5, rtol=1e-5)

    def test_dropless_flops_fraction_of_dense(self):
        """Static cost: dropless FFN FLOPs must be ~1/E of dense's.

        Platform-dependent: the TPU lowering of ragged_dot is truly
        grouped (measured on chip: 2.1 GF vs dense's 17.2 GF at E=8 —
        docs/benchmarks.md), but the CPU lowering masks full matmuls, so
        the assertion only holds off-CPU.  The exactness tests above run
        everywhere."""
        if jax.default_backend() == "cpu":
            pytest.skip("CPU lowers ragged_dot to masked dense matmuls; "
                        "the 1/E cost claim is asserted on TPU")
        E, D, F, T = 8, 64, 128, 256
        p = _params(jax.random.PRNGKey(0), E, D, F)
        x = jax.random.normal(jax.random.PRNGKey(1), (T, D))

        def flops(fn):
            from horovod_tpu.obs.xprof import _cost_dict

            return _cost_dict(jax.jit(fn).lower(x).compile())["flops"]

        fd = flops(lambda x: _dense_oracle(x, p))
        fl = flops(lambda x: moe.dropless_moe(
            x, p["router"], p["w_gate"], p["w_up"], p["w_down"]))
        assert fl < fd / (E / 2), (fl, fd)


class TestSwitchDispatchExpertParallel:
    EP = 2

    def _shard_run(self, x_shards, p, cf, with_grad=False):
        """Run switch_moe under shard_map: experts sharded over ep, each
        device owning its token shard."""
        E = p["router"].shape[1]
        mesh = Mesh(np.array(jax.devices()[:self.EP]), axis_names=("ep",))

        def inner(x, router, wg, wu, wd):
            return moe.switch_moe(x[0], router, wg, wu, wd,
                                  capacity_factor=cf, axis_name="ep")[None]

        fn = jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep")),
            out_specs=P("ep"))
        args = (x_shards, p["router"], p["w_gate"], p["w_up"], p["w_down"])
        if not with_grad:
            return jax.jit(fn)(*args)

        def loss(wg, wu, wd, router):
            y = fn(x_shards, router, wg, wu, wd)
            return jnp.sum(y ** 2)

        return jax.value_and_grad(loss, argnums=(0, 1, 2, 3))(
            p["w_gate"], p["w_up"], p["w_down"], p["router"])

    @pytest.mark.slow
    def test_ep2_matches_local_dispatch(self):
        """ep=2 all_to_all dispatch == per-shard local dispatch (drops
        depend only on the shard-local token order), outputs and grads."""
        E, D, F, T_loc = 4, 16, 32, 12
        p = _params(jax.random.PRNGKey(0), E, D, F)
        x = jax.random.normal(jax.random.PRNGKey(1), (self.EP, T_loc, D))
        cf = 1.25

        out = self._shard_run(x, p, cf)
        for s in range(self.EP):
            ref = moe.switch_moe(x[s], p["router"], p["w_gate"], p["w_up"],
                                 p["w_down"], capacity_factor=cf)
            np.testing.assert_allclose(np.asarray(out[s]), np.asarray(ref),
                                       atol=1e-5, rtol=1e-5)

        l_ep, g_ep = self._shard_run(x, p, cf, with_grad=True)

        def loss_local(wg, wu, wd, router):
            tot = 0.0
            for s in range(self.EP):
                y = moe.switch_moe(x[s], router, wg, wu, wd,
                                   capacity_factor=cf)
                tot = tot + jnp.sum(y ** 2)
            return tot

        l_ref, g_ref = jax.value_and_grad(loss_local, argnums=(0, 1, 2, 3))(
            p["w_gate"], p["w_up"], p["w_down"], p["router"])
        np.testing.assert_allclose(float(l_ep), float(l_ref), rtol=1e-5)
        for a, b, name in zip(g_ep, g_ref, ("w_gate", "w_up", "w_down",
                                            "router")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4, err_msg=name)

    def test_ep_path_emits_all_to_all(self):
        """The exchange must be a true all_to_all in the compiled HLO —
        the ep axis shards compute, not just storage."""
        E, D, F, T_loc = 4, 16, 32, 8
        p = _params(jax.random.PRNGKey(0), E, D, F)
        x = jnp.zeros((self.EP, T_loc, D))
        mesh = Mesh(np.array(jax.devices()[:self.EP]), axis_names=("ep",))

        fn = jax.jit(jax.shard_map(
            lambda x, r, wg, wu, wd: moe.switch_moe(
                x[0], r, wg, wu, wd, capacity_factor=1.25,
                axis_name="ep")[None],
            mesh=mesh,
            in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep")),
            out_specs=P("ep")))
        hlo = fn.lower(x, p["router"], p["w_gate"], p["w_up"],
                       p["w_down"]).compile().as_text()
        assert "all-to-all" in hlo, hlo[:2000]


class TestModelSwitchMoE:
    def _cfg(self, **kw):
        import dataclasses

        from horovod_tpu.models import transformer as T

        base = T.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=16, n_experts=4, dtype=jnp.float32,
            attention_impl="reference")
        return T, dataclasses.replace(base, **kw)

    def test_forward_switch_vs_dense_no_drops(self):
        """Model-level: switch dispatch with dropless capacity equals the
        dense oracle forward."""
        import dataclasses

        T, cfg = self._cfg(capacity_factor=4.0)  # cf = E -> dropless
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        out_s = T.forward(params, tokens, cfg)
        out_d = T.forward(params, tokens,
                          dataclasses.replace(cfg, moe_impl="dense"))
        np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_d),
                                   atol=1e-4, rtol=1e-4)

    def test_forward_with_drops_diverges_from_dense_but_stays_finite(self):
        """When capacity drops DO occur (biased router, tight capacity),
        switch forward legitimately diverges from the dense oracle (the
        dropped tokens' MLP contributions are gone) but must stay finite
        — the documented training-time behavior."""
        import dataclasses

        T, cfg = self._cfg(capacity_factor=0.5)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        # Bias every layer's router hard toward expert 0 -> guaranteed
        # overflow at cf=0.5.
        L, D, E = params["layers"]["router"].shape
        params["layers"]["router"] = (
            jnp.zeros((L, D, E)).at[:, :, 0].set(10.0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        out_s = T.forward(params, tokens, cfg)
        out_d = T.forward(params, tokens,
                          dataclasses.replace(cfg, moe_impl="dense"))
        assert np.isfinite(np.asarray(out_s)).all()
        assert not np.allclose(np.asarray(out_s), np.asarray(out_d),
                               atol=1e-4), "drops must be observable"

    def test_bad_impl_raises(self):
        T, cfg = self._cfg(moe_impl="bogus")
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(ValueError, match="moe_impl"):
            T.forward(params, tokens, cfg)


class TestModelAuxLoss:
    """The Switch balance term wired into the FLAGSHIP training loss
    (cfg.moe_aux_coeff), and the routed-fraction observability that
    proves it keeps the router from collapsing."""

    def _cfg(self, **kw):
        import dataclasses

        from horovod_tpu.models import transformer as T

        base = T.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=16, n_experts=4, dtype=jnp.float32,
            attention_impl="reference")
        return T, dataclasses.replace(base, **kw)

    def test_loss_fn_adds_exactly_coeff_times_aux(self):
        """loss_fn(coeff) == loss_fn(0) + coeff * sum-of-layer-aux — the
        wiring is arithmetic, not approximate."""
        import dataclasses

        T, cfg = self._cfg(moe_aux_coeff=0.0)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        batch = T.synthetic_batch(1, cfg, batch=4)
        base = float(T.loss_fn(params, batch, cfg))
        _, aux = T.forward(params, batch["tokens"], cfg, return_aux=True)
        with_aux = float(T.loss_fn(
            params, batch, dataclasses.replace(cfg, moe_aux_coeff=0.02)))
        np.testing.assert_allclose(
            with_aux, base + 0.02 * float(aux), rtol=1e-6)

    def test_aux_nonzero_for_moe_zero_for_dense(self):
        T, cfg = self._cfg()
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        batch = T.synthetic_batch(1, cfg, batch=2)
        _, aux = T.forward(params, batch["tokens"], cfg, return_aux=True)
        assert float(aux) >= 2.0 - 1e-4  # >= n_layers * 1.0 (min per layer)

        Td, dcfg = self._cfg(n_experts=0)
        dparams = Td.init_params(jax.random.PRNGKey(0), dcfg)
        _, daux = Td.forward(dparams, batch["tokens"], dcfg, return_aux=True)
        assert float(daux) == 0.0

    @pytest.mark.slow
    def test_router_gradient_flows_from_aux(self):
        # Slow (PR 17 budget pass): grad-of-model compile is ~5 s;
        # test_loss_fn_adds_exactly_coeff_times_aux keeps the aux-loss
        # contract tier-1 (the full training loop is already slow).
        """With every token hard-routed to one expert, the plain LM loss
        gives the router no balance pressure; the aux term must produce a
        router gradient pushing load off the overloaded expert."""
        import dataclasses

        T, cfg = self._cfg(moe_aux_coeff=0.01, capacity_factor=1.0)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        L, D, E = params["layers"]["router"].shape
        params["layers"]["router"] = (
            jnp.asarray(params["layers"]["router"]).at[:, :, 0].add(3.0))
        batch = T.synthetic_batch(1, cfg, batch=4)
        g = jax.grad(lambda p: T.loss_fn(p, batch, cfg))(params)
        g0 = np.asarray(g["layers"]["router"])[:, :, 0]
        assert np.abs(g0).max() > 0, "aux must reach the router"

    @pytest.mark.slow
    def test_training_with_aux_keeps_load_uniform(self):
        """Train a small switch model under TIGHT capacity (cf=1.0, where
        every point of imbalance costs dropped tokens): with the aux term
        the routed-fraction histogram stays near uniform; the no-aux
        control drifts measurably less balanced.  (A linear bias-free
        router cannot be force-collapsed deterministically at this scale
        — rmsnorm'd activations kill constant logit offsets — so the
        assertion is the measured uniformity GAP, not a staged
        collapse.)"""
        import dataclasses

        import optax

        T, cfg0 = self._cfg(capacity_factor=1.0)
        rng = np.random.RandomState(0)
        toks = rng.randint(0, 64, size=(8, 16)).astype(np.int32)
        batch = {"tokens": jnp.asarray(toks),
                 "targets": jnp.asarray(np.roll(toks, -1, 1))}

        def train(coeff, steps=200):
            cfg = dataclasses.replace(cfg0, moe_aux_coeff=coeff)
            params = T.init_params(jax.random.PRNGKey(0), cfg)
            opt = optax.adam(1e-2)
            state = opt.init(params)

            @jax.jit
            def step(params, state):
                loss, g = jax.value_and_grad(
                    lambda p: T.loss_fn(p, batch, cfg))(params)
                up, state = opt.update(g, state, params)
                return optax.apply_updates(params, up), state, loss

            for _ in range(steps):
                params, state, loss = step(params, state)
            assert np.isfinite(float(loss))
            return np.asarray(T.expert_load(params, batch["tokens"], cfg))

        load_aux = train(0.02)
        load_ctrl = train(0.0)
        E = cfg0.n_experts
        # Aux run: near-uniform (ideal 1/E = 0.25) — no expert hoards,
        # every expert carries real load in every layer.
        assert load_aux.max() < 0.32, load_aux
        assert load_aux.min() > 0.10, load_aux
        # Control: measurably less balanced than the aux run.
        assert load_ctrl.max() > load_aux.max() + 0.02, (load_ctrl, load_aux)
