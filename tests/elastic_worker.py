"""Elastic-recovery worker: one phase of a crash-and-resume job.

Driven by tests/test_elastic.py (VERDICT r1 #9): phase 1 runs 3 ranks and
ELASTIC_CRASH_RANK dies mid-training after a commit; the launcher's
kill-all tears the job down (reference gloo_run.py:162-259).  Phase 2
relaunches with the 2 survivors, restores from the commit, and resumes
with consistent step counts — the reference's §5.3/5.4 recovery
convention (rank-0 checkpoint + restore-then-broadcast + re-init with
surviving hosts).
"""

import json
import os
import sys

sys.path.insert(0, os.environ["REPO"])

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import elastic  # noqa: E402

CKPT = os.environ["ELASTIC_CKPT"]
RESULTS = os.environ["ELASTIC_RESULTS"]
CRASH_RANK = int(os.environ.get("ELASTIC_CRASH_RANK", "-1"))
CRASH_AT_STEP = int(os.environ.get("ELASTIC_CRASH_AT_STEP", "7"))
COMMIT_AT_STEP = 5
TOTAL_STEPS = 10

hvd.init()
rank = hvd.process_rank()
size = hvd.num_processes()

state = elastic.State(
    params={"w": np.zeros(8, np.float32)},
    step=0,
)
resumed_from = None
if state.restore(CKPT):
    resumed_from = int(state.step)
state.sync()

step = int(state.step)
while step < TOTAL_STEPS:
    grad = np.full(8, float(rank + 1), np.float32)
    reduced = hvd.allreduce(grad, hvd.Average, name=f"elastic.g.{step}")
    state.params["w"] = state.params["w"] - 0.1 * reduced
    step += 1
    state.step = step
    if step == COMMIT_AT_STEP:
        state.commit(CKPT)
        hvd.barrier()  # commit visible before anyone can crash past it
    if rank == CRASH_RANK and step == CRASH_AT_STEP:
        print(f"ELASTIC-WORKER-CRASH rank={rank} step={step}", flush=True)
        os._exit(17)  # simulated host failure: no cleanup, no shutdown

checksum = float(np.sum(state.params["w"]))
with open(os.path.join(RESULTS, f"final.{rank}.json"), "w") as f:
    json.dump({"rank": rank, "size": size, "step": step,
               "resumed_from": resumed_from, "checksum": checksum}, f)
hvd.shutdown()
print(f"ELASTIC-WORKER-OK rank={rank} step={step}", flush=True)
