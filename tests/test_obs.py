"""Unified observability layer (horovod_tpu/obs/): metrics registry +
Prometheus exposition, request tracing, timeline dropped-event
accounting, and the /metrics endpoint.

The registry is the ONE place instruments live (duplicate registration
raises — the CI self-check); the tracer threads a Dapper-style trace
id submit -> prefill -> decode -> retirement and renders request spans,
tick-phase spans, and lifecycle instants through the existing timeline
writer so one Perfetto file carries training and serving on one time
axis.  The perf-marked test bounds the tracing overhead on the decode
hot path (disabled is two pointer checks per tick; enabled <= 5% at
the per-tick p25)."""

import json
import queue
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu import timeline as TL
from horovod_tpu.models import transformer as T
from horovod_tpu.obs import registry as R
from horovod_tpu.obs import tracing as TR
from horovod_tpu.obs import training_step

from conftest import http_post_json as _post  # noqa: E402
from conftest import parse_prometheus_text  # noqa: E402

pytestmark = pytest.mark.serving


def _cfg():
    return T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return T.init_params(jax.random.PRNGKey(0), cfg), cfg


def _engine(model, **kw):
    params, cfg = model
    defaults = dict(n_slots=2, max_len=40, min_prefill_bucket=4,
                    restart_backoff=0.01, restart_backoff_max=0.05)
    defaults.update(kw)
    return serving.InferenceEngine(
        params, cfg, serving.EngineConfig(**defaults))


def _run_until_done(engine, futs, max_ticks=300):
    for _ in range(max_ticks):
        if all(f.done() for f in futs):
            return
        engine.step()
    raise AssertionError("engine did not finish within the tick budget")


@pytest.fixture()
def tracer(tmp_path):
    """A started tracer writing to tmp files, torn down afterwards so
    the module-global never leaks into other tests."""
    path = str(tmp_path / "trace.json")
    t = TR.start(path, jsonl_path=path + ".jsonl")
    yield t, path
    if TR.get() is None and t is not None:
        TR.activate(t)  # stop() needs it active
    TR.stop()


class TestRegistry:
    def test_duplicate_registration_raises(self):
        """CI self-check: a name registers once; a second registration
        — same kind or different — raises typed, it never silently
        shares or shadows."""
        r = R.MetricsRegistry()
        r.counter("x_total")
        with pytest.raises(R.DuplicateMetricError):
            r.counter("x_total")
        with pytest.raises(R.DuplicateMetricError):
            r.gauge("x_total")
        with pytest.raises(R.DuplicateMetricError):
            r.histogram("x_total")
        # exist_ok is the explicit create-or-fetch — and still
        # type-checks
        assert r.counter("x_total", exist_ok=True) is r.get("x_total")
        with pytest.raises(R.DuplicateMetricError):
            r.gauge("x_total", exist_ok=True)
        with pytest.raises(R.DuplicateMetricError):
            r.counter("x_total", labels=("a",), exist_ok=True)

    def test_name_and_label_validation(self):
        r = R.MetricsRegistry()
        with pytest.raises(ValueError):
            r.counter("bad name")
        with pytest.raises(ValueError):
            r.counter("1leading_digit")
        with pytest.raises(ValueError):
            r.counter("ok_total", labels=("bad-label",))

    def test_counter_monotonic(self):
        c = R.Counter()
        c.inc()
        c.inc(5)
        assert c.value == 6
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_labeled_family_children_independent(self):
        r = R.MetricsRegistry()
        fam = r.counter("hits_total", labels=("site",))
        fam.labels(site="a").inc(2)
        fam.labels(site="b").inc()
        assert fam.labels(site="a").value == 2
        assert fam.labels(site="b").value == 1
        with pytest.raises(ValueError):
            fam.labels(wrong="x")
        snap = r.snapshot()
        assert snap["hits_total"] == {'site="a"': 2, 'site="b"': 1}

    def test_prometheus_exposition_parses(self):
        r = R.MetricsRegistry()
        r.counter("req_total", "requests").inc(3)
        r.gauge("depth", "queue depth").set(2.5)
        h = r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 9.0):
            h.observe(v)
        fam = r.counter("by_site_total", "per site", labels=("site",))
        fam.labels(site='we"ird\\').inc()
        text = r.to_prometheus()
        fams = parse_prometheus_text(text)
        assert fams["req_total"]["type"] == "counter"
        assert fams["req_total"]["samples"] == [("req_total", {}, 3.0)]
        assert fams["depth"]["samples"] == [("depth", {}, 2.5)]
        # histogram: cumulative buckets + sum/count validated by the
        # parser; spot-check the numbers
        hs = {(n, l.get("le")): v
              for n, l, v in fams["lat_seconds"]["samples"]}
        assert hs[("lat_seconds_bucket", "0.1")] == 1
        assert hs[("lat_seconds_bucket", "1")] == 2
        assert hs[("lat_seconds_bucket", "+Inf")] == 3
        assert hs[("lat_seconds_count", None)] == 3
        assert abs(hs[("lat_seconds_sum", None)] - 9.55) < 1e-9
        # escaped label values survive the round trip
        (_, labels, v), = fams["by_site_total"]["samples"]
        assert v == 1.0 and "site" in labels

    def test_histogram_api_unchanged(self):
        """The serving suite's Histogram contract (percentiles,
        snapshot dict) is served by the registry implementation."""
        h = serving.Histogram(buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.05, 0.5, 20.0):
            h.observe(v)
        assert h.snapshot()["buckets"] == {"0.1": 2, "1": 1, "10": 0,
                                           "+Inf": 1}
        assert h.percentile(0.5) == 0.1

    def test_serving_metrics_is_registry_view(self):
        """ServingMetrics keeps its attribute + snapshot API but every
        instrument is registered under a serving_* family in a PRIVATE
        registry — two engines never collide."""
        m1, m2 = serving.ServingMetrics(), serving.ServingMetrics()
        m1.admitted.inc(3)
        assert m2.admitted.value == 0
        snap = m1.snapshot()
        assert snap["requests_admitted"] == 3  # /stats keys unchanged
        fams = parse_prometheus_text(m1.registry.to_prometheus())
        assert fams["serving_requests_admitted_total"]["samples"][0][2] == 3
        assert "serving_ttft_seconds" in fams
        assert fams["serving_ttft_seconds"]["type"] == "histogram"

    def test_default_registry_families_seeded_at_init(self, hvd):
        """basics.init() registers the process gauges and the training
        + elastic families, so a /metrics scrape on a cold process
        already exposes them."""
        fams = parse_prometheus_text(R.default_registry().to_prometheus())
        for name in ("horovod_world_size", "horovod_inits_total",
                     "training_step_seconds", "training_steps_total",
                     "elastic_restarts_total", "elastic_commits_total",
                     "timeline_dropped_events_total"):
            assert name in fams, name
        assert fams["horovod_world_size"]["samples"][0][2] == hvd.size()

    def test_training_step_context(self, hvd):
        m = R.training_metrics()
        steps0, count0 = m.steps.value, m.step_time.count
        with training_step():
            time.sleep(0.002)
        assert m.steps.value == steps0 + 1
        assert m.step_time.count == count0 + 1


class TestTimelineDroppedEvents:
    def test_drops_counted_and_flushed_on_close(self, tmp_path):
        """The _emit queue.Full path is no longer silent: drops are
        counted (instance + registry) and the count is flushed as a
        trailing event on close(), so a sparse trace discloses its own
        gaps."""
        reg_counter = R.default_registry().get(
            "timeline_dropped_events_total")
        reg0 = reg_counter.value
        path = str(tmp_path / "tl.json")
        tl = TL.Timeline(path, queue_size=4)
        # Deterministic full-queue: make put_nowait refuse, as it would
        # under a wedged/slow writer, without racing the real thread.
        orig = tl._q.put_nowait
        tl._q.put_nowait = lambda ev: (_ for _ in ()).throw(queue.Full())
        for _ in range(5):
            tl.instant("lost")
        assert tl.dropped_events == 5
        assert reg_counter.value == reg0 + 5
        tl._q.put_nowait = orig
        tl.instant("kept")
        tl.close()
        events = json.load(open(path))
        assert [e["name"] for e in events].count("lost") == 0
        assert any(e["name"] == "kept" for e in events)
        trailing = events[-1]
        assert trailing["name"] == "TIMELINE_DROPPED_EVENTS"
        assert trailing["args"]["dropped_events"] == 5

    def test_no_trailer_without_drops(self, tmp_path):
        path = str(tmp_path / "tl2.json")
        tl = TL.Timeline(path)
        tl.instant("only")
        tl.close()
        events = json.load(open(path))
        assert [e["name"] for e in events] == ["only"]


class TestTracing:
    def test_mint_and_validate(self):
        a, b = TR.mint_trace_id(), TR.mint_trace_id()
        assert a != b and TR.valid_trace_id(a)
        assert TR.valid_trace_id("req-1.retry_2")
        assert not TR.valid_trace_id("")
        assert not TR.valid_trace_id(None)
        assert not TR.valid_trace_id("x" * 65)
        assert not TR.valid_trace_id('bad"quote')
        assert not TR.valid_trace_id("sp ace")

    def test_breakdown_math(self):
        tr = TR.RequestTrace("tid1")
        tr.submitted_at = 100.0
        tr.admitted_at = 100.5
        tr.first_token_at = 101.0
        tr.finished_at = 103.0
        tr.decode_ticks = 7
        tr.tokens = 8
        tr.host_sync_lag = 0.002
        tr.finish = "length"
        b = tr.breakdown()
        assert b == {
            "trace_id": "tid1", "span_id": tr.span_id,
            "queue_wait_s": 0.5, "prefill_s": 0.5,
            "decode_s": 2.0, "decode_ticks": 7, "tokens": 8,
            "host_sync_lag_s": 0.002, "total_s": 3.0, "finish": "length",
        }
        # unfinished / never-admitted requests measure what they can
        tr2 = TR.RequestTrace("tid2")
        tr2.submitted_at = 100.0
        b2 = tr2.breakdown(now=101.0)
        assert b2["queue_wait_s"] == 1.0 and b2["total_s"] == 1.0
        assert b2["prefill_s"] is None and b2["finish"] is None

    def test_engine_trace_propagation_and_spans(self, model, tracer):
        """A traced request: caller-supplied id survives to the future,
        the breakdown is coherent, and the trace file carries the
        request span (with nested phases), tick-phase spans, and an
        xla_compile instant — all through the ONE timeline writer."""
        t, path = tracer
        engine = _engine(model)
        fut = engine.submit([3, 4, 5], max_new_tokens=5,
                            trace_id="golden-req-1")
        _run_until_done(engine, [fut])
        toks = fut.result(timeout=0)
        assert fut.trace_id == "golden-req-1"
        b = fut.breakdown()
        assert b["finish"] == "length" and b["tokens"] == len(toks) == 5
        assert b["queue_wait_s"] >= 0 and b["prefill_s"] >= 0
        assert b["decode_s"] >= 0 and b["decode_ticks"] == 4
        assert b["host_sync_lag_s"] > 0
        assert abs(b["total_s"]
                   - (b["queue_wait_s"] + b["prefill_s"] + b["decode_s"])
                   ) < 1e-3
        TR.stop()
        TR.activate(t)  # fixture stops again; keep its handle valid
        events = json.load(open(path))
        names = [e["name"] for e in events]
        assert "request golden-req-1" in names
        for n in ("queue", "prefill", "decode", "tick_dispatch",
                  "tick_device_wait", "tick_host", "xla_compile"):
            assert n in names, n
        span = next(e for e in events
                    if e["name"] == "request golden-req-1")
        assert span["ph"] == "X"
        assert span["args"]["trace_id"] == "golden-req-1"
        # JSONL structured log carries the same breakdown
        lines = [json.loads(l) for l in
                 open(path + ".jsonl").read().splitlines()]
        rec = next(l for l in lines if l["trace_id"] == "golden-req-1")
        assert rec["event"] == "request" and rec["tokens"] == 5

    def test_minted_id_when_absent(self, model):
        engine = _engine(model)
        fut = engine.submit([1, 2], max_new_tokens=2)
        _run_until_done(engine, [fut])
        assert TR.valid_trace_id(fut.trace_id)

    def test_start_requires_path_or_timeline(self):
        with pytest.raises(ValueError, match="trace path"):
            TR.start()

    def test_double_start_raises(self, tracer):
        with pytest.raises(ValueError, match="already started"):
            TR.start("/tmp/never.json")


class TestServerObservability:
    @pytest.fixture()
    def served(self, model):
        engine = _engine(model)
        with serving.ServingServer(engine, port=0) as srv:
            host, port = srv.address
            yield engine, f"http://{host}:{port}"

    def test_metrics_endpoint_prometheus_golden(self, served, hvd):
        """GOLDEN: /metrics parses as valid Prometheus text exposition
        and covers the serving, training, AND elastic families in one
        scrape."""
        engine, base = served
        code, _ = _post(base + "/generate",
                        {"tokens": [3, 4], "max_new_tokens": 3})
        assert code == 200
        req = urllib.request.Request(base + "/metrics")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
            ctype = r.headers["Content-Type"]
            text = r.read().decode()
        assert ctype.startswith("text/plain")
        assert "version=0.0.4" in ctype
        fams = parse_prometheus_text(text)
        # serving family reflects the request we just made
        assert fams["serving_requests_admitted_total"]["samples"][0][2] >= 1
        assert fams["serving_ttft_seconds"]["type"] == "histogram"
        # training + elastic + process families ride the same scrape
        for name in ("training_step_seconds", "training_steps_total",
                     "elastic_restarts_total", "elastic_rendezvous_total",
                     "horovod_world_size", "xla_compiles_total"):
            assert name in fams, name

    def test_healthz_heartbeat_age_and_restarts(self, served):
        """Liveness probes read heartbeat age + restart count straight
        off /healthz — no /stats parsing."""
        engine, base = served
        code, _ = _post(base + "/generate",
                        {"tokens": [5, 6], "max_new_tokens": 2})
        assert code == 200
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            h = json.loads(r.read())
        assert h["status"] == "healthy"
        assert isinstance(h["heartbeat_age_s"], float)
        assert 0 <= h["heartbeat_age_s"] < 60
        assert h["engine_restarts"] == 0

    def test_trace_header_roundtrip(self, served):
        """X-Trace-Id in -> same id in the response body, response
        header, and per-request breakdown; absent/invalid headers get
        a minted id."""
        engine, base = served
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"tokens": [3, 4, 5],
                             "max_new_tokens": 4}).encode(),
            headers={"Content-Type": "application/json",
                     "X-Trace-Id": "edge-abc.1"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
            hdr = r.headers["X-Trace-Id"]
        assert out["trace_id"] == hdr == "edge-abc.1"
        assert out["breakdown"]["trace_id"] == "edge-abc.1"
        assert out["breakdown"]["finish"] == "length"
        assert out["breakdown"]["tokens"] == 4
        # invalid header -> minted, never echoed
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"tokens": [1], "max_new_tokens": 2}).encode(),
            headers={"Content-Type": "application/json",
                     "X-Trace-Id": "bad header!{}"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert out["trace_id"] != "bad header!{}"
        assert TR.valid_trace_id(out["trace_id"])

    def test_submit_rejection_carries_trace_id(self, served):
        engine, base = served
        code, out = _post(base + "/generate",
                          {"tokens": list(range(60)),
                           "max_new_tokens": 8})
        assert (code, out["type"]) == (413, "too_long")
        assert TR.valid_trace_id(out["trace_id"])


@pytest.mark.perf
class TestTracingOverhead:
    def test_enabled_per_tick_work_bounded(self, tmp_path):
        """PERF GUARD (enabled <=5%): the tracer work one steady-state
        decode tick performs — three buffered tick_phase records plus
        the amortized batch flush through the live writer thread — must
        cost <= 50us per tick at the p25.  A serving-shaped decode tick
        is >= 1ms (the CPU smoke config's is several ms, TPU ticks
        similar), so 50us caps the enabled overhead at the issue's 5%
        budget; in practice this measures ~2-5us.  A deterministic
        micro-bound instead of an engine wall-clock A/B: this sandbox's
        host noise swings per-tick times tens of percent (the same
        reason _ab_decode compares p25s and only the BENCHMARK reports
        the measured ratio — see tracing_overhead_ratio in
        benchmarks/serving.py)."""
        path = str(tmp_path / "perf_trace.json")
        tracer = TR.start(path)
        try:
            n, reps = 400, 30
            samples = []
            for _ in range(reps):
                t0 = time.perf_counter()
                for _ in range(n):
                    # exactly what the engine emits per steady tick
                    tracer.tick_phase("tick_dispatch", 1.0, 1e-4)
                    tracer.tick_phase("tick_device_wait", 1.0, 1e-3)
                    tracer.tick_phase("tick_host", 1.0, 1e-4)
                samples.append((time.perf_counter() - t0) / n)
            per_tick = float(np.percentile(samples, 25))
            assert per_tick <= 50e-6, f"{per_tick * 1e6:.1f}us per tick"
        finally:
            TR.stop()

    def test_enabled_tick_emissions_bounded(self, model, tmp_path):
        """Structural half of the enabled bound: a steady-state decode
        tick makes EXACTLY three tracer calls (the tick phases) — no
        per-token, per-slot, or per-future emission creep on the hot
        path.  Counted with a stub tracer so the assertion is exact."""
        calls = {"tick_phase": 0, "other": 0}

        class StubTracer:
            def tick_phase(self, *a, **k):
                calls["tick_phase"] += 1

            def __getattr__(self, name):
                def record(*a, **k):
                    calls["other"] += 1
                return record

        engine = _engine(model, n_slots=2)
        fut = engine.submit([2, 3, 4], max_new_tokens=36)
        for _ in range(6):  # admission + pipeline fill
            engine.step()
        assert not fut.done()
        prev = TR.activate(StubTracer())
        try:
            n = 10
            for _ in range(n):
                engine.step()
        finally:
            TR.activate(prev)
        assert not fut.done()  # still steady-state
        assert calls["tick_phase"] == 3 * n, calls
        assert calls["other"] == 0, calls
        _run_until_done(engine, [fut])

    def test_disabled_per_tick_work_bounded(self):
        """PERF GUARD (disabled <=2%): with no tracer attached the hot
        path's entire tracing cost is the per-site `tracing.get() is
        None` check (two per tick).  Bound it at 2us per tick — three
        orders of magnitude under 2% of a 1ms tick; in practice
        ~0.1us."""
        assert TR.get() is None
        n, reps = 2000, 30
        samples = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(n):
                if TR.get() is not None:  # the dispatch-site check
                    raise AssertionError
                if TR.get() is not None:  # the retire-site check
                    raise AssertionError
            samples.append((time.perf_counter() - t0) / n)
        per_tick = float(np.percentile(samples, 25))
        assert per_tick <= 2e-6, f"{per_tick * 1e6:.2f}us per tick"

    def test_disabled_tracing_adds_no_host_syncs(self, model):
        """Structural half of the <=2%-disabled bound: with no tracer,
        the steady-state tick performs the same single host sync — the
        hooks never touch the device path."""
        engine = _engine(model, n_slots=2)
        assert TR.get() is None
        fut = engine.submit([2, 3, 4], max_new_tokens=30)
        for _ in range(6):
            engine.step()
        syncs0 = engine.metrics.host_syncs.value
        ticks0 = engine.metrics.decode_ticks.value
        for _ in range(10):
            engine.step()
        assert (engine.metrics.host_syncs.value - syncs0
                <= engine.metrics.decode_ticks.value - ticks0)
        _run_until_done(engine, [fut])
