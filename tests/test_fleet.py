"""Fleet observability (docs/observability.md "Fleet"): cross-rank
metric aggregation, XLA cost/memory introspection + live MFU, straggler
detection, per-rank trace paths, and the multi-rank timeline merge.

The acceptance drill lives in TestFleetEndToEnd: a real multi-process
job (ElasticDriver + tests/fleet_worker.py) publishes snapshots over
the rendezvous KV; the driver's /metrics passes conftest's STRICT
Prometheus parser with rank/host labels, counters summed and
histograms merged, and an artificially slowed rank is flagged within a
few steps — report-only.  Everything else is unit-level: merge
semantics (incl. the typed bucket-mismatch error), percentile edge
semantics, the xprof<->bench MFU equivalence, and the metrics-naming
lint that keeps the docs catalog honest.
"""

import json
import os
import re
import sys
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from horovod_tpu import serving
from horovod_tpu import timeline as TL
from horovod_tpu.models import transformer as T
from horovod_tpu.obs import aggregate as AGG
from horovod_tpu.obs import fleet as FLEET
from horovod_tpu.obs import merge as MERGE
from horovod_tpu.obs import registry as R
from horovod_tpu.obs import tracing as TR
from horovod_tpu.obs import training_step, xprof
from horovod_tpu.runner.discovery import FixedHostDiscovery
from horovod_tpu.runner.elastic_driver import ElasticDriver
from horovod_tpu.runner.hosts import HostSpec

from conftest import parse_prometheus_text  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLEET_WORKER = os.path.join(REPO, "tests", "fleet_worker.py")


def _two_rank_registries():
    regs = {}
    for rank, (c, g, obs) in enumerate(((3, 1.0, (0.05, 0.5)),
                                        (5, 3.0, (2.0,)))):
        r = R.MetricsRegistry()
        r.counter("reqs_total", "requests").inc(c)
        r.gauge("occupancy", "slots").set(g)
        h = r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
        for v in obs:
            h.observe(v)
        fam = r.counter("errs_total", "errors", labels=("kind",))
        fam.labels(kind="oom").inc(rank + 1)
        regs[rank] = r
    return regs


class TestAggregate:
    def test_counters_sum_gauges_roll_up_histograms_merge(self):
        regs = _two_rank_registries()
        agg = AGG.merge_exports(
            {r: reg.export() for r, reg in regs.items()},
            hosts={0: "host-a", 1: "host-b"})
        snap = agg.snapshot()
        assert snap["reqs_total"] == 8
        assert snap["errs_total"] == {'kind="oom"': 3}
        assert snap["occupancy"]["per_rank"] == {"0": 1.0, "1": 3.0}
        assert snap["occupancy"]["min"] == 1.0
        assert snap["occupancy"]["median"] == 2.0
        assert snap["occupancy"]["max"] == 3.0
        # bucket-wise histogram merge is exact: counts/sum/count add
        hs = snap["lat_seconds"]
        assert hs["count"] == 3
        assert hs["buckets"] == {"0.1": 1, "1": 1, "+Inf": 1}
        assert hs["sum"] == pytest.approx(2.55)

    def test_prometheus_rank_host_labels_strict_parse(self):
        regs = _two_rank_registries()
        agg = AGG.merge_exports(
            {r: reg.export() for r, reg in regs.items()},
            hosts={0: "host-a", 1: "host-b"})
        fams = parse_prometheus_text(agg.to_prometheus())
        # counter: ONE fleet-summed sample, no rank label
        (name, labels, v), = fams["reqs_total"]["samples"]
        assert v == 8.0 and "rank" not in labels
        # labeled counter family: summed per label-set
        (_, labels, v), = fams["errs_total"]["samples"]
        assert labels == {"kind": "oom"} and v == 3.0
        # gauge: one series per rank with rank+host labels ...
        series = {(l["rank"], l["host"]): v
                  for _, l, v in fams["occupancy"]["samples"]}
        assert series == {("0", "host-a"): 1.0, ("1", "host-b"): 3.0}
        # ... plus min/median/max roll-up families
        assert fams["occupancy_min"]["samples"][0][2] == 1.0
        assert fams["occupancy_median"]["samples"][0][2] == 2.0
        assert fams["occupancy_max"]["samples"][0][2] == 3.0
        # merged histogram passes the parser's cumulative invariants
        assert fams["lat_seconds"]["type"] == "histogram"

    def test_bucket_mismatch_is_typed_error(self):
        r1, r2 = R.MetricsRegistry(), R.MetricsRegistry()
        r1.histogram("lat_seconds", buckets=(0.1, 1.0)).observe(0.2)
        r2.histogram("lat_seconds", buckets=(0.2, 2.0)).observe(0.2)
        with pytest.raises(AGG.BucketMismatchError):
            AGG.merge_exports({0: r1.export(), 1: r2.export()}) \
               .to_prometheus()

    def test_kind_mismatch_is_typed_error(self):
        r1, r2 = R.MetricsRegistry(), R.MetricsRegistry()
        r1.counter("thing_total").inc()
        r2.gauge("thing_total").set(2)
        with pytest.raises(AGG.MergeConflictError):
            AGG.merge_exports({0: r1.export(), 1: r2.export()})

    def test_export_roundtrips_through_json(self):
        """The wire format the workers publish: json.dumps/loads must
        preserve merge results exactly."""
        regs = _two_rank_registries()
        direct = AGG.merge_exports(
            {r: reg.export() for r, reg in regs.items()}).snapshot()
        wired = AGG.merge_exports(
            {r: json.loads(json.dumps(reg.export()))
             for r, reg in regs.items()}).snapshot()
        assert direct == wired


class TestPercentileEdgeSemantics:
    """Histogram.percentile reports bucket UPPER EDGES, and the +Inf
    overflow reports the largest finite edge — fleet-merged p99s are
    bucket estimates, not exact quantiles (docs/observability.md)."""

    def test_values_land_on_upper_edges(self):
        h = R.Histogram(buckets=(1.0, 2.0, 4.0))
        h.observe(1.5)
        assert h.percentile(0.5) == 2.0  # 1.5 reported as its edge
        h2 = R.Histogram(buckets=(1.0, 2.0, 4.0))
        h2.observe(2.0)  # exactly ON an edge belongs to that bucket
        assert h2.percentile(1.0) == 2.0

    def test_inf_bucket_reports_largest_finite_edge(self):
        h = R.Histogram(buckets=(1.0, 2.0, 4.0))
        h.observe(100.0)
        # "at least 4", not "exactly 4": the overflow bucket cannot
        # know how far past the top edge the tail went
        assert h.percentile(0.99) == 4.0

    def test_empty_and_q0(self):
        h = R.Histogram(buckets=(1.0, 2.0))
        assert h.percentile(0.5) is None
        h.observe(1.5)
        # smallest configured edge — a floor, not a minimum
        assert h.percentile(0.0) == 1.0

    def test_merged_histogram_same_semantics(self):
        h1 = R.Histogram(buckets=(1.0, 2.0, 4.0))
        h2 = R.Histogram(buckets=(1.0, 2.0, 4.0))
        for _ in range(99):
            h1.observe(0.5)
        h2.observe(50.0)  # the fleet's one outlier, in +Inf
        m = AGG.merged_histogram([h1.state(), h2.state()])
        assert m.count == 100
        assert m.percentile(0.5) == 1.0
        assert m.percentile(0.995) == 4.0  # largest finite edge


class TestXprof:
    @pytest.fixture(scope="class")
    def compiled(self):
        f = jax.jit(lambda x: jnp.tanh(x @ x).sum())
        return f.lower(jnp.ones((64, 64), jnp.float32)).compile()

    def test_introspect_matches_hand_rolled_cost_analysis(self, compiled):
        """The MFU-epsilon guard: introspect's FLOPs are EXACTLY what
        bench.py's hand-rolled ca.get('flops') read, so switching
        bench.py to xprof cannot move its reported MFU."""
        report = xprof.introspect(compiled, fn="fleet_test_fn",
                                  register=False)
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        assert report.flops == float(ca["flops"])
        # and the MFU formula is bench.py's: flops / seconds / peak
        assert xprof.mfu(report.flops, 0.01, peak=1e12) == \
            pytest.approx(report.flops / 0.01 / 1e12)
        assert xprof.mfu(report.flops, 0.01, peak=None) is None  # CPU
        assert xprof.mfu(None, 0.01, peak=1e12) is None

    def test_introspect_registers_gauges(self, compiled):
        r = R.MetricsRegistry()
        report = xprof.introspect(compiled, fn="gauged", registry=r)
        fam = r.get("xla_flops")
        assert fam.labels(fn="gauged").value == report.flops
        if report.peak_hbm_bytes is not None:
            assert r.get("xla_hbm_peak_bytes").labels(
                fn="gauged").value == report.peak_hbm_bytes

    def test_peak_hbm_positive_when_available(self, compiled):
        report = xprof.introspect(compiled, fn="hbm", register=False)
        if report.peak_hbm_bytes is not None:  # backend-dependent
            assert report.peak_hbm_bytes > 0

    def test_live_training_mfu_gauge(self, hvd):
        """obs.training_step() sets training_mfu from the armed cost:
        within epsilon of the bench-style flops/dt/peak computation."""
        xprof.set_training_cost(5e9, peak=1e12)
        try:
            t0 = time.monotonic()
            with training_step():
                time.sleep(0.02)
            dt = time.monotonic() - t0
            gauge = R.training_metrics().mfu.value
            assert gauge == pytest.approx(5e9 / dt / 1e12, rel=0.5)
            assert R.training_metrics().last_step.value >= 0.02
        finally:
            xprof.set_training_cost(None)
        # disarmed: the gauge stops updating but training_step still works
        with training_step():
            pass

    def test_transformer_flops_per_token(self):
        cfg = T.TransformerConfig(
            vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
            max_seq=16, dtype=jnp.float32)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        fpt = xprof.transformer_flops_per_token(params)
        import numpy as np

        n_params = sum(int(np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(params))
        embed = int(np.prod(params["embed"].shape))
        assert fpt == 2.0 * (n_params - embed) > 0


class TestServingAchievedFlops:
    def test_stats_reports_achieved_flops(self):
        cfg = T.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=48, dtype=jnp.float32, attention_impl="reference",
            n_kv_heads=2)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        fpt = 1e6
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(
                n_slots=2, max_len=40, min_prefill_bucket=4,
                model_flops_per_token=fpt))
        s0 = engine.stats()  # first sample: no window yet
        assert s0["model_flops_per_token"] == fpt
        t0 = time.monotonic()
        fut = engine.submit([3, 4, 5], max_new_tokens=8)
        for _ in range(100):
            if fut.done():
                break
            engine.step()
        toks = fut.result(timeout=0)
        s1 = engine.stats()
        dt = time.monotonic() - t0
        assert s1["achieved_flops_per_sec"] == pytest.approx(
            len(toks) * fpt / dt, rel=0.5)
        # the gauges ride the engine's Prometheus registry too
        fams = parse_prometheus_text(engine.metrics.registry.to_prometheus())
        assert fams["serving_model_flops_per_token"]["samples"][0][2] == fpt
        assert fams["serving_achieved_flops_per_sec"]["samples"][0][2] > 0

    def test_unconfigured_stays_null(self):
        m = serving.ServingMetrics()
        assert m.snapshot()["model_flops_per_token"] is None
        assert m.snapshot()["achieved_flops_per_sec"] is None

    def test_http_metrics_scrape_refreshes_gauge(self):
        """A Prometheus scraper that only ever hits GET /metrics (the
        documented endpoint) must see a live achieved-FLOP/s value —
        the windowed gauge refreshes per scrape, not only on /stats."""
        cfg = T.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=48, dtype=jnp.float32, attention_impl="reference",
            n_kv_heads=2)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(
                n_slots=2, max_len=40, min_prefill_bucket=4,
                model_flops_per_token=1e6))
        with serving.ServingServer(engine, port=0) as srv:
            base = "http://%s:%d" % srv.address
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10):
                pass  # opens the rate window
            req = urllib.request.Request(
                base + "/generate",
                data=json.dumps({"tokens": [3, 4],
                                 "max_new_tokens": 6}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                assert r.status == 200
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as r:
                fams = parse_prometheus_text(r.read().decode())
        assert fams["serving_achieved_flops_per_sec"][
            "samples"][0][2] > 0

    def _tiny_engine(self):
        cfg = T.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=48, dtype=jnp.float32, attention_impl="reference",
            n_kv_heads=2)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        return serving.InferenceEngine(
            params, cfg, serving.EngineConfig(
                n_slots=2, max_len=40, min_prefill_bucket=4,
                model_flops_per_token=1e6))

    def test_metrics_swap_resets_rate_window(self):
        """benchmarks/serving.py swaps in a fresh ServingMetrics after
        warmup; the rate window must restart with the new counter or
        the next sample computes (0 - old_tokens)/dt < 0."""
        engine = self._tiny_engine()
        engine.metrics.tokens_generated.inc(50_000)
        engine.stats()  # window base: (t0, 50000) from the OLD metrics
        engine.metrics = serving.ServingMetrics()
        time.sleep(0.01)
        s = engine.stats()  # counter restarted at 0
        achieved = s["achieved_flops_per_sec"]
        assert achieved is None or achieved >= 0

    def test_concurrent_stats_scrapes(self):
        """stats() is served from ThreadingHTTPServer handler threads;
        concurrent scrapes must not corrupt the rate window (the
        unlocked prune could empty the list -> IndexError)."""
        import threading as _threading
        engine = self._tiny_engine()
        errs = []

        def scrape():
            try:
                for _ in range(200):
                    engine.stats()
            except Exception as e:  # pragma: no cover - failure path
                errs.append(e)

        threads = [_threading.Thread(target=scrape) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errs == []


class TestStragglerDetection:
    def _beat(self, m, rank, step, step_s):
        m.heartbeat(rank, f"host-{rank}",
                    {"t": 0.0, "steps": step, "step_s": step_s})

    def test_sustained_straggler_flagged_within_patience(self):
        m = FLEET.FleetMonitor(straggler_threshold=2.0,
                               straggler_patience=3)
        m.begin_epoch(0)
        for step in range(1, 6):
            for rank, s in ((0, 0.1), (1, 0.1), (2, 0.5)):
                self._beat(m, rank, step, s)
            if step < 3:
                assert m.stragglers() == []  # patience not yet met
        assert m.stragglers() == ["2"]
        assert m.skew == pytest.approx(5.0)
        # ONE episode = ONE count, however long it persists
        assert m.registry.get("elastic_straggler_total").labels(
            rank="2").value == 1

    def test_recovery_clears_flag_new_episode_counts_again(self):
        m = FLEET.FleetMonitor(straggler_threshold=2.0,
                               straggler_patience=2)
        m.begin_epoch(0)
        step = 0
        for _ in range(3):
            step += 1
            for rank, s in ((0, 0.1), (1, 0.1), (2, 0.9)):
                self._beat(m, rank, step, s)
        assert m.stragglers() == ["2"]
        step += 1
        for rank in (0, 1, 2):
            self._beat(m, rank, step, 0.1)  # rank 2 recovered
        assert m.stragglers() == []
        for _ in range(2):
            step += 1
            for rank, s in ((0, 0.1), (1, 0.1), (2, 0.9)):
                self._beat(m, rank, step, s)
        assert m.registry.get("elastic_straggler_total").labels(
            rank="2").value == 2

    def test_two_rank_fleet_can_flag(self):
        """The suspect is compared against the median of the OTHER
        ranks: with self included, slowest/median is bounded below 2x
        on a 2-rank fleet and a 10x straggler could never be
        flagged."""
        m = FLEET.FleetMonitor(straggler_threshold=2.0,
                               straggler_patience=2)
        m.begin_epoch(0)
        for step in range(1, 4):
            for rank, s in ((0, 0.05), (1, 0.5)):
                self._beat(m, rank, step, s)
        assert m.stragglers() == ["1"]

    def test_no_strike_without_fresh_step(self):
        """Driver polls faster than steps complete: re-observing the
        same heartbeat step count must not advance the strike count."""
        m = FLEET.FleetMonitor(straggler_threshold=2.0,
                               straggler_patience=2)
        m.begin_epoch(0)
        for _ in range(10):  # same steps value, many polls
            for rank, s in ((0, 0.1), (1, 0.1), (2, 0.9)):
                self._beat(m, rank, 1, s)
        assert m.stragglers() == []  # only ONE fresh step observed

    def test_epoch_turnover_resets_ranks_keeps_counters(self):
        m = FLEET.FleetMonitor(straggler_threshold=2.0,
                               straggler_patience=1)
        m.begin_epoch(0)
        for rank, s in ((0, 0.1), (2, 0.1), (1, 0.9)):
            self._beat(m, rank, 1, s)
        assert m.stragglers() == ["1"]
        m.begin_epoch(1)
        assert m.stragglers() == []
        assert m.registry.get("elastic_straggler_total").labels(
            rank="1").value == 1  # job-lifetime fact survives

    def test_parse_heartbeat_legacy_and_structured(self):
        assert FLEET.parse_heartbeat(b"1723456.789") == {"t": 1723456.789}
        assert FLEET.parse_heartbeat(
            b'{"t": 1.0, "steps": 4, "step_s": 0.25}') == {
                "t": 1.0, "steps": 4, "step_s": 0.25}
        assert FLEET.parse_heartbeat(b"not json") == {}

    def test_fleet_json_view(self):
        m = FLEET.FleetMonitor(straggler_patience=1)
        m.begin_epoch(3)
        r = R.MetricsRegistry()
        r.counter("work_total").inc(7)
        m.snapshot(0, "host-a", r.export())
        m.heartbeat(0, "host-a", {"t": 0.0, "steps": 1, "step_s": 0.1})
        fl = m.fleet_json()
        assert fl["epoch"] == 3
        assert fl["ranks"]["0"]["host"] == "host-a"
        assert fl["ranks"]["0"]["has_metrics"] is True
        assert fl["ranks"]["0"]["step_seconds"] == 0.1
        assert fl["metrics"]["work_total"] == 7
        assert fl["stragglers"] == []


class TestTimelineMergeTool:
    def _write_trace(self, path, pid, names, truncated=False):
        evs = [{"name": n, "ph": "i", "s": "p", "ts": 100.0 + i,
                "pid": pid, "tid": 0, "args": {}}
               for i, n in enumerate(names)]
        text = "[\n" + ",\n".join(json.dumps(e) for e in evs)
        if not truncated:
            text += "\n]\n"
        with open(path, "w") as f:
            f.write(text)

    def test_merge_remaps_pids_and_labels_ranks(self, tmp_path):
        a = str(tmp_path / "trace.rank0.json")
        b = str(tmp_path / "trace.rank1.json")
        self._write_trace(a, pid=4242, names=["step_a1", "step_a2"])
        # rank 1 killed mid-run: truncated file must still merge
        self._write_trace(b, pid=4242, names=["step_b1"], truncated=True)
        out = str(tmp_path / "merged.json")
        assert MERGE.main([out, a, b]) == 0
        events = json.load(open(out))
        by_name = {e["name"]: e for e in events if e["ph"] == "i"}
        # the same original pid lands on DISTINCT per-rank tracks
        assert by_name["step_a1"]["pid"] != by_name["step_b1"]["pid"]
        assert by_name["step_a1"]["pid"] == by_name["step_a2"]["pid"]
        # process_name metadata labels each track by rank
        meta = {e["pid"]: e["args"]["name"] for e in events
                if e.get("ph") == "M" and e["name"] == "process_name"}
        assert meta[by_name["step_a1"]["pid"]] == "rank 0"
        assert meta[by_name["step_b1"]["pid"]] == "rank 1"
        # timestamps untouched (shared monotonic clock)
        assert by_name["step_a1"]["ts"] == 100.0

    def test_merge_real_timeline_files(self, tmp_path):
        """End-to-end over the REAL writer: two Timeline instances (as
        two ranks would produce with %r paths) merge into one Perfetto
        file with one distinct pid track per rank."""
        paths = []
        for rank in (0, 1):
            p = str(tmp_path / f"tl.rank{rank}.json")
            tl = TL.Timeline(p)
            tl.instant(f"from_rank_{rank}")
            tl.close()
            paths.append(p)
        out = str(tmp_path / "merged.json")
        assert MERGE.main([out] + paths) == 0
        events = json.load(open(out))
        pids = {e["pid"] for e in events
                if e["name"].startswith("from_rank_")}
        assert len(pids) == 2

    def test_same_file_different_spellings_merged_once(self, tmp_path,
                                                       monkeypatch):
        """Input dedup is on the resolved path, not the raw argv
        string — a glob plus an explicit spelling of the same file
        must not yield two identical rank tracks."""
        monkeypatch.chdir(tmp_path)
        self._write_trace(str(tmp_path / "t0.json"), pid=7,
                          names=["only_once"])
        out = str(tmp_path / "merged.json")
        assert MERGE.main([out, "t0.json", "./t0.json",
                           str(tmp_path / "t0.json")]) == 0
        events = json.load(open(out))
        assert len([e for e in events if e["name"] == "only_once"]) == 1

    def test_empty_and_garbage_inputs_skipped(self, tmp_path, capsys):
        """A rank SIGKILLed before its first flush (0-byte file) or a
        mid-write garbage file must not cost the healthy ranks their
        merged view."""
        good = str(tmp_path / "tl.rank0.json")
        self._write_trace(good, pid=1, names=["kept"])
        empty = str(tmp_path / "tl.rank1.json")
        open(empty, "w").close()
        garbage = str(tmp_path / "tl.rank2.json")
        with open(garbage, "w") as f:
            f.write("[{{{{ not json")
        out = str(tmp_path / "merged.json")
        assert MERGE.main([out, good, empty, garbage]) == 0
        events = json.load(open(out))
        assert [e["name"] for e in events if e["ph"] == "i"] == ["kept"]
        assert "skipped" in capsys.readouterr().err

    def test_missing_input_skipped(self, tmp_path, capsys):
        """A deleted dead-rank file or an unmatched glob (kept as a
        literal path) must be skipped like garbage, not abort the
        merge of the healthy ranks."""
        good = str(tmp_path / "tl.rank0.json")
        self._write_trace(good, pid=1, names=["kept"])
        gone = str(tmp_path / "tl.rank1.json")  # never written
        unmatched = str(tmp_path / "other" / "tl.*.json")
        out = str(tmp_path / "merged.json")
        assert MERGE.main([out, good, gone, unmatched]) == 0
        events = json.load(open(out))
        assert [e["name"] for e in events if e["ph"] == "i"] == ["kept"]
        assert capsys.readouterr().err.count(": skipped (") == 2

    def test_all_inputs_unreadable_fails_without_output(self, tmp_path,
                                                        capsys):
        """Zero readable events -> non-zero exit and NO empty merged
        file masquerading as a successful merge."""
        out = str(tmp_path / "merged.json")
        assert MERGE.main([out, str(tmp_path / "nope.json")]) == 1
        assert not os.path.exists(out)
        assert "no readable trace events" in capsys.readouterr().err

    def test_wildcard_bind_reports_reachable_address(self, monkeypatch):
        """A 0.0.0.0 bind is reported as a connectable host — the
        documented way to learn the port with --metrics-port 0."""
        monkeypatch.setenv("HOROVOD_HOSTNAME", "scrape-me.example")
        srv = FLEET.FleetServer(FLEET.FleetMonitor(), host="0.0.0.0",
                                port=0).start()
        try:
            host, port = srv.address
            assert host == "scrape-me.example"
            assert port > 0
        finally:
            srv.stop()

    def test_mid_object_truncation_repaired(self, tmp_path):
        """Buffered IO means a SIGKILL cuts the file at an arbitrary
        byte — the partial trailing event is dropped, complete ones
        survive."""
        p = str(tmp_path / "cut.rank0.json")
        self._write_trace(p, pid=1, names=["kept1", "kept2", "lost"])
        text = open(p).read()
        cut = text.rindex('"lost"') + 3  # mid-string, mid-object
        with open(p, "w") as f:
            f.write(text[:cut])
        events = MERGE.load_trace(p)
        assert [e["name"] for e in events] == ["kept1", "kept2"]

    def test_percent_r_filenames_label_correctly(self):
        """The %r path style (tl.0.json ... tl.11.json) has no 'rank'
        in the name: the trailing number is the rank, NOT the
        lexicographic glob position (which would call tl.10.json
        'rank 2')."""
        assert MERGE._label_for("/x/tl.10.json", 2) == "rank 10"
        assert MERGE._label_for("/x/tl.2.json", 4) == "rank 2"
        assert MERGE._label_for("/x/trace.rank7.json", 0) == "rank 7"
        assert MERGE._label_for("/x/nonumber.json", 3) == "rank 3"

    def test_align_start_rezeroes(self, tmp_path):
        a = str(tmp_path / "r0.json")
        self._write_trace(a, pid=1, names=["x"])
        out = str(tmp_path / "m.json")
        assert MERGE.main([out, a, "--align-start"]) == 0
        events = json.load(open(out))
        ev = next(e for e in events if e["name"] == "x")
        assert ev["ts"] == 0.0


class TestRankPathSubstitution:
    def test_expand_rank_path(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_RANK", "7")
        assert TL.expand_rank_path("/tmp/t.%r.json") == "/tmp/t.7.json"
        assert TL.expand_rank_path("/tmp/plain.json") == "/tmp/plain.json"
        assert TL.expand_rank_path("/tmp/t.%r.json", rank=3) == \
            "/tmp/t.3.json"
        monkeypatch.delenv("HOROVOD_RANK")
        # falls back to the initialized context / 0
        assert TL.expand_rank_path("t.%r.json").endswith(".json")

    def test_timeline_writes_per_rank_file(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_RANK", "2")
        tl = TL.Timeline(str(tmp_path / "tl.%r.json"))
        tl.instant("hi")
        tl.close()
        assert (tmp_path / "tl.2.json").exists()
        assert not (tmp_path / "tl.%r.json").exists()

    def test_tracer_jsonl_per_rank(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_RANK", "5")
        assert TR.get() is None
        t = TR.start(str(tmp_path / "tr.%r.json"),
                     jsonl_path=str(tmp_path / "tr.%r.jsonl"))
        try:
            t.log_event({"event": "x"})
        finally:
            TR.stop()
        assert (tmp_path / "tr.5.json").exists()
        assert (tmp_path / "tr.5.jsonl").exists()


class TestMetricsNamingLint:
    """CI self-check (the metrics catalog stays honest): every family
    registered in any known registry matches the Prometheus naming
    convention and is documented in docs/observability.md."""

    NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

    def test_every_family_named_and_documented(self, hvd, tmp_path):
        # force the lazily-registered introspection gauges into being
        c = jax.jit(lambda x: x * 2).lower(jnp.ones((8,))).compile()
        xprof.introspect(c, fn="lint")
        R.default_registry().gauge(
            "xla_hbm_peak_bytes", "", labels=("fn",), exist_ok=True)
        # ... and the distributed-tracing trace_* families (registered
        # lazily by the first SpanRecorder this process opens)
        from horovod_tpu.obs import tracing as TR

        TR.SpanRecorder(str(tmp_path / "lint.spans.jsonl"),
                        proc="lint").close()
        registries = {
            "default": R.default_registry(),
            "serving": serving.ServingMetrics().registry,
            "fleet": FLEET.FleetMonitor().registry,
            "router": serving.router.RouterMetrics().registry,
        }
        docs = open(os.path.join(REPO, "docs", "observability.md")).read()
        problems = []
        for scope, reg in registries.items():
            for name in reg.names():
                if not self.NAME_RE.match(name):
                    problems.append(
                        f"{scope}:{name} violates ^[a-z][a-z0-9_]*$")
                if name not in docs:
                    problems.append(
                        f"{scope}:{name} missing from "
                        f"docs/observability.md catalog")
        assert not problems, "\n".join(problems)


class TestDriverFleetResilience:
    def test_metrics_port_conflict_does_not_fail_training(self):
        """Observability failing must not fail training: a taken
        metrics port logs a warning and the job runs on without the
        scrape endpoint (and the rendezvous server is still torn
        down cleanly)."""
        import socket

        blocker = socket.socket()
        blocker.bind(("0.0.0.0", 0))
        blocker.listen(1)
        port = blocker.getsockname()[1]
        try:
            d = ElasticDriver(
                ["x"], FixedHostDiscovery([HostSpec("localhost-a", 1)]),
                min_np=1, metrics_port=port,
                _executor=lambda cmd, env=None, **kw: 0,
                _sleep=lambda s: None)
            assert d.run() == 0
            assert d.fleet_address is None
        finally:
            blocker.close()


class TestFleetEndToEnd:
    """The acceptance drill: 3 real worker processes publish snapshots
    + step durations over the rendezvous KV; the driver serves ONE
    aggregated rank/host-labeled Prometheus scrape (strict-parser
    clean) and flags the artificially slowed rank — report-only, the
    job still succeeds."""

    @pytest.mark.slow
    def test_fleet_scrape_and_straggler_flagging(self, tmp_path):
        env = {
            "PATH": os.environ.get("PATH", ""),
            "REPO": REPO,
            "FLEET_STEP_S": "0.05",
            "FLEET_SLOW_RANK": "1",
            "FLEET_SLOW_FACTOR": "6.0",
            "FLEET_RUN_S": "8.0",
        }
        d = ElasticDriver(
            [sys.executable, FLEET_WORKER],
            FixedHostDiscovery([HostSpec("localhost-a", 1),
                                HostSpec("localhost-b", 1),
                                HostSpec("localhost-c", 1)]),
            min_np=3, env=env,
            heartbeat_interval=0.25,
            metrics_port=0,
            straggler_threshold=2.0, straggler_patience=2,
            output_filename=str(tmp_path / "out"))
        result = {}
        t = threading.Thread(target=lambda: result.update(rc=d.run()),
                             daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 60
            while d.fleet_address is None:
                assert time.monotonic() < deadline, "fleet server not up"
                time.sleep(0.05)
            base = "http://%s:%d" % d.fleet_address

            def _get(path):
                with urllib.request.urlopen(base + path, timeout=10) as r:
                    return r.read().decode()

            # Poll until all 3 ranks report metrics AND the slow rank
            # is flagged (workers pay a few seconds of import first).
            fl = None
            while time.monotonic() < deadline:
                fl = json.loads(_get("/fleet"))
                ready = (len(fl["ranks"]) == 3
                         and all(st["has_metrics"]
                                 for st in fl["ranks"].values())
                         and fl["stragglers"])
                if ready:
                    break
                time.sleep(0.25)
            assert fl is not None and len(fl["ranks"]) == 3, fl
            assert fl["stragglers"] == ["1"], fl
            assert fl["ranks"]["1"]["straggler"] is True
            assert fl["ranks"]["1"]["host"] == "localhost-b"
            assert fl["step_time_skew"] > 2.0

            # The fleet scrape: strict-parser clean, rank/host labeled.
            fams = parse_prometheus_text(_get("/metrics"))
            # histograms merged bucket-wise across ranks
            assert fams["training_step_seconds"]["type"] == "histogram"
            count = next(v for n, l, v
                         in fams["training_step_seconds"]["samples"]
                         if n == "training_step_seconds_count")
            assert count > 0
            # counters summed (worker increments by 2 per step)
            (_, labels, items), = \
                fams["fleet_test_items_total"]["samples"]
            assert "rank" not in labels and items > 0 and items % 2 == 0
            # gauges per-rank with rank+host labels + roll-ups
            series = {l["rank"]: (l["host"], v) for _, l, v
                      in fams["training_last_step_seconds"]["samples"]}
            assert set(series) == {"0", "1", "2"}
            assert series["2"][0] == "localhost-c"
            assert "training_last_step_seconds_median" in fams
            # the straggler counter + skew gauge ride the same scrape
            assert any(l.get("rank") == "1" and v >= 1 for _, l, v
                       in fams["elastic_straggler_total"]["samples"])
            assert fams["elastic_step_time_skew"]["samples"][0][2] > 2.0
            assert fams["fleet_ranks_reporting"]["samples"][0][2] == 3
        finally:
            t.join(timeout=60)
        assert not t.is_alive(), "driver did not finish"
        # report-only: the slowed rank was flagged, NOT evicted
        assert result.get("rc") == 0
        assert d.blacklist.hosts() == []
        assert d.epoch_sizes == [3]
