"""Callback, schedule, timeline, and autotune tests (reference:
test_keras.py callbacks, test_timeline.py, autotune coverage via
parameter_manager)."""

import json
import os

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import autotune, callbacks, timeline


@pytest.fixture(autouse=True)
def _restore_hierarchical_env():
    """The autotuner's _apply writes the HOROVOD_HIERARCHICAL_* env flags
    while exploring categorical settings; leaking them would flip later
    test files (make_train_step picks the hierarchical mesh and changes
    collective semantics)."""
    keys = ("HOROVOD_HIERARCHICAL_ALLREDUCE", "HOROVOD_HIERARCHICAL_ALLGATHER")
    saved = {k: os.environ.get(k) for k in keys}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


class _Model:
    params = {"w": np.ones(2, np.float32)}
    opt_state = None
    lr = 0.0


class TestCallbacks:
    def test_metric_average_single_process(self):
        cb = callbacks.MetricAverageCallback()
        logs = {"loss": 2.0, "acc": 0.5, "name": "x"}
        cb.on_epoch_end(0, logs)
        assert logs["loss"] == pytest.approx(2.0)
        assert logs["acc"] == pytest.approx(0.5)
        assert logs["name"] == "x"

    def test_broadcast_global_variables(self):
        cb = callbacks.BroadcastGlobalVariablesCallback(0)
        m = _Model()
        cb.set_model(m)
        cb.on_train_begin()
        np.testing.assert_allclose(np.asarray(m.params["w"]), [1, 1])

    def test_lr_schedule_staircase(self):
        cb = callbacks.LearningRateScheduleCallback(
            multiplier=lambda e: 0.1**e, initial_lr=1.0
        )
        m = _Model()
        cb.set_model(m)
        cb.on_epoch_begin(0)
        assert m.lr == pytest.approx(1.0)
        cb.on_epoch_begin(2)
        assert m.lr == pytest.approx(0.01)

    def test_lr_schedule_range(self):
        cb = callbacks.LearningRateScheduleCallback(
            multiplier=0.5, start_epoch=2, end_epoch=4, initial_lr=1.0
        )
        m = _Model()
        m.lr = -1.0
        cb.set_model(m)
        cb.on_epoch_begin(0)
        assert m.lr == -1.0  # outside range: untouched
        cb.on_epoch_begin(3)
        assert m.lr == pytest.approx(0.5)

    def test_warmup_progression(self):
        spe = 10
        cb = callbacks.LearningRateWarmupCallback(
            warmup_epochs=2, steps_per_epoch=spe, initial_lr=1.0
        )
        m = _Model()
        cb.set_model(m)
        cb.on_epoch_begin(0)
        cb.on_batch_begin(0)
        first = m.lr
        cb.current_epoch = 1
        cb.on_batch_begin(9)
        last = m.lr
        assert first == pytest.approx(1.0 / hvd.size())
        assert last > first
        assert last <= 1.0 + 1e-6

    def test_warmup_requires_steps_per_epoch(self):
        cb = callbacks.LearningRateWarmupCallback(warmup_epochs=1, initial_lr=1.0)
        cb.set_model(_Model())
        cb.on_epoch_begin(0)
        with pytest.raises(ValueError, match="steps_per_epoch"):
            cb.on_batch_begin(0)


class TestSchedules:
    def test_warmup_schedule(self):
        sched = callbacks.warmup_schedule(0.1, warmup_steps=10, size=8)
        assert float(sched(0)) == pytest.approx(0.1)
        assert float(sched(10)) == pytest.approx(0.8)
        assert float(sched(100)) == pytest.approx(0.8)

    def test_multiplier_schedule(self):
        sched = callbacks.multiplier_schedule(1.0, [(10, 0.1), (20, 0.01)])
        assert float(sched(0)) == pytest.approx(1.0)
        assert float(sched(15)) == pytest.approx(0.1)
        assert float(sched(25)) == pytest.approx(0.01)


class TestTimeline:
    def test_events_written(self, tmp_path):
        path = str(tmp_path / "tl.json")
        tl = timeline.Timeline(path)
        with tl.activity("ALLREDUCE", "collective"):
            pass
        tl.instant("NEGOTIATE_ALLREDUCE")
        tl.mark_cycle()
        tl.close()
        with open(path) as f:
            events = json.load(f)
        names = [e["name"] for e in events]
        assert "ALLREDUCE" in names
        assert "NEGOTIATE_ALLREDUCE" in names
        assert "CYCLE" in names
        phases = {e["ph"] for e in events}
        assert {"B", "E", "i"} <= phases

    def test_start_stop_api(self, tmp_path):
        path = str(tmp_path / "tl2.json")
        tl = timeline.start_timeline(path)
        assert timeline.get() is tl
        with pytest.raises(ValueError):
            timeline.start_timeline(path)
        timeline.stop_timeline()
        assert timeline.get() is None


class TestGaussianProcess:
    def test_gp_fits_smooth_function(self):
        gp = autotune.GaussianProcessRegressor(length_scale=0.2)
        x = np.linspace(0, 1, 12)[:, None]
        y = np.sin(4 * x).ravel()
        gp.fit(x, y)
        mu, sigma = gp.predict(x)
        np.testing.assert_allclose(mu, y, atol=1e-2)
        assert np.all(sigma < 0.1)

    def test_gp_uncertainty_grows_off_data(self):
        gp = autotune.GaussianProcessRegressor(length_scale=0.1)
        gp.fit(np.array([[0.0], [0.1]]), np.array([1.0, 1.1]))
        _, s_near = gp.predict(np.array([[0.05]]))
        _, s_far = gp.predict(np.array([[0.9]]))
        assert s_far > s_near


class TestBayesianOptimization:
    def test_finds_maximum(self):
        bo = autotune.BayesianOptimization(bounds=[(0.0, 7.0)], seed=1)
        f = lambda k: -((k - 4.2) ** 2)  # max at 4.2
        for _ in range(20):
            x = bo.suggest()
            bo.register(x, f(float(x[0])))
        best = bo.xs[int(np.argmax(bo.ys))]
        best_knob = bo._denormalize(best)[0]
        assert abs(best_knob - 4.2) < 1.0


class TestAutotuner:
    def test_joint_bo_converges_and_freezes(self, tmp_path):
        log = str(tmp_path / "autotune.csv")
        at = autotune.Autotuner(
            warmup_samples=1, steps_per_sample=2, log_path=log, categoricals=[]
        )
        # Synthetic world: throughput peaks at 16MB threshold (knob=4) AND
        # cycle time 2ms — a separable joint optimum the 2-D BO must find.
        def world(threshold, cycle_ms):
            knob = np.log2(threshold / (1024 * 1024))
            return 1e9 * np.exp(-((knob - 4.0) ** 2) / 2) * np.exp(
                -((cycle_ms - 2.0) ** 2) / 8
            )

        for _ in range(100):
            if not at.active:
                break
            score = world(at.fusion_threshold, at.cycle_time_ms)
            # record() wants bytes and seconds; steps_per_sample=2
            at.record(score, 1.0)
            at.record(score, 1.0)
        assert not at.active
        final_knob = np.log2(at.fusion_threshold / (1024 * 1024))
        assert abs(final_knob - 4.0) < 2.0
        assert 0.5 <= at.cycle_time_ms <= 10.0
        with open(log) as f:
            assert len(f.readlines()) > 3

    def test_categorical_chain_picks_best(self):
        at = autotune.Autotuner(
            warmup_samples=0,
            steps_per_sample=1,
            sync_scores=False,
            categoricals=[
                autotune.CategoricalParam("cache_capacity", [1024, 0]),
                autotune.CategoricalParam("hierarchical_allreduce",
                                          [False, True]),
            ],
        )
        # World: cache off is 2x better; hierarchical on is 1.5x better.
        def world(s):
            v = 1e9
            if s["cache_capacity"] == 0:
                v *= 2
            if s["hierarchical_allreduce"]:
                v *= 1.5
            return v

        for _ in range(50):
            if at._phase == "bo" or not at.active:
                break
            at.record(world(at.settings), 1.0)
        assert at.settings["cache_capacity"] == 0
        assert at.settings["hierarchical_allreduce"] is True

    def test_hierarchical_flags_applied_to_env(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_HIERARCHICAL_ALLREDUCE", raising=False)
        at = autotune.Autotuner(categoricals=[])
        at._apply({"hierarchical_allreduce": True})
        assert os.environ["HOROVOD_HIERARCHICAL_ALLREDUCE"] == "1"
        at._apply({"hierarchical_allreduce": False})
        assert os.environ["HOROVOD_HIERARCHICAL_ALLREDUCE"] == "0"
        monkeypatch.delenv("HOROVOD_HIERARCHICAL_ALLREDUCE", raising=False)

    def test_lockstep_determinism(self, monkeypatch):
        """Two tuners fed identical (synced) scores propose identical
        settings at every sample — the cross-rank agreement invariant."""
        # The default categorical chain writes the hierarchical env flags;
        # register the keys with monkeypatch so teardown restores them.
        monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "0")
        monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLGATHER", "0")
        mk = lambda: autotune.Autotuner(
            warmup_samples=1, steps_per_sample=1, sync_scores=False
        )
        a, b = mk(), mk()
        rng = np.random.RandomState(7)
        for _ in range(25):
            if not a.active:
                break
            score = float(rng.rand() * 1e9)
            a.record(score, 1.0)
            b.record(score, 1.0)
            assert a.settings == b.settings
        assert a.settings == b.settings

    def test_tuned_threshold_feeds_ingraph_fusion(self, hvd, monkeypatch):
        from horovod_tpu import basics
        from horovod_tpu.ops import fusion

        at = autotune.Autotuner(categoricals=[])
        at._apply({"fusion_threshold": 12345678})
        monkeypatch.setattr(basics._ctx(), "autotuner", at, raising=False)
        assert fusion.fusion_threshold_bytes() == 12345678

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "5")
        at = autotune.Autotuner.from_env()
        assert at.warmup_samples == 5

    def test_synchronize(self):
        at = autotune.Autotuner()
        at.synchronize()  # single process: no-op
        assert at.fusion_threshold == 64 * 1024 * 1024
